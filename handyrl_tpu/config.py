"""Config loading with a defaults layer and validation.

The reference reads config.yaml into a raw dict with no defaults or checks
(main.py:9-10); here every knob has a documented default and unknown keys are
reported, so partial configs work.
"""

from __future__ import annotations

import copy
from typing import Any, Dict

import yaml

TRAIN_DEFAULTS: Dict[str, Any] = {
    'turn_based_training': True,
    'observation': False,
    'gamma': 0.8,
    'forward_steps': 16,
    'burn_in_steps': 0,
    'compress_steps': 4,
    'compress_level': 9,          # bz2 compresslevel for episode moments (1 fastest .. 9 smallest); engine-mode workers are compression-dominated, so actor-starved hosts can trade upload bytes for episodes/sec
    'entropy_regularization': 1.0e-1,
    'entropy_regularization_decay': 0.1,
    'update_episodes': 200,
    'batch_size': 128,
    'minimum_episodes': 400,
    'maximum_episodes': 100000,
    'epochs': -1,
    'num_batchers': 2,
    'eval_rate': 0.1,
    'worker': {'num_parallel': 6},
    'lambda': 0.7,
    'policy_target': 'TD',        # 'UPGO' 'VTRACE' 'TD' 'MC'
    'value_target': 'TD',         # 'VTRACE' 'TD' 'MC'
    'eval': {'opponent': ['random']},
    'seed': 0,
    'restart_epoch': 0,           # resume from models/<n>.ckpt; -1 = auto-resume from the newest checkpoint that passes integrity verification (0 when none exists)
    'init_params': '',            # warm-start: load model params (a .ckpt snapshot of the SAME architecture) at epoch 0, fresh optimizer/episode counters — for measurement runs that need a late-stage policy (e.g. the replay-weighting A/B's long-episode regime)
    # --- TPU-native extensions (absent in the reference) ---
    'batched_generation': True,   # in-process vectorized self-play actors
    'generation_envs': 64,        # env count per batched actor
    'eval_envs': None,            # concurrent online-eval matches; None = max(4, generation_envs // 8)
    'device_chunk_steps': 16,     # plies per device-generation program dispatch
    'device_eval': True,          # device-resident eval matches when device_generation is on and the opponent is 'random'
    'device_ingest': True,        # assemble training windows on device (device_generation + device_replay, single-device)
    'device_generation': False,   # fully device-resident rollouts (envs with a pure-JAX twin)
    'device_replay': False,       # HBM-resident replay ring; batches sampled on device
    'replay_windows_per_episode': None,  # windows ingested per episode (uniformly placed); sets both the ring budget and the sampling WEIGHTING — 1 = exact per-episode mass like the reference's draw (train.py:291-306), >1 weights long episodes by min(len//fs, W). None = max(1, 64 // forward_steps)
    'replay_fused_steps': 8,      # SGD steps fused into one device program in device_replay mode
    'max_sample_reuse': None,     # device_replay threaded trainer: cap samples-drawn / windows-ingested (None = free-spin like the reference)
    'fused_pipeline': True,       # one dispatch = rollout chunk + ingest + K SGD steps (device_ingest configs)
    'sgd_steps_per_chunk': None,  # fused-pipeline SGD steps per rollout chunk (pins the replay ratio); None = 16
    'checkpoint_interval': 1,     # fused loop: write model/trainer ckpt files every N epochs (params still refresh on device every epoch; a final flush always lands on shutdown)
    'model_dir': 'models',        # checkpoint directory
    'metrics_jsonl': '',          # optional structured metrics path
    'distributed': {},            # multi-host learner: coordinator_address / num_processes / process_id

    # mesh partitioning (parallel/partition.py, docs/large_scale_training.md
    # "Mesh-sharded training"): the learner's compiled steps take explicit
    # NamedShardings over the ('data', 'model') mesh; regex partition rules
    # map the param/optimizer pytree to replicate-vs-sharded specs
    'parallel': {
        'model_parallel': 1,      # width of the mesh's 'model' axis (tensor parallelism); devices/model_parallel becomes the 'data' axis the batch shards over
        'partition_rules': [],    # [[regex, spec], ...] over '/'-joined param/optimizer paths, first match wins; spec = null/[] replicate, 'data'/'model' shard dim 0, or a per-dim axis list like [null, 'model']. [] = replicate everything (pure data parallelism); a trailing catch-all replicate rule is implied
    },

    # distributed-fleet fault tolerance (docs/large_scale_training.md):
    # heartbeats, silent-peer detach, supervised reconnect, task re-issue
    'fault_tolerance': {
        'heartbeat_interval': 10.0,    # gather -> server liveness beacon period (s)
        'liveness_timeout': 60.0,      # detach a silent socket peer after (s); must exceed heartbeat_interval
        'rpc_timeout': 120.0,          # gather-side blocking RPC deadline (s); a dead server fails the call instead of hanging it
        'task_deadline': 300.0,        # re-issue an assigned generation/eval task not returned within (s)
        'reconnect_initial_delay': 1.0,  # first reconnect backoff step (s); doubles per failure, jittered
        'reconnect_max_delay': 30.0,   # backoff ceiling (s)
        'reconnect_max_tries': 30,     # redials before a gather gives up (and respawns before a gather slot is abandoned)
        'resend_buffer': 256,          # max unacked uploads a gather retains across reconnects; older ones are dropped + counted

        # elastic fleet control (fault.FleetController, train.py server()):
        # per-host health states derived from ledger strandings + heartbeat
        # fault telemetry; flapping hosts are drained then quarantined
        # (fresh tasks withheld) and re-admitted after the quarantine
        'host_degrade_after': 1,       # fault signals (strandings or engine failovers/restarts) within host_health_window before a host is marked degraded
        'host_quarantine_after': 3,    # strandings within the window before the host is drained (no fresh tasks) and then quarantined
        'host_health_window': 120.0,   # sliding window (s) for per-host fault accounting
        'host_quarantine_period': 60.0,  # quarantine length (s) before a flapping host is re-admitted with a cleared fault history
    },

    # learner-side crash/corruption resilience (guard.py,
    # docs/large_scale_training.md "Preemption and recovery")
    'guard': {
        'nonfinite_policy': 'rollback',  # non-finite update handling: 'skip' (drop + count), 'rollback' (skip, then restore the last good checkpoint after rollback_after consecutive bad updates or a loss-spike trip), 'abort' (fail the run)
        'rollback_after': 8,           # consecutive non-finite updates before an in-place rollback
        'loss_spike_zscore': 0.0,      # >0: also roll back when the (finite) loss deviates this many EMA stddevs from its running mean; 0 disables
        'check_episodes': True,        # drop (and count) incoming episodes whose decoded observations/rewards contain non-finite values before they reach the buffer
        'preempt_signals': True,       # SIGTERM/SIGINT: flush a full checkpoint at the next safe point and exit 75 (supervisor contract: restart into restart_epoch -1)
    },
    'keep_checkpoints': 0,        # GC numbered models/<epoch>.ckpt beyond the newest N after each save (0 = keep all; league-opponent checkpoint paths are never deleted)

    # durable training plane (spool.py EpisodeSpool + fault.LedgerJournal,
    # docs/large_scale_training.md "Zero-loss training plane"): a SIGKILLed
    # remote learner restarts with zero admitted episodes lost — episodes
    # WAL to a segmented spool before they are counted, the task ledger's
    # outstanding book persists snapshot+delta, and surviving gathers
    # reattach through the resume-token handshake instead of respawning
    'durability': {
        'spool': True,            # WAL every admitted episode under model_dir/spool/ before feed_episodes counts it (remote learners only; a restart replays records past the newest checkpoint's consumption horizon back into the buffer)
        'segment_mb': 64,         # spool segment rotation size (MB); only the live segment can hold a torn tail
        'keep_segments': 2,       # closed segments retained past the GC horizon as cushion (GC runs at each epoch sync; disk stays ~= (keep_segments + 1) * segment_mb + live)
        'ledger_snapshot': True,  # persist the TaskLedger book (ledger.snap at each epoch + ledger.delta.wal between), so a restarted learner re-issues stranded tasks with their ORIGINAL sample_keys
    },

    # streaming partial-episode ingest (streaming.py ChunkAssembler,
    # docs/large_scale_training.md "Streaming ingest"): workers flush
    # fixed-T window chunks of in-flight episodes through the upload path
    # instead of holding completed episodes, so long games stop adding
    # full-episode latency to policy lag. Default off; off is byte-identical
    # to the whole-episode path. Chunk boundaries are a pure function of
    # (seed, sample_key, chunk_steps), so re-issued attempts regenerate
    # identical chunks and the assembler's duplicate screen merges them.
    'streaming': {
        'enabled': False,         # flush in-flight episodes as fixed-T chunks (remote 'g' tasks); the final chunk carries the outcome
        'chunk_steps': 32,        # plies per flushed chunk (T); must be a multiple of compress_steps so chunk-local bz2 blocks land on the whole-episode block grid
        'staleness_half_life': 0.0,  # seconds after which a sampled chunk's selection weight halves (per-chunk recv age); 0 = no staleness-aware reselection (selection byte-identical to whole-episode draws)
        'max_reselect': 4,        # bounded re-draws before a stale window is accepted regardless (keeps selection O(1) under backlog)
        'target_clip': 0.0,       # IMPACT-style clipped target network: V-trace rhos computed against a lagged target policy, clipped at this ceiling; 0 = off (independent of streaming.enabled)
        'target_sync_epochs': 1,  # epochs between target-network refreshes from the live params (target_clip > 0)
    },

    # per-host batched inference service for the distributed actor fleet
    # (inference.py, docs/large_scale_training.md "Actor inference service"):
    # workers become pure env-steppers; one engine per host coalesces their
    # act/plan requests into batched forward passes
    'inference': {
        'enabled': False,        # route worker inference through the host engine
        'batch_wait_ms': 2.0,    # coalescing deadline: how long the engine holds the oldest request while the batch fills (it dispatches early once every local worker has a request in flight)
        'max_batch': 64,         # request cap per dispatched forward batch
        'engine_backend': 'cpu',  # 'cpu' pins the engine to host cores; 'device' lets the engine claim a worker-host-local accelerator (never set on hosts sharing the learner's chip)
        'vault_size': 3,         # materialized model snapshots cached (engine-side in engine mode, per worker otherwise — including a degraded worker's local fallback vault)

        # self-healing tier (inference.EngineSupervisor / EngineClient,
        # docs/large_scale_training.md "Engine failover and elastic fleet")
        'queue_max': 1024,       # bounded engine intake queue: submits past it are shed with an immediate error reply (backpressure instead of unbounded growth); 0 = unbounded
        'stall_timeout': 30.0,   # watchdog: a busy engine with no tick progress for this long is declared stalled, its requests error-answered, and a fresh engine started
        'restart_max_delay': 10.0,  # supervised engine-restart backoff ceiling (s); first restart after 0.5s, doubling
        'request_timeout': 10.0,  # worker-side deadline (s) on one engine round trip
        'request_retries': 1,    # resends after a timeout before the worker gives up on the engine for that request
        'failover': True,        # degrade to the per-worker inference path when the engine is unreachable (lossless: records stay byte-identical); False = raise, losing that episode
        'reprobe_initial_delay': 2.0,  # circuit breaker: first half-open probe delay (s) after a degradation, doubling up to reprobe_max_delay
        'reprobe_max_delay': 30.0,     # probe backoff ceiling (s)
    },

    # standalone model-serving tier (serving/, docs/serving.md): a
    # long-lived InferenceService process hosting registry-versioned models
    # behind the framed INFER protocol, plus the learner's
    # publish-to-registry hook and the workers' remote-engine endpoint
    'serving': {
        'port': 9997,            # service listen port (main.py --serve); 0 = ephemeral (reported on the ready line)
        'host': '',              # service bind host ('' = all interfaces)
        'endpoint': '',          # 'host:port' of a remote InferenceService (or a comma-separated list of replica endpoints); engine-mode workers dial it instead of the in-Gather engine (same deadlines/retries/circuit-breaker; with several endpoints a dead replica fails over to the next, and only when ALL are down does the worker degrade to the local path byte-identically)
        'line': 'default',       # model line used by the learner's publish hook and for resolving bare-integer request ids ('<line>@<mid>')
        'registry_dir': '',      # ModelRegistry root (registry.json + owned version files); '' = model_dir
        'publish': False,        # learner: register every numbered checkpoint with the registry as '<line>@<epoch>' (pinning it against keep_checkpoints GC)
        'auto_promote': True,    # with publish: each published version also becomes the line's champion (one atomic manifest swap); False = candidates only, promote by hand
        'engines': 1,            # InferenceEngine fleets inside one service process; models partition across them by handle
        'max_clients': 64,       # admission control: connections past this are refused with an error frame (serve_shed_total) instead of queueing unboundedly
        'drain_timeout': 30.0,   # graceful-drain deadline (s) on SIGTERM: every accepted request is answered before exit 75 (the PreemptionGuard supervisor contract)
        'metrics_port': 0,       # service-side Prometheus /metrics port (0 = exporter off)
        'lock_timeout': 10.0,    # registry manifest-lock deadline (s): a mutation that cannot take the cross-process flock within it raises RegistryLockTimeout (counted registry_lock_timeouts_total) instead of hanging on a wedged peer

        # serving fleet (serving/fleet.py, docs/serving.md "Serving fleet"):
        # a ServiceResolver fronting N InferenceService replicas — replicas
        # register + heartbeat SLO snapshots, clients route through the
        # resolver with per-replica circuit breakers, and an optional
        # autoscaler admits/drains replicas off the p99/shed SLO
        'fleet': {
            'resolver': '',              # 'host:port' of the ServiceResolver a replica registers with (and heartbeats to); '' = standalone service, no fleet membership
            'port': 0,                   # resolver listen port (main.py --serve-fleet); 0 = ephemeral (reported on the fleet_ready line)
            'replica': '',               # this replica's stable name; '' = resolver-assigned. A respawned replica re-registering under its old name is re-admitted immediately (the healthy round trip)
            'advertise': '',             # endpoint host advertised to the resolver ('' = the bind host, or 127.0.0.1 when binding all interfaces)
            'heartbeat_interval': 2.0,   # replica -> resolver liveness + SLO beacon period (s)
            'heartbeat_timeout': 10.0,   # resolver quarantines a replica silent for this long (s); must exceed heartbeat_interval
            'refresh_interval': 2.0,     # router-side replica-table refresh period (s); failures also force a refresh
            'replicas': 2,               # replicas the resolver spawns and supervises under --serve-fleet (0 = externally-managed replicas only)
            'min_replicas': 1,           # autoscaler floor: idle-drain never shrinks the healthy fleet below this
            'max_replicas': 4,           # autoscaler ceiling: SLO-breach admission never grows past this
            'autoscale': False,          # consume the heartbeat SLO snapshots: sustained p99/shed breach admits a standby replica, sustained idleness drains one through the SIGTERM graceful-drain contract
            'slo_p99_ms': 0.0,           # autoscaler p99 latency breach threshold (ms); 0 = breach only on request sheds
            'breach_window': 10.0,       # SLO breach must persist this long (s) before a replica is admitted
            'idle_window': 60.0,         # fleet must be fully idle this long (s) before a replica is drained
            'quarantine_period': 30.0,   # quarantine length (s) before a silent replica is speculatively re-admitted (a re-registration re-admits it immediately)
            'metrics_port': 0,           # resolver-side Prometheus /metrics + /statusz port (0 = exporter off); the fleet's alert engine and replica-state view live here
        },

        # match gateway (serving/gateway.py, docs/serving.md "Match
        # gateway"): the sessionful tier over the fleet — clients open
        # matches, the gateway hosts the env, steps opponent seats through
        # the replicas, and survives replica loss by hidden-state handoff
        # (drain) or byte-identical journal reconstruction (SIGKILL)
        'gateway': {
            'port': 0,               # gateway listen port (main.py --gateway); 0 = ephemeral (reported on the gateway_ready line)
            'resolver': '',          # 'host:port' of the fleet resolver the gateway routes plies through; '' = serving.fleet.resolver
            'model': 'default@champion',  # opponent spec a session opens against when the client names none; floating selectors are pinned to a concrete line@version at open, so a mid-match promote never forks the opponent
            'workers': 4,            # session worker threads; each owns its own RoutedClient, so concurrent sessions' plies coalesce into the engine batch without sharing a submitter
            'max_sessions': 64,      # admission control: opens past this are shed with an error reply (gateway_shed_total) — opens are shed, plies never are
            'ply_timeout': 15.0,     # per-ply fleet round-trip deadline (s); also bounds reconstruction replays
            'monitor_interval': 0.5, # fleet-table poll period (s) for the handoff/reconstruct monitor (and the worker routers' refresh interval)
            'session_timeout': 600.0,  # idle sessions (no ply this long, s) are reaped as drops — an abandoned match must not pin fleet affinity forever
            'metrics_port': 0,       # gateway-side Prometheus /metrics port (0 = exporter off)
        },
    },

    # league training (league.py, docs/league.md): PFSP opponent sampling
    # over registry versions + anchors, persistent Elo ratings, and a
    # rating-gated champion promotion replacing recency auto_promote
    'league': {
        'enabled': False,        # worker-fleet 'g' tasks seat PFSP-sampled pool opponents and an 'e' slice becomes rating matches; requires serving.publish (the pool is the registry line). False = mirror self-play, records byte-identical to pre-league behavior
        'line': '',              # registry line the pool draws members from; '' = serving.line
        'anchors': ['random'],   # built-in pool members needing no checkpoint: 'random' (uniform legal play, usable in 'g' and 'e') and 'rulebase'/'rulebase-<key>' (env rule_based_action; 'e' rating matches only)
        'curve': 'variance',     # PFSP weighting over the learner's per-member win rate p: 'variance' (p*(1-p), prefers even matchups), 'hard' ((1-p)^hard_exponent, prefers members the learner loses to), 'uniform'
        'hard_exponent': 2.0,    # exponent k of the 'hard' curve's (1-p)^k weighting
        'self_play_rate': 0.5,   # fraction of 'g' tasks kept as mirror self-play against the current epoch; the rest seat a PFSP-drawn pool member (deterministic per (seed, sample_key))
        'rating_match_rate': 0.25,  # fraction of 'e' tasks turned into rating matches against a round-robin pool member (the rest keep the configured eval.opponent rotation)
        'max_members': 8,        # newest registry versions kept in the member window (champion + rollback target always included); bounds the GC-pinned set
        'initial_rating': 1200.0,  # Elo rating every member (and the learner) starts from
        'k_factor': 32.0,        # Elo K: max rating delta per game (scaled down by sigma/initial_sigma when track_sigma is on)
        'track_sigma': True,     # TrueSkill-lite: per-member sigma shrinks with games played and scales the effective K, so established ratings move slowly and fresh members converge fast
        'initial_sigma': 200.0,  # starting rating uncertainty under track_sigma
        'min_sigma': 50.0,       # sigma floor under track_sigma (effective K never collapses to 0)
        'promote_margin': 30.0,  # rating-gated promotion: the learner must clear the incumbent champion member's rating by this many Elo points
        'min_games': 20,         # rated games the learner must book since the last champion flip before promotion is considered
        'rating_flush_seconds': 5.0,  # write the rating journal through after an outcome lands, at most this often (s) — a hard-killed learner loses at most this window of ratings instead of a whole epoch; 0 = epoch-sync flushes only
    },

    # fleet generation backend (worker.py gather_loop + DeviceActorGather,
    # device_generation.py DeviceActorEngine, docs/large_scale_training.md
    # "Device actor backend"): how a gather host turns its assigned ledger
    # tasks into episode records
    'generation': {
        'backend': '',            # '' = auto (engine when inference.enabled, else worker); 'worker' = per-worker stepping, 'engine' = host-batched inference, 'device' = the fused Anakin scan (envs with a pure-JAX twin); a gather host overrides it with worker_args.backend
        'device_actor_envs': 64,  # parallel envs inside the device actor's compiled scan — one ledger task per env lane
        'device_actor_chunk_steps': 16,  # plies per compiled chunk dispatch; the scan fill-ratio gauge watches lanes idled by finished episodes
        'device_actor_slots': 2,  # stacked opponent-param slots traced into the ONE compiled program (slot 0 = learner params); league pairings beyond this defer to a later block instead of retracing
        'device_actor_record': '',  # '' = auto per the env twin's RNG_COMPAT contract; 'strict' = replay sampling host-side for byte-compatible records; 'device' = faster device-sampled records, record_version-stamped
    },

    # unified telemetry (docs/observability.md): metric registry + spans +
    # heartbeat-piggybacked fleet aggregation + optional Prometheus endpoint
    # + episode-lifecycle distributed tracing. Accepts a bool (legacy
    # collection switch) or a block:
    #   telemetry: {enabled: true, trace_dir: traces/, trace_sample_rate: 0.1}
    # trace_dir (or HANDYRL_TPU_TRACE=<dir>, which wins) turns on Chrome-
    # trace span export across every fleet process; trace_sample_rate keeps
    # a deterministic fraction of episodes so overhead stays bounded.
    'telemetry': True,            # collect metrics (near-zero cost off; also HANDYRL_TPU_TELEMETRY=0)
    'telemetry_port': 0,          # serve Prometheus text format on this port (0 = exporter off; a busy port retries then falls back to an ephemeral one, logged)
    'profile_epochs': '',         # epochs to wrap in a jax.profiler device trace ('3', '2,5', '3-5'); written to <trace_dir|model_dir>/profile unless profile_dir is set

    'batcher_processes': False,   # build batches in spawned CPU processes instead of threads
    'decode_cache_blocks': 1024,  # LRU capacity (bz2 blocks) of the batchers' decoded-moment cache; recency-biased selection re-decodes the same blocks every batch without it. 0 disables; memory cost ~= blocks * compress_steps * per-moment bytes
    'batcher_shared_memory': False,  # with batcher_processes: children assemble batches in shared-memory arenas and the trainer maps them zero-copy (no pickle over the pipe); slots recycle after the staged device upload completes
    'prefetch_depth': 2,          # device staging ring depth: batches held as in-flight host->device uploads ahead of the compiled update step (1 = single-slot overlap, the pre-ring behavior)
    'compute_dtype': '',          # '' = float32; 'bfloat16' for MXU-friendly activations
    'profile_dir': '',            # when set, capture a jax profiler trace early in training
}

WORKER_DEFAULTS: Dict[str, Any] = {
    'server_address': '',
    'num_parallel': 8,
    'backend': '',   # per-host generation-backend override ('' = follow generation.backend): a host that owns an accelerator sets 'device' while the rest of the fleet keeps the worker/engine path
}


def parse_epoch_set(spec) -> set:
    """Parse the ``profile_epochs`` knob: an int, a list of ints, or a
    comma-separated string accepting ranges ('3', '2,5', '3-5,8')."""
    if not spec:
        return set()
    if isinstance(spec, int):
        return {int(spec)}
    if isinstance(spec, (list, tuple)):
        return {int(x) for x in spec}
    out: set = set()
    for part in str(spec).split(','):
        part = part.strip()
        if not part:
            continue
        if '-' in part and not part.startswith('-'):
            lo, _, hi = part.partition('-')
            out.update(range(int(lo), int(hi) + 1))
        else:
            out.add(int(part))
    return out


def _merge(defaults: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    out = copy.deepcopy(defaults)
    for k, v in (overrides or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


def load_config(path: str = 'config.yaml') -> Dict[str, Any]:
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    return apply_defaults(raw)


def apply_defaults(raw: Dict[str, Any]) -> Dict[str, Any]:
    args = {
        'env_args': raw.get('env_args', {'env': 'TicTacToe'}),
        'train_args': _merge(TRAIN_DEFAULTS, raw.get('train_args', {})),
        'worker_args': _merge(WORKER_DEFAULTS, raw.get('worker_args', {})),
    }
    validate(args)
    return args


def validate(args: Dict[str, Any]) -> None:
    ta = args['train_args']
    # Both estimators dispatch through the same compute_target
    # (ops/targets.py), exactly as the reference's losses.py:63 does for
    # policy AND value — so all four algorithms are legal for either knob.
    _TARGETS = ('MC', 'TD', 'VTRACE', 'UPGO')
    assert ta['policy_target'] in _TARGETS, ta['policy_target']
    assert ta['value_target'] in _TARGETS, ta['value_target']
    assert ta['forward_steps'] >= 1
    assert ta['burn_in_steps'] >= 0
    assert ta['compress_steps'] >= 1
    assert 0.0 <= ta['eval_rate'] <= 1.0
    assert ta['batch_size'] >= 1
    if ta.get('max_sample_reuse') is not None:
        assert float(ta['max_sample_reuse']) > 0, \
            'max_sample_reuse must be > 0 (unset it to free-spin)'
    if ta.get('prefetch_depth') is not None:
        assert int(ta['prefetch_depth']) >= 1, \
            'prefetch_depth must be >= 1 (or null for the default)'
    ft = ta.get('fault_tolerance') or {}
    for key in ('heartbeat_interval', 'liveness_timeout', 'rpc_timeout',
                'task_deadline', 'reconnect_initial_delay',
                'reconnect_max_delay', 'reconnect_max_tries',
                'resend_buffer', 'host_degrade_after',
                'host_quarantine_after', 'host_health_window',
                'host_quarantine_period'):
        if ft.get(key) is not None:
            assert float(ft[key]) > 0, \
                'fault_tolerance.%s must be > 0' % key
    if ft.get('liveness_timeout') and ft.get('heartbeat_interval'):
        assert float(ft['liveness_timeout']) > float(ft['heartbeat_interval']), \
            'liveness_timeout must exceed heartbeat_interval or every ' \
            'healthy peer is detached between beacons'
    assert int(ta.get('restart_epoch') or 0) >= -1, \
        'restart_epoch must be >= -1 (-1 = auto-resume from the newest ' \
        'valid checkpoint)'
    assert int(ta.get('keep_checkpoints') or 0) >= 0, \
        'keep_checkpoints must be >= 0 (0 keeps every checkpoint)'
    dur = ta.get('durability') or {}
    assert isinstance(dur, dict), \
        'durability must be a block (spool / segment_mb / keep_segments / ' \
        'ledger_snapshot)'
    assert float(dur.get('segment_mb', 64)) > 0, \
        'durability.segment_mb must be > 0'
    assert int(dur.get('keep_segments', 2)) >= 0, \
        'durability.keep_segments must be >= 0 (0 = GC every closed ' \
        'segment past the horizon)'
    stm = ta.get('streaming') or {}
    assert isinstance(stm, dict), \
        'streaming must be a block (enabled / chunk_steps / ' \
        'staleness_half_life / max_reselect / target_clip / ' \
        'target_sync_epochs)'
    assert int(stm.get('chunk_steps', 32)) >= 1, \
        'streaming.chunk_steps must be >= 1'
    assert int(stm.get('chunk_steps', 32)) % int(ta['compress_steps']) == 0, \
        'streaming.chunk_steps must be a multiple of compress_steps so ' \
        'chunk-local bz2 blocks align with the whole-episode block grid ' \
        '(byte-identical reassembly)'
    assert float(stm.get('staleness_half_life', 0.0)) >= 0, \
        'streaming.staleness_half_life must be >= 0 (0 = off)'
    assert int(stm.get('max_reselect', 4)) >= 1, \
        'streaming.max_reselect must be >= 1'
    assert float(stm.get('target_clip', 0.0)) >= 0, \
        'streaming.target_clip must be >= 0 (0 = no target network)'
    assert int(stm.get('target_sync_epochs', 1)) >= 1, \
        'streaming.target_sync_epochs must be >= 1'
    g = ta.get('guard') or {}
    assert str(g.get('nonfinite_policy', 'rollback')) in \
        ('skip', 'rollback', 'abort'), \
        "guard.nonfinite_policy must be 'skip', 'rollback' or 'abort'"
    assert int(g.get('rollback_after', 8)) >= 1, \
        'guard.rollback_after must be >= 1'
    assert float(g.get('loss_spike_zscore', 0.0)) >= 0, \
        'guard.loss_spike_zscore must be >= 0 (0 disables the trip)'
    tel = ta.get('telemetry', True)
    assert isinstance(tel, (bool, dict)), \
        'telemetry must be a bool or a block (enabled / trace_dir / ' \
        'trace_sample_rate / blackbox_dir / recorder_events / ' \
        'metrics_rotate_mb / alerts / perf_plane / retrace / ' \
        'retrace_warmup_epochs)'
    tel_enabled = bool(tel.get('enabled', True)) if isinstance(tel, dict) \
        else bool(tel)
    if isinstance(tel, dict):
        rate = float(tel.get('trace_sample_rate', 1.0))
        assert 0.0 <= rate <= 1.0, \
            'telemetry.trace_sample_rate must be a fraction in [0, 1]'
        assert int(tel.get('recorder_events', 256)) >= 16, \
            'telemetry.recorder_events must be >= 16 (the flight-recorder ' \
            'ring needs room for a useful postmortem tail)'
        assert float(tel.get('metrics_rotate_mb', 0)) >= 0, \
            'telemetry.metrics_rotate_mb must be >= 0 (0 disables rotation)'
        alerts = tel.get('alerts', {})
        assert isinstance(alerts, (bool, dict, list)), \
            'telemetry.alerts must be a block ({builtin, interval, rules}), ' \
            'a rule list, or False'
        if isinstance(alerts, dict) and alerts.get('interval') is not None:
            assert float(alerts['interval']) > 0, \
                'telemetry.alerts interval must be > 0 seconds'
        rules = alerts.get('rules') if isinstance(alerts, dict) else \
            (alerts if isinstance(alerts, list) else None)
        for rule in (rules or []):
            assert isinstance(rule, dict) and rule.get('name') \
                and rule.get('metric'), \
                'each telemetry.alerts rule needs at least name + metric'
        assert str(tel.get('retrace', 'warn')).lower() in \
            ('warn', 'abort', 'off'), \
            "telemetry.retrace must be 'warn', 'abort' or 'off'"
        assert int(tel.get('retrace_warmup_epochs', 1)) >= 0, \
            'telemetry.retrace_warmup_epochs must be >= 0'
    if ta.get('profile_epochs'):
        epochs = parse_epoch_set(ta['profile_epochs'])
        assert epochs and all(e >= 1 for e in epochs), \
            "profile_epochs must name epochs >= 1 ('3', '2,5', '3-5')"
    if ta.get('telemetry_port') is not None:
        port = int(ta['telemetry_port'])
        assert 0 <= port <= 65535, \
            'telemetry_port must be a TCP port (0 disables the exporter)'
        assert port == 0 or tel_enabled, \
            'telemetry_port needs telemetry enabled (the exporter serves ' \
            'the registry the collection switch turns off)'
    assert 1 <= int(ta.get('compress_level', 9)) <= 9, \
        'compress_level must be a bz2 compresslevel in 1..9'
    inf = ta.get('inference') or {}
    assert str(inf.get('engine_backend', 'cpu')) in ('cpu', 'device'), \
        "inference.engine_backend must be 'cpu' or 'device'"
    assert float(inf.get('batch_wait_ms', 2.0)) >= 0, \
        'inference.batch_wait_ms must be >= 0 (0 = dispatch immediately)'
    assert int(inf.get('max_batch', 64)) >= 1, \
        'inference.max_batch must be >= 1'
    assert int(inf.get('vault_size', 3)) >= 1, \
        'inference.vault_size must be >= 1'
    assert int(inf.get('queue_max', 1024)) >= 0, \
        'inference.queue_max must be >= 0 (0 = unbounded)'
    assert int(inf.get('request_retries', 1)) >= 0, \
        'inference.request_retries must be >= 0'
    for key in ('stall_timeout', 'restart_max_delay', 'request_timeout',
                'reprobe_initial_delay', 'reprobe_max_delay'):
        if inf.get(key) is not None:
            assert float(inf[key]) > 0, 'inference.%s must be > 0' % key
    srv = ta.get('serving') or {}
    for key in ('port', 'metrics_port'):
        if srv.get(key) is not None:
            port = int(srv[key])
            assert 0 <= port <= 65535, \
                'serving.%s must be a TCP port (0 = %s)' % (
                    key, 'ephemeral' if key == 'port' else 'exporter off')
    assert int(srv.get('engines', 1)) >= 1, \
        'serving.engines must be >= 1'
    assert int(srv.get('max_clients', 64)) >= 1, \
        'serving.max_clients must be >= 1'
    assert float(srv.get('drain_timeout', 30.0)) > 0, \
        'serving.drain_timeout must be > 0'
    assert str(srv.get('line', 'default')).strip(), \
        'serving.line must be a non-empty model-line name'
    endpoint = str(srv.get('endpoint') or '')
    for one in filter(None, (e.strip() for e in endpoint.split(','))):
        _ep_host, _, ep_port = one.rpartition(':')
        assert ep_port.isdigit() and 0 < int(ep_port) <= 65535, \
            "serving.endpoint entries must look like 'host:port' (got %r)" \
            % one
    assert float(srv.get('lock_timeout', 10.0)) > 0, \
        'serving.lock_timeout must be > 0'
    flt = srv.get('fleet') or {}
    for key in ('heartbeat_interval', 'heartbeat_timeout', 'refresh_interval',
                'breach_window', 'idle_window', 'quarantine_period'):
        if flt.get(key) is not None:
            assert float(flt[key]) > 0, 'serving.fleet.%s must be > 0' % key
    if flt.get('heartbeat_timeout') and flt.get('heartbeat_interval'):
        assert float(flt['heartbeat_timeout']) \
            > float(flt['heartbeat_interval']), \
            'serving.fleet.heartbeat_timeout must exceed heartbeat_interval ' \
            'or every live replica is quarantined between beacons'
    if flt.get('port') is not None:
        assert 0 <= int(flt['port']) <= 65535, \
            'serving.fleet.port must be a TCP port (0 = ephemeral)'
    assert int(flt.get('replicas', 2)) >= 0, \
        'serving.fleet.replicas must be >= 0 (0 = external replicas only)'
    assert int(flt.get('min_replicas', 1)) >= 1, \
        'serving.fleet.min_replicas must be >= 1'
    assert int(flt.get('max_replicas', 4)) >= int(flt.get('min_replicas', 1)), \
        'serving.fleet.max_replicas must be >= min_replicas'
    assert float(flt.get('slo_p99_ms', 0.0)) >= 0, \
        'serving.fleet.slo_p99_ms must be >= 0 (0 = breach on sheds only)'
    resolver = str(flt.get('resolver') or '')
    if resolver:
        _r_host, _, r_port = resolver.rpartition(':')
        assert r_port.isdigit() and 0 < int(r_port) <= 65535, \
            "serving.fleet.resolver must look like 'host:port' (got %r)" \
            % resolver
    gw = srv.get('gateway') or {}
    for key in ('port', 'metrics_port'):
        if gw.get(key) is not None:
            assert 0 <= int(gw[key]) <= 65535, \
                'serving.gateway.%s must be a TCP port (0 = %s)' % (
                    key, 'ephemeral' if key == 'port' else 'exporter off')
    assert int(gw.get('workers', 4)) >= 1, \
        'serving.gateway.workers must be >= 1'
    assert int(gw.get('max_sessions', 64)) >= 1, \
        'serving.gateway.max_sessions must be >= 1'
    for key in ('ply_timeout', 'monitor_interval', 'session_timeout'):
        if gw.get(key) is not None:
            assert float(gw[key]) > 0, \
                'serving.gateway.%s must be > 0' % key
    gw_resolver = str(gw.get('resolver') or '')
    if gw_resolver:
        _g_host, _, g_port = gw_resolver.rpartition(':')
        assert g_port.isdigit() and 0 < int(g_port) <= 65535, \
            "serving.gateway.resolver must look like 'host:port' (got %r)" \
            % gw_resolver
    lg = ta.get('league') or {}
    assert str(lg.get('curve', 'variance')) in \
        ('variance', 'hard', 'uniform'), \
        "league.curve must be 'variance', 'hard' or 'uniform'"
    assert float(lg.get('hard_exponent', 2.0)) > 0, \
        'league.hard_exponent must be > 0'
    assert 0.0 <= float(lg.get('self_play_rate', 0.5)) <= 1.0, \
        'league.self_play_rate must be a fraction in [0, 1]'
    assert 0.0 <= float(lg.get('rating_match_rate', 0.25)) <= 1.0, \
        'league.rating_match_rate must be a fraction in [0, 1]'
    assert int(lg.get('max_members', 8)) >= 1, \
        'league.max_members must be >= 1'
    assert float(lg.get('k_factor', 32.0)) > 0, \
        'league.k_factor must be > 0'
    assert float(lg.get('promote_margin', 30.0)) >= 0, \
        'league.promote_margin must be >= 0'
    assert int(lg.get('min_games', 20)) >= 1, \
        'league.min_games must be >= 1'
    assert float(lg.get('initial_sigma', 200.0)) \
        >= float(lg.get('min_sigma', 50.0)) > 0, \
        'league sigma bounds need initial_sigma >= min_sigma > 0'
    assert float(lg.get('rating_flush_seconds', 5.0)) >= 0, \
        'league.rating_flush_seconds must be >= 0 (0 = epoch-sync ' \
        'flushes only)'
    for anchor in (lg.get('anchors') or []):
        assert anchor == 'random' or str(anchor).startswith('rulebase'), \
            "league.anchors entries must be 'random' or 'rulebase[-key]' " \
            '(got %r)' % (anchor,)
    if lg.get('enabled'):
        assert srv.get('publish'), \
            'league.enabled requires serving.publish (pool members ARE the ' \
            "registry line's versions)"
    gen = ta.get('generation') or {}
    _BACKENDS = ('', 'worker', 'engine', 'device')
    assert str(gen.get('backend', '')) in _BACKENDS, \
        "generation.backend must be '', 'worker', 'engine' or 'device'"
    assert int(gen.get('device_actor_envs', 64)) >= 1, \
        'generation.device_actor_envs must be >= 1'
    assert int(gen.get('device_actor_chunk_steps', 16)) >= 1, \
        'generation.device_actor_chunk_steps must be >= 1'
    assert int(gen.get('device_actor_slots', 2)) >= 1, \
        'generation.device_actor_slots must be >= 1 (slot 0 carries the ' \
        'learner params)'
    assert str(gen.get('device_actor_record', '')) in \
        ('', 'strict', 'device'), \
        "generation.device_actor_record must be '', 'strict' or 'device'"
    assert str((args.get('worker_args') or {}).get('backend', '')) \
        in _BACKENDS, \
        "worker_args.backend must be '', 'worker', 'engine' or 'device'"
    par = ta.get('parallel') or {}
    assert int(par.get('model_parallel', 1)) >= 1, \
        'parallel.model_parallel must be >= 1 (1 = no tensor parallelism)'
    rules = par.get('partition_rules') or []
    assert isinstance(rules, (list, tuple)), \
        'parallel.partition_rules must be a list of [regex, spec] pairs'
    import re as _re
    for entry in rules:
        assert isinstance(entry, (list, tuple)) and len(entry) == 2, \
            'each partition rule is a [regex, spec] pair, got %r' % (entry,)
        pattern, spec = entry
        _re.compile(str(pattern))   # raises on an invalid regex
        axes = [spec] if isinstance(spec, str) or spec is None else list(spec)
        for axis in axes:
            assert axis in (None, 'null', '', 'data', 'model'), \
                "partition-rule axes must be null, 'data' or 'model' " \
                '(got %r in %r)' % (axis, entry)
    if ta.get('batcher_shared_memory'):
        assert ta.get('batcher_processes'), \
            'batcher_shared_memory requires batcher_processes (the thread ' \
            'batcher already shares the trainer address space)'
    assert 'env' in args['env_args'], 'env_args.env is required'
