"""Evaluation: online evaluator role, offline tournaments, network battles.

Round-2 redesign of the evaluation stack. Feature parity with the reference
(evaluation.py:83-285): shared-env matches, delta-synced remote matches over
the diff_info protocol, a multiprocess tournament with first/second seat
balancing for 2-player games, and the TCP battle mode on port 9876. The
construction differs:

* one match engine (:func:`run_match`) drives every match; the difference
  between a local agent and a remote client is a *seat* adapter
  (:class:`_AgentSeat` / :class:`_WireSeat`), not a second engine;
* the offline harness is a :class:`Tournament` object with explicit
  schedule / launch / collect / report phases instead of one long function;
* model files are our msgpack checkpoints (see train.py) — loading one
  cannot execute code, unlike unpickling a torch module — and all network
  traffic rides the data-only msgpack codec (connection.py).

stdout formats (``total games``, ``---agent N---``, win-rate lines) are kept
verbatim: the log format is the metrics interface the plot tooling parses.
"""

from __future__ import annotations

import multiprocessing as mp
import random
import time
from typing import Any, Dict, List, Optional

import numpy as np

from . import telemetry
from .agent import Agent, EnsembleAgent, RandomAgent, RuleBasedAgent, SoftAgent
from .connection import (accept_socket_connections, connect_socket_connection,
                         force_cpu_backend, send_recv)
from .environment import make_env, prepare_env

network_match_port = 9876

__all__ = [
    'Agent', 'EnsembleAgent', 'RandomAgent', 'RuleBasedAgent', 'SoftAgent',
    'NetworkAgent', 'NetworkAgentClient', 'Evaluator', 'ExportedModel',
    'run_match', 'exec_match', 'exec_network_match', 'evaluate_mp',
    'network_match_acception', 'wp_func', 'load_model', 'build_agent',
    'eval_main', 'eval_server_main', 'eval_client_main',
]


def view(env, player=None):
    if hasattr(env, 'view'):
        env.view(player=player)
    else:
        print(env)


def view_transition(env):
    if hasattr(env, 'view_transition'):
        env.view_transition()


# ---------------------------------------------------------------------------
# network battle protocol


class NetworkAgentClient:
    """Remote side of a battle: executes server commands against a local
    env + agent. Commands: update / action / observe / outcome / quit."""

    def __init__(self, agent, env, conn):
        self.conn = conn
        self.agent = agent
        self.env = env

    def run(self):
        while True:
            try:
                command, args = self.conn.recv()
            except (ConnectionResetError, EOFError, OSError):
                break
            if command == 'quit':
                break
            self.conn.send(self._execute(command, list(args)))

    def _execute(self, command: str, args: list):
        if command == 'outcome':
            print('outcome = %f' % args[0])
            return None
        if command in ('action', 'observe'):
            view(self.env)
            reply = getattr(self.agent, command)(self.env, *args, show=True)
            if command == 'action':
                reply = self.env.action2str(reply, args[0])
            return reply
        # env-state command (update etc.) mirrored onto the local env
        reply = getattr(self.env, command)(*args)
        if command == 'update':
            if args[1]:                        # reset flag: new game
                self.agent.reset(self.env, show=True)
            else:
                view_transition(self.env)
        return reply


class NetworkAgent:
    """Learner-side proxy for one remote NetworkAgentClient."""

    def __init__(self, conn):
        self.conn = conn

    def _call(self, command: str, *args):
        return send_recv(self.conn, (command, list(args)))

    def update(self, data, reset):
        return self._call('update', data, reset)

    def outcome(self, value):
        return self._call('outcome', value)

    def action(self, player):
        return self._call('action', player)

    def observe(self, player):
        return self._call('observe', player)


# ---------------------------------------------------------------------------
# match engine


class _AgentSeat:
    """A player slot occupied by an in-process agent on the shared env."""

    def __init__(self, agent):
        self.agent = agent

    def begin(self, env, player, show):
        self.agent.reset(env, show=show)

    def act(self, env, player, show):
        return self.agent.action(env, player, show=show)

    def watch(self, env, player, show):
        self.agent.observe(env, player, show=show)

    def sync(self, env, player):
        pass

    def finish(self, env, player, outcome):
        pass


class _WireSeat:
    """A player slot occupied by a remote client that mirrors the env from
    diff_info deltas and exchanges actions as strings."""

    def __init__(self, proxy: NetworkAgent):
        self.proxy = proxy

    def begin(self, env, player, show):
        self.proxy.update(env.diff_info(player), True)

    def act(self, env, player, show):
        return env.str2action(self.proxy.action(player), player)

    def watch(self, env, player, show):
        self.proxy.observe(player)

    def sync(self, env, player):
        self.proxy.update(env.diff_info(player), False)

    def finish(self, env, player, outcome):
        self.proxy.outcome(outcome)


def run_match(env, seats: Dict[int, Any], critic=None, show=False,
              game_args={}) -> Optional[dict]:
    """Play one game to completion; None on env failure."""
    if env.reset(game_args):
        return None
    for p, seat in seats.items():
        seat.begin(env, p, show)
    while not env.terminal():
        if show:
            view(env)
            if critic is not None:
                print('cv = ', critic.observe(env, None, show=False)[0])
        acting, watching = env.turns(), env.observers()
        moves = {}
        for p, seat in seats.items():
            if p in acting:
                moves[p] = seat.act(env, p, show)
            elif p in watching:
                seat.watch(env, p, show)
        if env.step(moves):
            return None
        if show:
            view_transition(env)
        for p, seat in seats.items():
            seat.sync(env, p)
    outcome = env.outcome()
    if show:
        print('final outcome = %s' % outcome)
    for p, seat in seats.items():
        seat.finish(env, p, outcome[p])
    return {'result': outcome}


def exec_match(env, agents: Dict[int, Any], critic=None, show=False,
               game_args={}) -> Optional[dict]:
    """Match between in-process agents on one shared environment."""
    return run_match(env, {p: _AgentSeat(a) for p, a in agents.items()},
                     critic, show, game_args)


def exec_network_match(env, network_agents: Dict[int, NetworkAgent],
                       critic=None, show=False, game_args={}
                       ) -> Optional[dict]:
    """Match against remote clients speaking the diff_info protocol."""
    return run_match(env,
                     {p: _WireSeat(a) for p, a in network_agents.items()},
                     critic, show, game_args)


# ---------------------------------------------------------------------------
# online evaluation (during training)


def build_agent(raw: str, env=None):
    if raw == 'random':
        return RandomAgent()
    if raw.startswith('rulebase'):
        key = raw.split('-')[1] if '-' in raw else None
        return RuleBasedAgent(key)
    return None


class Evaluator:
    """Online evaluation during training: the trained model vs a configured
    opponent pool (default 'random'). Opponent specs may be built-in agent
    names or model checkpoint paths; checkpoint opponents are loaded once
    and cached across matches."""

    def __init__(self, env, args):
        self.env = env
        self.args = args
        self.default_opponent = 'random'
        self._opponent_cache: Dict[str, Any] = {}

    def _opponent_agent(self, spec: str):
        agent = build_agent(spec, self.env)
        if agent is not None:
            return agent
        if spec not in self._opponent_cache:
            self._opponent_cache[spec] = Agent(load_model(spec, self.env))
        return self._opponent_cache[spec]

    def _draw_opponent(self, opponents, eval_args) -> str:
        """Pool draw keyed by the server-stamped ``sample_key`` through the
        audited seeded helper (graftlint GL001): which opponent an eval
        task meets is then a pure function of (seed, sample_key), identical
        across workers and ledger re-issues. Namespace 2 keeps the stream
        disjoint from generation's episode keys (0) and worker-local
        fallbacks (1)."""
        if not opponents:
            return self.default_opponent
        skey = (eval_args or {}).get('sample_key')
        if skey is None:
            # direct use without a server task (tests, ad-hoc eval): any
            # member of the pool is a valid opponent
            return opponents[random.randrange(len(opponents))]  # graftlint: allow[GL001] no sample_key outside server-stamped tasks; opponent identity is recorded in the result payload either way
        from .generation import sample_seed
        seq = sample_seed(self.args.get('seed', 0), (2, int(skey)), 0)
        idx = int(np.random.default_rng(seq).integers(len(opponents)))
        return opponents[idx]

    def execute(self, models: Dict[int, Any], eval_args) -> Optional[dict]:
        # a server-stamped opponent (league rating matches, train.py)
        # overrides the local pool draw: the task says exactly who to
        # meet. Registry-member opponents arrive as seated model_ids
        # (every seat's model is non-None, so the name is only the
        # result label); anchor names resolve below like any pool spec.
        opponent = (eval_args or {}).get('opponent')
        if not opponent:
            opponents = self.args.get('eval', {}).get('opponent', [])
            opponent = self._draw_opponent(opponents, eval_args)

        agents = {p: Agent(model) if model is not None
                  else self._opponent_agent(opponent)
                  for p, model in models.items()}

        with telemetry.trace_span(
                'evaluate', trace_id=telemetry.episode_trace_id(eval_args)):
            results = exec_match(self.env, agents)
        if results is None:
            print('None episode in evaluation!')
            return None
        return {'args': eval_args, 'opponent': opponent, **results}


# ---------------------------------------------------------------------------
# offline tournament


def wp_func(results: Dict[Optional[float], int]) -> float:
    games = sum(v for k, v in results.items() if k is not None)
    win = sum((k + 1) / 2 * v for k, v in results.items() if k is not None)
    return win / games if games else 0.0


def _tournament_child(agents, critic, env_args, index, job_queue,
                      result_queue, seed, show=False):
    """One match-runner process: drain jobs until the None sentinel."""
    force_cpu_backend()
    random.seed(seed + index)
    env = make_env({**env_args, 'id': index})
    remote_mode = isinstance(agents[0], NetworkAgent)
    while True:
        job = job_queue.get()
        if job is None:
            break
        serial, seat_ids, label, game_args = job
        print('*** Game %d ***' % serial)
        lineup = {env.players()[i]: agents[ai]
                  for i, ai in enumerate(seat_ids)}
        engine = exec_network_match if remote_mode else exec_match
        outcome = engine(env, lineup, critic, show=show, game_args=game_args)
        result_queue.put((label, seat_ids, outcome))
    result_queue.put(None)


class Tournament:
    """Offline round-robin harness over N processes.

    ``schedule`` materializes every game up front (2-player games get
    first/second seat balancing; larger games get shuffled seats);
    ``launch`` starts the runner processes (or runs inline for 1 process);
    ``collect`` tallies outcomes per agent per pattern; ``report`` prints
    the reference-format summary the plot tooling parses.
    """

    def __init__(self, env, agents: List[Any], critic, env_args,
                 args_patterns: Dict[str, dict], num_process: int,
                 num_games: int, seed: int):
        self.env = env
        self.agents = agents
        self.critic = critic
        self.env_args = env_args
        self.patterns = args_patterns
        self.num_process = num_process
        self.num_games = num_games
        self.seed = seed
        self.jobs: List[tuple] = []
        self.by_pattern = [dict() for _ in agents]   # agent -> label -> tally
        self.overall = [dict() for _ in agents]      # agent -> tally

    def _seating(self, game_index: int) -> tuple:
        """(label_suffix, seat assignment) for one game."""
        n = len(self.agents)
        if n == 2:
            plays_first = game_index < (self.num_games + 1) // 2
            return ('-F', [0, 1]) if plays_first else ('-S', [1, 0])
        return ('', random.sample(range(n), n))

    def schedule(self):
        serial = 0
        for label, game_args in self.patterns.items():
            for i in range(self.num_games):
                suffix, seat_ids = self._seating(i)
                self.jobs.append((serial, seat_ids, label + suffix, game_args))
                for tallies in self.by_pattern:
                    tallies.setdefault(label + suffix, {})
                serial += 1

    def launch(self, per_process_agents: List[List[Any]], show_inline: bool):
        job_queue: Any = mp.Queue()
        self.results: Any = mp.Queue()
        for job in self.jobs:
            job_queue.put(job)
        for _ in range(self.num_process):
            job_queue.put(None)
        for i in range(self.num_process):
            child_args = (per_process_agents[i], self.critic, self.env_args,
                          i, job_queue, self.results, self.seed)
            if self.num_process > 1:
                mp.Process(target=_tournament_child, args=child_args).start()
                for agent in per_process_agents[i]:
                    if isinstance(agent, NetworkAgent):
                        agent.conn.close()   # child owns the duplicate now
            else:
                _tournament_child(*child_args, show=show_inline)

    def collect(self):
        pending = self.num_process
        while pending > 0:
            item = self.results.get()
            if item is None:
                pending -= 1
                continue
            label, seat_ids, match = item
            outcome = (match or {}).get('result')
            if outcome is None:
                continue
            for idx, player in enumerate(self.env.players()):
                agent_id = seat_ids[idx]
                score = outcome[player]
                pat = self.by_pattern[agent_id][label]
                pat[score] = pat.get(score, 0) + 1
                self.overall[agent_id][score] = \
                    self.overall[agent_id].get(score, 0) + 1

    def report(self):
        for a, per_pattern in enumerate(self.by_pattern):
            print('---agent %d---' % a)
            for label, tally in per_pattern.items():
                print(label,
                      {k: tally[k] for k in sorted(tally, reverse=True)},
                      wp_func(tally))
            print('total',
                  {k: self.overall[a][k]
                   for k in sorted(self.overall[a], reverse=True)},
                  wp_func(self.overall[a]))


def evaluate_mp(env, agents: List[Any], critic, env_args, args_patterns,
                num_process: int, num_games: int, seed: int):
    """Run an offline tournament (compatibility wrapper over Tournament)."""
    tournament = Tournament(env, agents, critic, env_args, args_patterns,
                            num_process, num_games, seed)
    print('total games = %d' % (len(args_patterns) * num_games))
    time.sleep(0.1)
    tournament.schedule()

    network_mode = agents[0] is None
    if network_mode:
        per_process = network_match_acception(
            num_process, env_args, len(agents), network_match_port)
    else:
        per_process = [agents] * num_process

    tournament.launch(per_process, show_inline=num_process == 1)
    tournament.collect()
    tournament.report()


def network_match_acception(n: int, env_args, num_agents: int, port: int):
    """Accept exactly n*num_agents client connections, grouped per match;
    every accepted client immediately receives env_args (the reference only
    answered the first of each group and relied on surplus reconnects)."""
    waiting, groups = [], []
    acceptor = accept_socket_connections(port)
    while len(groups) < n:
        conn = next(acceptor)
        if conn is None:
            continue
        waiting.append(conn)
        if len(waiting) == num_agents:
            for c in waiting:
                c.send(env_args)
            groups.append([NetworkAgent(c) for c in waiting])
            waiting = []
    return groups


# ---------------------------------------------------------------------------
# model loading


class ExportedModel:
    """Inference over a serialized-StableHLO export (scripts/export_model.py).

    Counterpart of the reference's OnnxModel (evaluation.py:288-354): same
    numpy-in/numpy-out ``inference``/``init_hidden`` surface, loadable
    without the model's Python class. Hidden-state shapes are recovered from
    the export's input signature."""

    def __init__(self, model_path: str):
        self.model_path = model_path
        self._exported = None
        self._hidden_spec = None

    def _open(self):
        if self._exported is not None:
            return
        from jax import export as jexport
        from jax import tree_util
        with open(self.model_path, 'rb') as f:
            self._exported = jexport.deserialize(f.read())
        args, _kwargs = tree_util.tree_unflatten(
            self._exported.in_tree, list(self._exported.in_avals))
        self._hidden_spec = args[1] if len(args) > 1 else None

    def init_hidden(self, batch_size=None):
        import numpy as np
        from jax import tree_util
        self._open()
        if self._hidden_spec is None:
            return None
        return tree_util.tree_map(
            lambda a: np.zeros(a.shape, a.dtype), self._hidden_spec)

    def inference(self, x, hidden=None):
        import numpy as np
        from .utils.tree import map_structure
        self._open()
        obs = map_structure(lambda v: np.asarray(v, np.float32)[None], x)
        if self._hidden_spec is not None:
            outputs = self._exported.call(obs, hidden)
        else:
            outputs = self._exported.call(obs)
        result = {}
        for k, v in outputs.items():
            if k == 'hidden':
                result[k] = v
            elif v is not None:
                result[k] = np.asarray(v)[0]
        return result


def load_model(model_path: str, env):
    """Load a model spec: .jaxexp exports (self-contained StableHLO),
    learner checkpoints (msgpack params + the env's architecture), or the
    serving tier's named models (docs/serving.md):

    * ``serve://host:port/line@selector`` — a proxy onto a running
      InferenceService: every agent/evaluator inference becomes a framed
      request against the engine fleet, resolved by name, so eval servers
      and league matches follow a promote without restarting;
    * ``registry://root/line@selector`` — the registry-pinned checkpoint
      loaded locally (CRC-verified), e.g. ``registry://models/default@champion``.
    """
    if model_path.startswith('serve://'):
        from .serving.client import model_from_spec
        return model_from_spec(model_path)
    if model_path.startswith('registry://'):
        from .model import ModelWrapper
        from .serving.registry import ModelRegistry, parse_spec
        rest = model_path[len('registry://'):]
        root, _, spec = rest.rpartition('/')
        line, selector = parse_spec(spec)
        snap = ModelRegistry(root or '.').load_snapshot(line, selector)
        env.reset()
        example_obs = env.observation(env.players()[0])
        return ModelWrapper.from_snapshot(snap, example_obs)
    if model_path.endswith('.jaxexp'):
        return ExportedModel(model_path)
    from .model import ModelWrapper
    wrapper = ModelWrapper(env.net())
    env.reset()
    example_obs = env.observation(env.players()[0])
    with open(model_path, 'rb') as f:
        wrapper.load_params_bytes(f.read(), example_obs)
    return wrapper


def _resolve_agent(model_path: str, env):
    agent = build_agent(model_path, env)
    if agent is None:
        agent = Agent(load_model(model_path, env))
    return agent


def split_model_specs(raw: str) -> List[str]:
    """Split the CLI's ``MODEL[:OPPONENT]`` argv on ``:`` while keeping
    URL-style specs whole: ``serve://host:port/line@sel`` and
    ``registry://root/line@sel`` carry colons of their own (the scheme and
    the endpoint port), so a naive split would shred them."""
    out: List[str] = []
    for part in raw.split(':'):
        if out and out[-1].endswith(('serve', 'registry')) \
                and part.startswith('//'):
            out[-1] += ':' + part          # scheme:// reassembled
        elif out and '://' in out[-1] and part[:1].isdigit():
            out[-1] += ':' + part          # the endpoint's port
        else:
            out.append(part)
    return out


# ---------------------------------------------------------------------------
# CLI entry points


def eval_main(args, argv):
    force_cpu_backend()   # evaluation is a host-side workload
    env_args = args['env_args']
    prepare_env(env_args)
    env = make_env(env_args)

    model_paths = (split_model_specs(argv[0]) if len(argv) >= 1
                   else ['models/latest.ckpt'])
    num_games = int(argv[1]) if len(argv) >= 2 else 100
    num_process = int(argv[2]) if len(argv) >= 3 else 1

    main_agent = _resolve_agent(model_paths[0], env)
    critic = None

    print('%d process, %d games' % (num_process, num_games))
    seed = random.randrange(int(1e8))
    print('seed = %d' % seed)

    opponent = model_paths[1] if len(model_paths) > 1 else 'random'
    agents = [main_agent] + [_resolve_agent(opponent, env)
                             for _ in range(len(env.players()) - 1)]
    evaluate_mp(env, agents, critic, env_args, {'default': {}},
                num_process, num_games, seed)


def eval_server_main(args, argv):
    force_cpu_backend()
    print('network match server mode')
    env_args = args['env_args']
    prepare_env(env_args)
    env = make_env(env_args)

    num_games = int(argv[0]) if len(argv) >= 1 else 100
    num_process = int(argv[1]) if len(argv) >= 2 else 1

    print('%d process, %d games' % (num_process, num_games))
    seed = random.randrange(int(1e8))
    print('seed = %d' % seed)

    evaluate_mp(env, [None] * len(env.players()), None, env_args,
                {'default': {}}, num_process, num_games, seed)


def client_mp_child(env_args, model_path, conn):
    force_cpu_backend()
    env = make_env(env_args)
    agent = _resolve_agent(model_path, env)
    NetworkAgentClient(agent, env, conn).run()


def eval_client_main(args, argv):
    force_cpu_backend()
    print('network match client mode')
    while True:
        try:
            host = argv[1] if len(argv) >= 2 else 'localhost'
            conn = connect_socket_connection(host, network_match_port)
            env_args = conn.recv()
        except (ConnectionResetError, ConnectionRefusedError, OSError):
            break
        model_path = argv[0] if len(argv) >= 1 else 'models/latest.ckpt'
        mp.Process(target=client_mp_child,
                   args=(env_args, model_path, conn)).start()
        conn.close()
