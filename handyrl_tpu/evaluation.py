"""Evaluation: online evaluator role, offline match harness, network battles.

Parity with the reference evaluation stack (evaluation.py): shared-env
matches (``exec_match``), delta-synced per-player env matches
(``exec_network_match``), the multiprocess tournament runner with
first/second-player balancing, and the TCP network battle mode on port 9876
(server accepts remote/human agents speaking the diff_info protocol).

Model files are our msgpack checkpoints (see train.py) — loading one cannot
execute code, unlike unpickling a torch module.
"""

from __future__ import annotations

import multiprocessing as mp
import random
import time
from typing import Any, Dict, List, Optional

from .agent import Agent, EnsembleAgent, RandomAgent, RuleBasedAgent, SoftAgent
from .connection import (accept_socket_connections, connect_socket_connection,
                         send_recv)
from .environment import make_env, prepare_env

network_match_port = 9876


def view(env, player=None):
    if hasattr(env, 'view'):
        env.view(player=player)
    else:
        print(env)


def view_transition(env):
    if hasattr(env, 'view_transition'):
        env.view_transition()


class NetworkAgentClient:
    """Client side of a network battle: executes commands from the server
    against a local env + agent."""

    def __init__(self, agent, env, conn):
        self.conn = conn
        self.agent = agent
        self.env = env

    def run(self):
        while True:
            try:
                command, args = self.conn.recv()
            except ConnectionResetError:
                break
            if command == 'quit':
                break
            elif command == 'outcome':
                print('outcome = %f' % args[0])
            elif hasattr(self.agent, command):
                if command in ('action', 'observe'):
                    view(self.env)
                ret = getattr(self.agent, command)(self.env, *args, show=True)
                if command == 'action':
                    player = args[0]
                    ret = self.env.action2str(ret, player)
            else:
                ret = getattr(self.env, command)(*args)
                if command == 'update':
                    reset = args[1]
                    if reset:
                        self.agent.reset(self.env, show=True)
                    else:
                        view_transition(self.env)
            self.conn.send(ret)


class NetworkAgent:
    """Server-side stub driving a remote NetworkAgentClient."""

    def __init__(self, conn):
        self.conn = conn

    def update(self, data, reset):
        return send_recv(self.conn, ('update', [data, reset]))

    def outcome(self, outcome):
        return send_recv(self.conn, ('outcome', [outcome]))

    def action(self, player):
        return send_recv(self.conn, ('action', [player]))

    def observe(self, player):
        return send_recv(self.conn, ('observe', [player]))


def exec_match(env, agents: Dict[int, Any], critic=None, show=False,
               game_args={}) -> Optional[dict]:
    """Match on one shared environment."""
    if env.reset(game_args):
        return None
    for agent in agents.values():
        agent.reset(env, show=show)
    while not env.terminal():
        if show:
            view(env)
        if show and critic is not None:
            print('cv = ', critic.observe(env, None, show=False)[0])
        turn_players = env.turns()
        observers = env.observers()
        actions = {}
        for p, agent in agents.items():
            if p in turn_players:
                actions[p] = agent.action(env, p, show=show)
            elif p in observers:
                agent.observe(env, p, show=show)
        if env.step(actions):
            return None
        if show:
            view_transition(env)
    outcome = env.outcome()
    if show:
        print('final outcome = %s' % outcome)
    return {'result': outcome}


def exec_network_match(env, network_agents: Dict[int, NetworkAgent],
                       critic=None, show=False, game_args={}) -> Optional[dict]:
    """Match where each remote agent mirrors the env from diff_info deltas and
    communicates actions as strings."""
    if env.reset(game_args):
        return None
    for p, agent in network_agents.items():
        agent.update(env.diff_info(p), True)
    while not env.terminal():
        if show:
            view(env)
        turn_players = env.turns()
        observers = env.observers()
        actions = {}
        for p, agent in network_agents.items():
            if p in turn_players:
                actions[p] = env.str2action(agent.action(p), p)
            elif p in observers:
                agent.observe(p)
        if env.step(actions):
            return None
        for p, agent in network_agents.items():
            agent.update(env.diff_info(p), False)
    outcome = env.outcome()
    for p, agent in network_agents.items():
        agent.outcome(outcome[p])
    return {'result': outcome}


def build_agent(raw: str, env=None):
    if raw == 'random':
        return RandomAgent()
    if raw.startswith('rulebase'):
        key = raw.split('-')[1] if '-' in raw else None
        return RuleBasedAgent(key)
    return None


class Evaluator:
    """Online evaluation during training: the trained model vs a configured
    opponent pool (default 'random')."""

    def __init__(self, env, args):
        self.env = env
        self.args = args
        self.default_opponent = 'random'

    def execute(self, models: Dict[int, Any], eval_args) -> Optional[dict]:
        opponents = self.args.get('eval', {}).get('opponent', [])
        opponent = random.choice(opponents) if opponents else self.default_opponent

        agents = {}
        for p, model in models.items():
            if model is None:
                agents[p] = build_agent(opponent, self.env)
            else:
                agents[p] = Agent(model)

        results = exec_match(self.env, agents)
        if results is None:
            print('None episode in evaluation!')
            return None
        return {'args': eval_args, 'opponent': opponent, **results}


def wp_func(results: Dict[Optional[float], int]) -> float:
    games = sum(v for k, v in results.items() if k is not None)
    win = sum((k + 1) / 2 * v for k, v in results.items() if k is not None)
    return win / games if games else 0.0


def eval_process_mp_child(agents, critic, env_args, index, in_queue, out_queue,
                          seed, show=False):
    from .connection import force_cpu_backend
    force_cpu_backend()
    random.seed(seed + index)
    env = make_env({**env_args, 'id': index})
    while True:
        args = in_queue.get()
        if args is None:
            break
        g, agent_ids, pat_idx, game_args = args
        print('*** Game %d ***' % g)
        agent_map = {env.players()[p]: agents[ai]
                     for p, ai in enumerate(agent_ids)}
        if isinstance(list(agent_map.values())[0], NetworkAgent):
            results = exec_network_match(env, agent_map, critic, show=show,
                                         game_args=game_args)
        else:
            results = exec_match(env, agent_map, critic, show=show,
                                 game_args=game_args)
        out_queue.put((pat_idx, agent_ids, results))
    out_queue.put(None)


def evaluate_mp(env, agents: List[Any], critic, env_args, args_patterns,
                num_process: int, num_games: int, seed: int):
    """Offline tournament: jobs over N processes; in 2-player games the
    first/second seats are balanced across games."""
    in_queue, out_queue = mp.Queue(), mp.Queue()
    args_cnt = 0
    total_results = [{} for _ in agents]
    result_map = [{} for _ in agents]
    print('total games = %d' % (len(args_patterns) * num_games))
    time.sleep(0.1)
    for pat_idx, args in args_patterns.items():
        for i in range(num_games):
            if len(agents) == 2:
                first = 0 if i < (num_games + 1) // 2 else 1
                tmp_pat_idx, agent_ids = ((pat_idx + '-F', [0, 1]) if first == 0
                                          else (pat_idx + '-S', [1, 0]))
            else:
                tmp_pat_idx = pat_idx
                agent_ids = random.sample(range(len(agents)), len(agents))
            in_queue.put((args_cnt, agent_ids, tmp_pat_idx, args))
            for p in range(len(agents)):
                result_map[p][tmp_pat_idx] = {}
            args_cnt += 1

    network_mode = agents[0] is None
    if network_mode:
        agents = network_match_acception(num_process, env_args, len(agents),
                                         network_match_port)
    else:
        agents = [agents] * num_process

    for i in range(num_process):
        in_queue.put(None)
        args = agents[i], critic, env_args, i, in_queue, out_queue, seed
        if num_process > 1:
            mp.Process(target=eval_process_mp_child, args=args).start()
            if network_mode:
                for agent in agents[i]:
                    agent.conn.close()
        else:
            eval_process_mp_child(*args, show=True)

    finished_cnt = 0
    while finished_cnt < num_process:
        ret = out_queue.get()
        if ret is None:
            finished_cnt += 1
            continue
        pat_idx, agent_ids, results = ret
        outcome = results.get('result') if results else None
        if outcome is not None:
            for idx, p in enumerate(env.players()):
                agent_id = agent_ids[idx]
                oc = outcome[p]
                result_map[agent_id][pat_idx][oc] = \
                    result_map[agent_id][pat_idx].get(oc, 0) + 1
                total_results[agent_id][oc] = total_results[agent_id].get(oc, 0) + 1

    for p, r_map in enumerate(result_map):
        print('---agent %d---' % p)
        for pat_idx, results in r_map.items():
            print(pat_idx, {k: results[k] for k in sorted(results, reverse=True)},
                  wp_func(results))
        print('total', {k: total_results[p][k]
                        for k in sorted(total_results[p], reverse=True)},
              wp_func(total_results[p]))


def network_match_acception(n: int, env_args, num_agents: int, port: int):
    """Accept exactly n*num_agents client connections, grouped per match;
    every accepted client immediately receives env_args (the reference only
    answered the first of each group and relied on surplus reconnects)."""
    waiting, accepted = [], []
    acceptor = accept_socket_connections(port)
    while len(accepted) < n * num_agents:
        conn = next(acceptor)
        if conn is None:
            continue
        waiting.append(conn)
        if len(waiting) == num_agents:
            for c in waiting:
                c.send(env_args)
            accepted += waiting
            waiting = []
    return [[NetworkAgent(accepted[i * num_agents + j])
             for j in range(num_agents)] for i in range(n)]


class ExportedModel:
    """Inference over a serialized-StableHLO export (scripts/export_model.py).

    Counterpart of the reference's OnnxModel (evaluation.py:288-354): same
    numpy-in/numpy-out ``inference``/``init_hidden`` surface, loadable
    without the model's Python class. Hidden-state shapes are recovered from
    the export's input signature."""

    def __init__(self, model_path: str):
        self.model_path = model_path
        self._exported = None
        self._hidden_spec = None

    def _open(self):
        if self._exported is not None:
            return
        import jax
        from jax import export as jexport
        from jax import tree_util
        with open(self.model_path, 'rb') as f:
            self._exported = jexport.deserialize(f.read())
        args, _kwargs = tree_util.tree_unflatten(
            self._exported.in_tree, list(self._exported.in_avals))
        self._hidden_spec = args[1] if len(args) > 1 else None

    def init_hidden(self, batch_size=None):
        import numpy as np
        from jax import tree_util
        self._open()
        if self._hidden_spec is None:
            return None
        return tree_util.tree_map(
            lambda a: np.zeros(a.shape, a.dtype), self._hidden_spec)

    def inference(self, x, hidden=None):
        import numpy as np
        from .utils.tree import map_structure
        self._open()
        obs = map_structure(lambda v: np.asarray(v, np.float32)[None], x)
        if self._hidden_spec is not None:
            outputs = self._exported.call(obs, hidden)
        else:
            outputs = self._exported.call(obs)
        result = {}
        for k, v in outputs.items():
            if k == 'hidden':
                result[k] = v
            elif v is not None:
                result[k] = np.asarray(v)[0]
        return result


def load_model(model_path: str, env):
    """Load a model file: .jaxexp exports (self-contained StableHLO) or
    learner checkpoints (msgpack params + the env's architecture)."""
    if model_path.endswith('.jaxexp'):
        return ExportedModel(model_path)
    from .model import ModelWrapper
    wrapper = ModelWrapper(env.net())
    env.reset()
    example_obs = env.observation(env.players()[0])
    with open(model_path, 'rb') as f:
        wrapper.load_params_bytes(f.read(), example_obs)
    return wrapper


def _resolve_agent(model_path: str, env):
    agent = build_agent(model_path, env)
    if agent is None:
        agent = Agent(load_model(model_path, env))
    return agent


def eval_main(args, argv):
    from .connection import force_cpu_backend
    force_cpu_backend()   # evaluation is a host-side workload
    env_args = args['env_args']
    prepare_env(env_args)
    env = make_env(env_args)

    model_paths = argv[0].split(':') if len(argv) >= 1 else ['models/latest.ckpt']
    num_games = int(argv[1]) if len(argv) >= 2 else 100
    num_process = int(argv[2]) if len(argv) >= 3 else 1

    main_agent = _resolve_agent(model_paths[0], env)
    critic = None

    print('%d process, %d games' % (num_process, num_games))
    seed = random.randrange(int(1e8))
    print('seed = %d' % seed)

    opponent = model_paths[1] if len(model_paths) > 1 else 'random'
    agents = [main_agent] + [_resolve_agent(opponent, env)
                             for _ in range(len(env.players()) - 1)]
    evaluate_mp(env, agents, critic, env_args, {'default': {}},
                num_process, num_games, seed)


def eval_server_main(args, argv):
    from .connection import force_cpu_backend
    force_cpu_backend()
    print('network match server mode')
    env_args = args['env_args']
    prepare_env(env_args)
    env = make_env(env_args)

    num_games = int(argv[0]) if len(argv) >= 1 else 100
    num_process = int(argv[1]) if len(argv) >= 2 else 1

    print('%d process, %d games' % (num_process, num_games))
    seed = random.randrange(int(1e8))
    print('seed = %d' % seed)

    evaluate_mp(env, [None] * len(env.players()), None, env_args,
                {'default': {}}, num_process, num_games, seed)


def client_mp_child(env_args, model_path, conn):
    from .connection import force_cpu_backend
    force_cpu_backend()
    env = make_env(env_args)
    agent = build_agent(model_path, env)
    if agent is None:
        agent = Agent(load_model(model_path, env))
    NetworkAgentClient(agent, env, conn).run()


def eval_client_main(args, argv):
    from .connection import force_cpu_backend
    force_cpu_backend()
    print('network match client mode')
    while True:
        try:
            host = argv[1] if len(argv) >= 2 else 'localhost'
            conn = connect_socket_connection(host, network_match_port)
            env_args = conn.recv()
        except ConnectionResetError:
            break
        model_path = argv[0] if len(argv) >= 1 else 'models/latest.ckpt'
        mp.Process(target=client_mp_child,
                   args=(env_args, model_path, conn)).start()
        conn.close()
