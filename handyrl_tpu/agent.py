"""Match-time policies: the five agent kinds the evaluation stack speaks.

Round-2 redesign. The agent protocol (``reset`` / ``action`` / ``observe``,
each taking ``(env, player, show)``) is the compatibility surface the match
engines and the network-battle client dispatch on (reference agent.py:13-113
defines the same five kinds); the implementations here are built around a
single model-driven core:

* legal-move handling is one helper producing ``-inf``-masked logits;
* temperature is a parameter of :class:`Agent` (0 = argmax), so the "soft"
  variant is just a preset;
* :class:`EnsembleAgent` composes member ``Agent`` objects (each carrying
  its own recurrent state) and averages their heads, rather than managing a
  parallel list of models and hiddens by hand.
"""

from __future__ import annotations

import random
from typing import Optional

import numpy as np

from .utils.tree import softmax


def masked_logits(logits: np.ndarray, legal) -> np.ndarray:
    """Logits with every illegal action driven to -inf."""
    out = np.full_like(logits, -np.inf)
    out[legal] = logits[legal]
    return out


def _show_outputs(env, probs, value):
    """Human-readable policy/value dump; envs may override the format."""
    if hasattr(env, 'print_outputs'):
        env.print_outputs(probs, value)
        return
    if value is not None:
        print('v = %f' % np.asarray(value).reshape(-1)[0])
    if probs is not None:
        print('p = %s' % (probs * 1000).astype(int))


class RandomAgent:
    """Uniform over legal actions; the universal baseline opponent."""

    def reset(self, env, show=False):
        pass

    def action(self, env, player, show=False):
        return random.choice(env.legal_actions(player))

    def observe(self, env, player, show=False):
        return [0.0]


class RuleBasedAgent(RandomAgent):
    """Plays the env's scripted policy when one exists, else random."""

    def __init__(self, key: Optional[str] = None):
        self.key = key

    def action(self, env, player, show=False):
        rule = getattr(env, 'rule_based_action', None)
        if rule is None:
            return super().action(env, player, show)
        return rule(player, key=self.key)


class Agent:
    """Model-driven agent.

    ``temperature`` 0 plays the argmax of the masked policy; otherwise
    actions are sampled from softmax(logits / temperature). Recurrent
    models carry their hidden state across the episode via ``reset``.
    """

    def __init__(self, model, temperature: float = 0.0,
                 observation: bool = True):
        self.model = model
        self.temperature = temperature
        self.observation = observation
        self.hidden = None

    def reset(self, env, show=False):
        self.hidden = self.model.init_hidden()

    def _advance(self, obs) -> dict:
        """One inference step; consumes and refreshes the hidden state."""
        outputs = self.model.inference(obs, self.hidden)
        self.hidden = outputs.pop('hidden', None)
        return outputs

    def _pick(self, logits: np.ndarray) -> int:
        if self.temperature == 0:
            return int(np.argmax(logits))
        probs = softmax(logits / self.temperature)
        return random.choices(range(len(logits)), weights=probs)[0]

    def action(self, env, player, show=False):
        outputs = self._advance(env.observation(player))
        logits = masked_logits(outputs['policy'],
                               env.legal_actions(player))
        if show:
            _show_outputs(env, softmax(logits), outputs.get('value'))
        return self._pick(logits)

    def observe(self, env, player, show=False):
        if not self.observation:
            return None
        value = self._advance(env.observation(player)).get('value')
        if show:
            _show_outputs(env, None, value)
        return value


class EnsembleAgent(Agent):
    """Averages the output heads of several models.

    Built as a committee of member Agents so each member keeps its own
    hidden state; only the averaged heads leave the committee.
    """

    def __init__(self, models, temperature: float = 0.0,
                 observation: bool = True):
        super().__init__(None, temperature, observation)
        self.members = [Agent(m) for m in models]

    def reset(self, env, show=False):
        for member in self.members:
            member.reset(env, show)

    def _advance(self, obs) -> dict:
        heads: dict = {}
        for member in self.members:
            for k, v in member._advance(obs).items():
                heads.setdefault(k, []).append(v)
        return {k: np.mean(vs, axis=0) for k, vs in heads.items()}


class SoftAgent(Agent):
    """Samples at temperature 1 — the exploration-faithful evaluator."""

    def __init__(self, model):
        super().__init__(model, temperature=1.0)
