"""Agents: policies driving a single environment in matches.

Parity with the reference agent set (agent.py:13-113): RandomAgent,
RuleBasedAgent, greedy/temperature Agent, EnsembleAgent (output averaging),
SoftAgent (temperature 1).
"""

from __future__ import annotations

import random
from typing import Optional

import numpy as np

from .utils.tree import softmax


class RandomAgent:
    def reset(self, env, show=False):
        pass

    def action(self, env, player, show=False):
        return random.choice(env.legal_actions(player))

    def observe(self, env, player, show=False):
        return [0.0]


class RuleBasedAgent(RandomAgent):
    """Defers to the env's ``rule_based_action`` when it has one."""

    def __init__(self, key: Optional[str] = None):
        self.key = key

    def action(self, env, player, show=False):
        if hasattr(env, 'rule_based_action'):
            return env.rule_based_action(player, key=self.key)
        return random.choice(env.legal_actions(player))


def print_outputs(env, prob, v):
    if hasattr(env, 'print_outputs'):
        env.print_outputs(prob, v)
    else:
        if v is not None:
            print('v = %f' % v)
        if prob is not None:
            print('p = %s' % (prob * 1000).astype(int))


class Agent:
    """Model-driven agent; temperature 0 = argmax over legal actions."""

    def __init__(self, model, temperature: float = 0.0, observation: bool = True):
        self.model = model
        self.hidden = None
        self.temperature = temperature
        self.observation = observation

    def reset(self, env, show=False):
        self.hidden = self.model.init_hidden()

    def plan(self, obs):
        outputs = self.model.inference(obs, self.hidden)
        self.hidden = outputs.pop('hidden', None)
        return outputs

    def action(self, env, player, show=False):
        outputs = self.plan(env.observation(player))
        actions = env.legal_actions(player)
        p = outputs['policy']
        v = outputs.get('value', None)
        mask = np.ones_like(p)
        mask[actions] = 0
        p = p - mask * 1e32

        if show:
            print_outputs(env, softmax(p), v)

        if self.temperature == 0:
            return max(actions, key=lambda a: p[a])
        probs = softmax(p / self.temperature)
        return random.choices(np.arange(len(p)), weights=probs)[0]

    def observe(self, env, player, show=False):
        v = None
        if self.observation:
            outputs = self.plan(env.observation(player))
            v = outputs.get('value', None)
            if show:
                print_outputs(env, None, v)
        return v


class EnsembleAgent(Agent):
    """Averages the outputs of several models (each with its own hidden)."""

    def reset(self, env, show=False):
        self.hidden = [model.init_hidden() for model in self.model]

    def plan(self, obs):
        outputs: dict = {}
        for i, model in enumerate(self.model):
            out = model.inference(obs, self.hidden[i])
            for k, v in out.items():
                if k == 'hidden':
                    self.hidden[i] = v
                else:
                    outputs.setdefault(k, []).append(v)
        return {k: np.mean(v, axis=0) for k, v in outputs.items()}


class SoftAgent(Agent):
    def __init__(self, model):
        super().__init__(model, temperature=1.0)
