"""Episode generation.

Two engines produce identical episode records (the data contract of
generation.py:20-93 in the reference):

  * ``Generator`` — one env, one step at a time, per-player ``inference``
    calls. Used by remote CPU workers and evaluation, and by games where
    players run different models.

  * ``BatchedGenerator`` — the TPU-first engine: N environments advance in
    lockstep against ONE jitted batched forward pass per step (self-play,
    shared latest model). The reference does B=1 CPU inference per env step
    (model.py:50-60); batching across envs is where actor throughput comes
    from. Finished episodes stream out; their slots reset immediately.

Episode record: ``{'args', 'steps', 'outcome', 'moment': [bz2 chunks]}``
with per-step moment dicts of 7 per-player entries + the turn list.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

import numpy as np

from . import telemetry
from .ops.batch import MOMENT_KEYS, compress_moments
from .utils.tree import map_structure, softmax, stack_structure

# every finished episode from ANY engine counts here; per-process registries
# ride the heartbeat frames, so the learner can attribute fleet generation
# volume (and derive per-peer episodes/sec) without extra RPCs
_EPISODES = telemetry.counter('episodes_generated_total')
_STEPS = telemetry.counter('generation_steps_total')


def _sample_action(policy: np.ndarray, legal_actions) -> tuple:
    """Mask illegal logits with +1e32 penalty, softmax, sample.

    Returns (action, prob_of_action, action_mask)."""
    action_mask = np.ones_like(policy) * 1e32
    action_mask[legal_actions] = 0
    p = softmax(policy - action_mask)
    action = random.choices(legal_actions, weights=p[legal_actions])[0]
    return action, p[action], action_mask


def _blank_moment(players) -> Dict[str, Dict[int, Any]]:
    return {key: {p: None for p in players} for key in MOMENT_KEYS}


def _finalize_episode(env, moments: List[dict], args: Dict[str, Any],
                      gen_args: Dict[str, Any]) -> Optional[dict]:
    if len(moments) < 1:
        return None
    for player in env.players():
        ret = 0.0
        for i, m in reversed(list(enumerate(moments))):
            ret = (m['reward'][player] or 0) + args['gamma'] * ret
            moments[i]['return'][player] = ret
    _EPISODES.inc()
    _STEPS.inc(len(moments))
    return {
        'args': gen_args, 'steps': len(moments),
        'outcome': env.outcome(),
        'moment': compress_moments(moments, args['compress_steps']),
    }


class Generator:
    """Sequential single-env episode generator (reference-parity engine)."""

    def __init__(self, env, args: Dict[str, Any]):
        self.env = env
        self.args = args

    def generate(self, models: Dict[int, Any], gen_args: Dict[str, Any]
                 ) -> Optional[dict]:
        moments: List[dict] = []
        hidden = {p: models[p].init_hidden() for p in self.env.players()}
        if self.env.reset():
            return None

        while not self.env.terminal():
            moment = _blank_moment(self.env.players())
            turn_players = self.env.turns()
            observers = self.env.observers()

            for player in self.env.players():
                if player not in turn_players + observers:
                    continue
                if (player not in turn_players and player in gen_args['player']
                        and not self.args['observation']):
                    continue

                obs = self.env.observation(player)
                outputs = models[player].inference(obs, hidden[player])
                hidden[player] = outputs.get('hidden', None)
                moment['observation'][player] = obs
                moment['value'][player] = outputs.get('value', None)

                if player in turn_players:
                    action, prob, amask = _sample_action(
                        outputs['policy'], self.env.legal_actions(player))
                    moment['selected_prob'][player] = prob
                    moment['action_mask'][player] = amask
                    moment['action'][player] = action

            if self.env.step(moment['action']):
                return None

            reward = self.env.reward()
            for player in self.env.players():
                moment['reward'][player] = reward.get(player, None)
            moment['turn'] = turn_players
            moments.append(moment)

        return _finalize_episode(self.env, moments, self.args, gen_args)

    def execute(self, models, gen_args) -> Optional[dict]:
        episode = self.generate(models, gen_args)
        if episode is None:
            telemetry.get_logger('generation').warning(
                'None episode in generation!')
        return episode


class BatchedGenerator:
    """N-env lockstep self-play generator against one batched forward.

    Every step gathers the observations of all (env, player) pairs that must
    run inference, evaluates them in ONE ``batch_inference`` call on device,
    then samples/steps on host. Recurrent state lives host-side per
    (env, player) and rides along in the same batch.
    """

    def __init__(self, make_env_fn, wrapper, args: Dict[str, Any],
                 n_envs: int = 64):
        self.envs = [make_env_fn(i) for i in range(n_envs)]
        self.wrapper = wrapper
        self.args = args
        self.n_envs = n_envs
        self._moments: List[List[dict]] = [[] for _ in range(n_envs)]
        self._hidden: List[Dict[int, Any]] = [{} for _ in range(n_envs)]
        for i, env in enumerate(self.envs):
            env.reset()
            self._hidden[i] = {p: wrapper.init_hidden() for p in env.players()}

    def _gen_args(self, env) -> Dict[str, Any]:
        return {'role': 'g', 'player': env.players(),
                'model_id': {p: -1 for p in env.players()}}

    def step(self) -> List[dict]:
        """Advance all envs one step; returns episodes finished this step."""
        jobs = []   # (env_idx, player, acting: bool, obs)
        for i, env in enumerate(self.envs):
            turn_players = env.turns()
            observers = env.observers()
            for player in env.players():
                if player not in turn_players + observers:
                    continue
                if (player not in turn_players and not self.args['observation']):
                    continue
                jobs.append((i, player, player in turn_players,
                             env.observation(player)))

        if not jobs:
            return []

        # pad the row count to a power-of-two bucket so simultaneous games
        # (variable active-player counts) trigger at most log2 recompiles
        rows = len(jobs)
        bucket = max(8, 1 << (rows - 1).bit_length())
        pad = bucket - rows

        def pad_rows(x):
            if pad == 0:
                return x
            return np.concatenate([x, np.repeat(x[:1], pad, axis=0)], axis=0)

        obs_batch = map_structure(pad_rows, stack_structure([j[3] for j in jobs]))
        use_hidden = any(self._hidden[i].get(p) is not None for i, p, _, _ in jobs)
        hidden_batch = None
        if use_hidden:
            hidden_batch = map_structure(
                pad_rows, stack_structure([self._hidden[i][p] for i, p, _, _ in jobs]))
        outputs = self.wrapper.batch_inference(obs_batch, hidden_batch)
        policies = np.asarray(outputs['policy'])
        values = np.asarray(outputs['value']) if 'value' in outputs else None
        returns_head = np.asarray(outputs['return']) if 'return' in outputs else None
        next_hidden = outputs.get('hidden', None)

        # vectorized categorical sampling for all acting rows at once:
        # mask illegal logits, then Gumbel-max (== sampling from the masked
        # softmax); selected_prob comes from the same masked softmax
        acting_rows = [r for r, j in enumerate(jobs) if j[2]]
        if acting_rows:
            amasks = np.full((len(acting_rows),) + policies.shape[1:], 1e32,
                             np.float32)
            for n, r in enumerate(acting_rows):
                i, player, _, _ = jobs[r]
                amasks[n][self.envs[i].legal_actions(player)] = 0
            masked = policies[acting_rows] - amasks
            probs = softmax(masked)
            gumbel = -np.log(-np.log(
                np.random.random_sample(masked.shape) + 1e-12) + 1e-12)
            sampled = np.argmax(masked + gumbel, axis=-1)
        row_to_sample = {r: n for n, r in enumerate(acting_rows)}

        # scatter results back into per-env moments
        pending: Dict[int, dict] = {}
        for row, (i, player, acting, obs) in enumerate(jobs):
            env = self.envs[i]
            if i not in pending:
                pending[i] = _blank_moment(env.players())
                pending[i]['turn'] = env.turns()
            moment = pending[i]
            moment['observation'][player] = obs
            if values is not None:
                moment['value'][player] = values[row]
            if next_hidden is not None:
                self._hidden[i][player] = map_structure(
                    lambda a: np.asarray(a)[row], next_hidden)
            if acting:
                n = row_to_sample[row]
                action = int(sampled[n])
                moment['selected_prob'][player] = probs[n, action]
                moment['action_mask'][player] = amasks[n]
                moment['action'][player] = action

        finished: List[dict] = []
        for i, moment in pending.items():
            env = self.envs[i]
            err = env.step(moment['action'])
            if err:
                self._reset_slot(i)
                continue
            reward = env.reward()
            for player in env.players():
                moment['reward'][player] = reward.get(player, None)
            self._moments[i].append(moment)

            if env.terminal():
                episode = _finalize_episode(env, self._moments[i], self.args,
                                            self._gen_args(env))
                if episode is not None:
                    finished.append(episode)
                self._reset_slot(i)
        return finished

    def _reset_slot(self, i: int):
        self._moments[i] = []
        self.envs[i].reset()
        self._hidden[i] = {p: self.wrapper.init_hidden()
                           for p in self.envs[i].players()}


class BatchedEvaluator:
    """Vectorized online evaluation: N concurrent matches of the trained
    model (greedy, one rotating seat per match) against configured
    opponents. Opponents may be host-side agents (random / rule-based) or
    model checkpoints ('eval: opponent: [models/5.ckpt]'): every
    model-driven seat — the trained seat and any model opponents — is
    batched across matches, one inference call per distinct model per step.
    The reference evaluates sequentially at B=1 (evaluation.py:159-177) and
    has no vectorized model-vs-model path at all."""

    MAIN = ''   # pool key of the trained model under evaluation

    def __init__(self, make_env_fn, wrapper, args: Dict[str, Any],
                 n_envs: int = 16):
        self.envs = [make_env_fn(i) for i in range(n_envs)]
        self.wrapper = wrapper
        self.args = args
        self.n_envs = n_envs
        self._seat_counter = 0
        self._opponents = (args.get('eval', {}).get('opponent', [])
                          or ['random'])
        self._model_pool: Dict[str, Any] = {self.MAIN: wrapper}
        # preload model opponents NOW: load_model resets the env it probes,
        # which must never happen once matches are in flight
        for spec in self._opponents:
            if self._host_agent(spec) is None:
                self._opponent_model(spec)
        self._slot_state: List[dict] = [None] * n_envs
        for i in range(n_envs):
            self._start_match(i)

    def _host_agent(self, name: str):
        """Host-side opponent for a spec name, or None if it names a model
        (same parser the worker-mode Evaluator uses)."""
        from .evaluation import build_agent
        return build_agent(name, self.envs[0])

    def _opponent_model(self, path: str):
        """Load (once) a checkpoint-file opponent into the model pool."""
        if path not in self._model_pool:
            from .evaluation import load_model
            model = load_model(path, self.envs[0])
            if not hasattr(model, 'batch_inference'):
                raise ValueError(
                    'evaluator model opponents must be .ckpt checkpoints '
                    '(batched inference); %r loads as %s'
                    % (path, type(model).__name__))
            self._model_pool[path] = model
        return self._model_pool[path]

    def _start_match(self, i: int):
        env = self.envs[i]
        env.reset()
        players = env.players()
        seat = players[self._seat_counter % len(players)]
        self._seat_counter += 1
        opponent = random.choice(self._opponents)

        agents: Dict[int, Any] = {}
        model_seats: Dict[int, dict] = {
            seat: {'key': self.MAIN, 'hidden': self.wrapper.init_hidden()}}
        for p in players:
            if p == seat:
                continue
            agent = self._host_agent(opponent)
            if agent is not None:
                agents[p] = agent
            else:
                opp = self._opponent_model(opponent)
                model_seats[p] = {'key': opponent,
                                  'hidden': opp.init_hidden()}
        self._slot_state[i] = {'seat': seat, 'opponent': opponent,
                               'agents': agents, 'model_seats': model_seats}

    def _batched_actions(self, jobs: List[tuple]) -> Dict[tuple, int]:
        """Greedy actions for (env_idx, player) model seats sharing one
        model: a single padded batch_inference call."""
        if not jobs:
            return {}
        key = self._slot_state[jobs[0][0]]['model_seats'][jobs[0][1]]['key']
        model = self._model_pool[key]
        rows = len(jobs)
        bucket = max(8, 1 << (rows - 1).bit_length())
        pad = bucket - rows

        def pad_rows(x):
            if pad == 0:
                return x
            return np.concatenate([x, np.repeat(x[:1], pad, axis=0)], axis=0)

        obs_batch = map_structure(pad_rows, stack_structure(
            [self.envs[i].observation(p) for i, p in jobs]))
        seats = [self._slot_state[i]['model_seats'][p] for i, p in jobs]
        hidden_batch = None
        if seats[0]['hidden'] is not None:
            hidden_batch = map_structure(pad_rows, stack_structure(
                [s['hidden'] for s in seats]))
        outputs = model.batch_inference(obs_batch, hidden_batch)
        policies = np.asarray(outputs['policy'])
        next_hidden = outputs.get('hidden', None)

        actions: Dict[tuple, int] = {}
        for row, (i, p) in enumerate(jobs):
            if next_hidden is not None:
                seats[row]['hidden'] = map_structure(
                    lambda a: np.asarray(a)[row], next_hidden)
            legal = self.envs[i].legal_actions(p)
            logits = policies[row]
            actions[(i, p)] = max(legal, key=lambda a: logits[a])  # greedy
        return actions

    def step(self) -> List[dict]:
        """Advance all matches one step; returns finished result records."""
        # group due model seats by model, one batched call per model
        due: Dict[str, List[tuple]] = {}
        for i, env in enumerate(self.envs):
            st = self._slot_state[i]
            for p in env.turns():
                seat_info = st['model_seats'].get(p)
                if seat_info is not None:
                    due.setdefault(seat_info['key'], []).append((i, p))
        model_actions: Dict[tuple, int] = {}
        for jobs in due.values():
            model_actions.update(self._batched_actions(jobs))

        finished = []
        for i, env in enumerate(self.envs):
            st = self._slot_state[i]
            actions = {}
            for p in env.turns():
                if p in st['model_seats']:
                    actions[p] = model_actions.get((i, p))
                else:
                    actions[p] = st['agents'][p].action(env, p)
            err = env.step(actions)
            if err:
                self._start_match(i)
                continue
            if env.terminal():
                outcome = env.outcome()
                eval_args = {'role': 'e', 'player': [st['seat']],
                             'model_id': {p: (-1 if p != st['seat'] else 0)
                                          for p in env.players()}}
                finished.append({'args': eval_args,
                                 'opponent': st['opponent'],
                                 'result': outcome})
                self._start_match(i)
        return finished
