"""Episode generation.

Two engines produce identical episode records (the data contract of
generation.py:20-93 in the reference):

  * ``Generator`` — one env, one step at a time, per-player ``inference``
    calls. Used by remote CPU workers and evaluation, and by games where
    players run different models.

  * ``BatchedGenerator`` — the TPU-first engine: N environments advance in
    lockstep against ONE jitted batched forward pass per step (self-play,
    shared latest model). The reference does B=1 CPU inference per env step
    (model.py:50-60); batching across envs is where actor throughput comes
    from. Finished episodes stream out; their slots reset immediately.

Episode record: ``{'args', 'steps', 'outcome', 'moment': [bz2 chunks]}``
with per-step moment dicts of 7 per-player entries + the turn list.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import telemetry
from .ops.batch import MOMENT_KEYS, compress_moments
from .utils.tree import map_structure, softmax, stack_structure

# every finished episode from ANY engine counts here; per-process registries
# ride the heartbeat frames, so the learner can attribute fleet generation
# volume (and derive per-peer episodes/sec) without extra RPCs
_EPISODES = telemetry.counter('episodes_generated_total')
_STEPS = telemetry.counter('generation_steps_total')


# ---------------------------------------------------------------------------
# action sampling — the ONE audited routine shared by the per-worker B=1
# path and the per-host InferenceEngine (inference.py). Sampling is keyed by
# an explicit seed sequence instead of hidden process-global RNG state, so a
# draw is a pure function of (seed sequence, policy, legal actions): the
# engine can replay any worker's draw bit-identically regardless of how
# requests interleave across the fleet.


def sample_seed(base_seed, episode_key: Sequence[int], draw_index: int
                ) -> List[int]:
    """Deterministic per-draw seed sequence for np.random.default_rng.

    ``episode_key`` identifies the episode (the server-stamped
    ``sample_key``, or a worker-local fallback stream); ``draw_index``
    counts action draws within the episode in play order."""
    seq = (int(base_seed), *(int(k) for k in episode_key), int(draw_index))
    return [k & 0xFFFFFFFFFFFFFFFF for k in seq]


def masked_sample_batch(policies: np.ndarray, legal_lists, seed_seqs):
    """Sample one action per row from the legality-masked softmax.

    Vectorized over rows: the mask build and the softmax (the hot part) run
    as single array ops; the draw itself is one inverse-CDF lookup per row
    from that row's own seeded generator. Returns
    ``(actions[int64], selected_probs[float32], action_masks[float32])``;
    the mask rows use the reference's +1e32 illegal penalty so recorded
    ``action_mask`` entries stay contract-identical.
    """
    policies = np.asarray(policies)
    masks = np.full(policies.shape, 1e32, policies.dtype)
    for n, legal in enumerate(legal_lists):
        masks[n, list(legal)] = 0
    probs = softmax(policies - masks)
    actions = np.empty(len(legal_lists), np.int64)
    selected = np.empty(len(legal_lists), policies.dtype)
    for n, (legal, seq) in enumerate(zip(legal_lists, seed_seqs)):
        legal = list(legal)
        cum = np.cumsum(probs[n, legal], dtype=np.float64)
        u = np.random.default_rng(seq).random() * cum[-1]
        idx = min(int(np.searchsorted(cum, u, side='right')), len(legal) - 1)
        actions[n] = legal[idx]
        selected[n] = probs[n, legal[idx]]
    return actions, selected, masks


def masked_sample(policy: np.ndarray, legal_actions, seed_seq) -> tuple:
    """B=1 view of :func:`masked_sample_batch`.

    Returns (action, prob_of_action, action_mask)."""
    actions, selected, masks = masked_sample_batch(
        np.asarray(policy)[None], [legal_actions], [seed_seq])
    return int(actions[0]), selected[0], masks[0]


def bucketed_inference(model, obs, hidden=None) -> Dict[str, Any]:
    """Single-sample forward through the power-of-two-bucket batched program.

    XLA compiles a DIFFERENT program for a batch-1 input than for the padded
    buckets the vectorized engines dispatch, and the two disagree in the
    last float bit (row outputs across bucket sizes 8/16/... are
    bit-identical to each other; only the B=1 program strays — and is
    slower on CPU besides). Routing the sequential path through the same
    bucketed program keeps per-worker episode records bit-identical to
    engine-mode ones. Models without ``batch_inference`` (RandomModel, wire
    proxies) fall back to their own ``inference``."""
    batch = getattr(model, 'batch_inference', None)
    if batch is None:
        return model.inference(obs, hidden)
    obs_b, _ = pad_to_bucket([obs])
    hidden_b = None
    if hidden is not None:
        hidden_b, _ = pad_to_bucket([hidden])
    outputs = batch(obs_b, hidden_b)
    out = {}
    for k, v in outputs.items():
        if v is None:
            continue
        if k == 'hidden':
            out[k] = map_structure(lambda a: np.asarray(a)[0], v)
        else:
            out[k] = np.asarray(v)[0]
    return out


def model_act(model, obs, hidden, legal_actions, seed_seq) -> Dict[str, Any]:
    """One acting ply: forward pass + masked sample.

    Engine-mode models (inference.RemoteModel) expose ``act`` and run both
    halves server-side in a coalesced batch; everything else runs the local
    bucketed forward and the same shared sampler."""
    act = getattr(model, 'act', None)
    if act is not None:
        return act(obs, hidden, legal_actions, seed_seq)
    outputs = bucketed_inference(model, obs, hidden)
    action, prob, mask = masked_sample(outputs['policy'], legal_actions,
                                       seed_seq)
    return {'action': action, 'prob': prob, 'action_mask': mask,
            'value': outputs.get('value'), 'hidden': outputs.get('hidden')}


def seed_env_rng(env, base_seed, episode_key) -> None:
    """Reseed an env's per-instance rng from the episode key.

    Envs with stochastic transitions (e.g. HungryGeese spawns) keep a
    ``random.Random`` instance; seeding it from (seed, episode_key) makes
    the whole episode a pure function of (seed, sample_key, params) —
    replayable on any worker, the host inference engine, or the device
    actor backend's strict-splice verifier. ONE definition of the seed
    string, shared by every replay path."""
    env_rng = getattr(env, 'rng', None)
    if isinstance(env_rng, random.Random):
        env_rng.seed('episode:%d:%s' % (int(base_seed), (episode_key,)))


def pad_to_bucket(structures: list, min_bucket: int = 8):
    """Stack a list of pytrees row-wise and pad the row count to a
    power-of-two bucket (replicating row 0), so simultaneous games with
    variable active-row counts trigger at most log2 recompiles.

    Returns ``(padded_batch, true_rows)``."""
    rows = len(structures)
    bucket = max(min_bucket, 1 << (rows - 1).bit_length())
    pad = bucket - rows

    def pad_rows(x):
        if pad == 0:
            return x
        return np.concatenate([x, np.repeat(x[:1], pad, axis=0)], axis=0)

    return map_structure(pad_rows, stack_structure(structures)), rows


def _blank_moment(players) -> Dict[str, Dict[int, Any]]:
    return {key: {p: None for p in players} for key in MOMENT_KEYS}


def finalize_episode_record(outcome, moments: List[dict],
                            args: Dict[str, Any], gen_args: Dict[str, Any]
                            ) -> Optional[dict]:
    """Build the canonical episode record from raw moments + outcome.

    ONE definition of the record's return fill and compression, shared by
    every producer — the host generators here, the device actor's splice,
    and the learner-side ChunkAssembler (streaming.py) reassembling chunked
    uploads. Returns need only the per-moment rewards, so a reassembled
    episode's decoded moment stream is bit-identical to a whole-episode
    upload's by construction (streaming.py spells out the exact claim)."""
    if len(moments) < 1:
        return None
    players = list(moments[0]['return'].keys())
    for player in players:
        ret = 0.0
        for i, m in reversed(list(enumerate(moments))):
            ret = (m['reward'][player] or 0) + args['gamma'] * ret
            moments[i]['return'][player] = ret
    # with engine-mode workers, bz2 compression is the dominant remaining
    # worker-side cost: time it under the shared stage_seconds vocabulary
    t0 = time.perf_counter()
    blocks = compress_moments(moments, args['compress_steps'],
                              level=args.get('compress_level', 9))
    telemetry.REGISTRY.observe_stage('compress', time.perf_counter() - t0)
    return {
        'args': gen_args, 'steps': len(moments),
        'outcome': outcome,
        'moment': blocks,
    }


def _finalize_episode(env, moments: List[dict], args: Dict[str, Any],
                      gen_args: Dict[str, Any]) -> Optional[dict]:
    record = finalize_episode_record(env.outcome(), moments, args, gen_args)
    if record is not None:
        _EPISODES.inc()
        _STEPS.inc(len(moments))
    return record


def build_chunk(gen_args: Dict[str, Any], chunk_index: int, base: int,
                window: List[dict], args: Dict[str, Any],
                final: bool = False, outcome=None) -> dict:
    """One streaming upload unit: a fixed-T window of in-flight moments.

    ``window`` moments carry ``'return': None`` (returns are filled by the
    learner once the final chunk lands); blocks use the SAME compress_steps
    grid as whole episodes (streaming.chunk_steps is validated to be a
    multiple of compress_steps), so a partial episode's chunk blocks index
    exactly like a finished record's and the batch builder can window into
    them unchanged."""
    return {
        'args': dict(gen_args), 'chunk': int(chunk_index), 'base': int(base),
        'steps': len(window),
        'moment': compress_moments(window, args['compress_steps'],
                                   level=args.get('compress_level', 9)),
        'final': bool(final),
        'outcome': outcome if final else None,
    }


class Generator:
    """Sequential single-env episode generator (reference-parity engine).

    ``namespace`` (the worker id) keys the fallback sampling stream for
    tasks without a server-stamped ``sample_key``, so parallel workers
    never replay one another's draws. When the task does carry a
    ``sample_key`` (train.py stamps every assignment), the episode is a
    pure function of (seed, sample_key, model params) — identical whether
    the draws run locally or on the host inference engine, and regardless
    of which worker the task lands on.
    """

    def __init__(self, env, args: Dict[str, Any], namespace: int = 0):
        self.env = env
        self.args = args
        self.namespace = int(namespace)
        self._local_episodes = 0

    @staticmethod
    def _record_act(moment: dict, player, hidden: dict, res: Dict[str, Any]):
        hidden[player] = res.get('hidden', None)
        moment['value'][player] = res.get('value', None)
        moment['selected_prob'][player] = res['prob']
        moment['action_mask'][player] = res['action_mask']
        moment['action'][player] = res['action']

    def generate(self, models: Dict[int, Any], gen_args: Dict[str, Any],
                 emit=None) -> Optional[dict]:
        base_seed = self.args.get('seed', 0)
        skey = (gen_args or {}).get('sample_key')
        episode_key = ((0, int(skey)) if skey is not None
                       else (1, self.namespace, self._local_episodes))
        self._local_episodes += 1
        draws = 0
        # streaming ingest: flush fixed-T chunks of the in-flight episode
        # through ``emit`` instead of holding it to completion. Boundaries
        # are a pure function of (seed, sample_key, T): every ply index is
        # deterministic under the purity contract, so a re-issued attempt
        # regenerates byte-identical chunks and the learner's duplicate
        # screen merges them. Only server-keyed tasks stream (the dedupe
        # key IS the sample_key).
        stream = None
        if emit is not None and skey is not None:
            stream = {'T': int((self.args.get('streaming') or {})
                               .get('chunk_steps', 32)),
                      'flushed': 0, 'chunk': 0}
        # envs with stochastic transitions keep a per-instance rng (e.g.
        # HungryGeese spawns); reseeding it from the episode key makes the
        # whole episode a pure function of (seed, sample_key, params) —
        # replayable on any worker and on either inference path
        seed_env_rng(self.env, base_seed, episode_key)
        moments: List[dict] = []
        hidden = {p: models[p].init_hidden() for p in self.env.players()}
        if self.env.reset():
            return None

        while not self.env.terminal():
            moment = _blank_moment(self.env.players())
            turn_players = self.env.turns()
            observers = self.env.observers()

            # acting plies first, SUBMIT-then-COLLECT: engine-mode models
            # put every simultaneous-turn request on the wire before any
            # reply is read, so a worker's whole ply coalesces into one

            # engine batch instead of paying one round trip per seat
            pending = []   # (player, model, request id)
            for player in turn_players:
                obs = self.env.observation(player)
                moment['observation'][player] = obs
                seed_seq = sample_seed(base_seed, episode_key, draws)
                draws += 1
                legal = self.env.legal_actions(player)
                submit = getattr(models[player], 'act_send', None)
                if submit is not None:
                    pending.append((player, models[player],
                                    submit(obs, hidden[player], legal,
                                           seed_seq)))
                else:
                    self._record_act(
                        moment, player, hidden,
                        model_act(models[player], obs, hidden[player],
                                  legal, seed_seq))
            for player, model, rid in pending:
                self._record_act(moment, player, hidden, model.act_recv(rid))

            for player in observers:
                if player in turn_players:
                    continue
                if (player in gen_args['player']
                        and not self.args['observation']):
                    continue
                obs = self.env.observation(player)
                outputs = bucketed_inference(models[player], obs,
                                             hidden[player])
                hidden[player] = outputs.get('hidden', None)
                moment['observation'][player] = obs
                moment['value'][player] = outputs.get('value', None)

            if self.env.step(moment['action']):
                return None

            reward = self.env.reward()
            for player in self.env.players():
                moment['reward'][player] = reward.get(player, None)
            moment['turn'] = turn_players
            moments.append(moment)

            if stream is not None and \
                    len(moments) - stream['flushed'] >= stream['T']:
                window = moments[stream['flushed']:
                                 stream['flushed'] + stream['T']]
                emit(build_chunk(gen_args, stream['chunk'],
                                 stream['flushed'], window, self.args))
                stream['flushed'] += stream['T']
                stream['chunk'] += 1

        if stream is not None:
            if len(moments) < 1:
                return None
            # final chunk: the moments past the last full window (possibly
            # zero of them) plus the outcome that closes the episode
            emit(build_chunk(gen_args, stream['chunk'], stream['flushed'],
                             moments[stream['flushed']:], self.args,
                             final=True, outcome=self.env.outcome()))
            _EPISODES.inc()
            _STEPS.inc(len(moments))
            return {'streamed': True, 'args': gen_args,
                    'steps': len(moments)}

        return _finalize_episode(self.env, moments, self.args, gen_args)

    def execute(self, models, gen_args, emit=None) -> Optional[dict]:
        # episode-lifecycle tracing: the whole env-stepping span, keyed by
        # the trace_id derived from the server-stamped task — the worker-
        # side hop of the task_assign -> generate -> upload -> ingest ->
        # train_step chain (docs/observability.md "Tracing")
        with telemetry.trace_span(
                'generate', trace_id=telemetry.episode_trace_id(gen_args),
                worker=self.namespace):
            episode = self.generate(models, gen_args, emit=emit)
        if episode is None:
            telemetry.get_logger('generation').warning(
                'None episode in generation!')
        return episode


class BatchedGenerator:
    """N-env lockstep self-play generator against one batched forward.

    Every step gathers the observations of all (env, player) pairs that must
    run inference, evaluates them in ONE ``batch_inference`` call on device,
    then samples/steps on host. Recurrent state lives host-side per
    (env, player) and rides along in the same batch.
    """

    def __init__(self, make_env_fn, wrapper, args: Dict[str, Any],
                 n_envs: int = 64):
        self.envs = [make_env_fn(i) for i in range(n_envs)]
        self.wrapper = wrapper
        self.args = args
        self.n_envs = n_envs
        self._moments: List[List[dict]] = [[] for _ in range(n_envs)]
        self._hidden: List[Dict[int, Any]] = [{} for _ in range(n_envs)]
        for i, env in enumerate(self.envs):
            env.reset()
            self._hidden[i] = {p: wrapper.init_hidden() for p in env.players()}

    def _gen_args(self, env) -> Dict[str, Any]:
        return {'role': 'g', 'player': env.players(),
                'model_id': {p: -1 for p in env.players()}}

    def step(self) -> List[dict]:
        """Advance all envs one step; returns episodes finished this step."""
        jobs = []   # (env_idx, player, acting: bool, obs)
        for i, env in enumerate(self.envs):
            turn_players = env.turns()
            observers = env.observers()
            for player in env.players():
                if player not in turn_players + observers:
                    continue
                if (player not in turn_players and not self.args['observation']):
                    continue
                jobs.append((i, player, player in turn_players,
                             env.observation(player)))

        if not jobs:
            return []

        obs_batch, _ = pad_to_bucket([j[3] for j in jobs])
        use_hidden = any(self._hidden[i].get(p) is not None for i, p, _, _ in jobs)
        hidden_batch = None
        if use_hidden:
            hidden_batch, _ = pad_to_bucket(
                [self._hidden[i][p] for i, p, _, _ in jobs])
        outputs = self.wrapper.batch_inference(obs_batch, hidden_batch)
        policies = np.asarray(outputs['policy'])
        values = np.asarray(outputs['value']) if 'value' in outputs else None
        returns_head = np.asarray(outputs['return']) if 'return' in outputs else None
        next_hidden = outputs.get('hidden', None)

        # vectorized categorical sampling for all acting rows at once:
        # mask illegal logits, then Gumbel-max (== sampling from the masked
        # softmax); selected_prob comes from the same masked softmax
        acting_rows = [r for r, j in enumerate(jobs) if j[2]]
        if acting_rows:
            amasks = np.full((len(acting_rows),) + policies.shape[1:], 1e32,
                             np.float32)
            for n, r in enumerate(acting_rows):
                i, player, _, _ = jobs[r]
                amasks[n][self.envs[i].legal_actions(player)] = 0
            masked = policies[acting_rows] - amasks
            probs = softmax(masked)
            gumbel = -np.log(-np.log(
                np.random.random_sample(masked.shape) + 1e-12) + 1e-12)
            sampled = np.argmax(masked + gumbel, axis=-1)
        row_to_sample = {r: n for n, r in enumerate(acting_rows)}

        # scatter results back into per-env moments
        pending: Dict[int, dict] = {}
        for row, (i, player, acting, obs) in enumerate(jobs):
            env = self.envs[i]
            if i not in pending:
                pending[i] = _blank_moment(env.players())
                pending[i]['turn'] = env.turns()
            moment = pending[i]
            moment['observation'][player] = obs
            if values is not None:
                moment['value'][player] = values[row]
            if next_hidden is not None:
                self._hidden[i][player] = map_structure(
                    lambda a: np.asarray(a)[row], next_hidden)
            if acting:
                n = row_to_sample[row]
                action = int(sampled[n])
                moment['selected_prob'][player] = probs[n, action]
                moment['action_mask'][player] = amasks[n]
                moment['action'][player] = action

        finished: List[dict] = []
        for i, moment in pending.items():
            env = self.envs[i]
            err = env.step(moment['action'])
            if err:
                self._reset_slot(i)
                continue
            reward = env.reward()
            for player in env.players():
                moment['reward'][player] = reward.get(player, None)
            self._moments[i].append(moment)

            if env.terminal():
                episode = _finalize_episode(env, self._moments[i], self.args,
                                            self._gen_args(env))
                if episode is not None:
                    finished.append(episode)
                self._reset_slot(i)
        return finished

    def _reset_slot(self, i: int):
        self._moments[i] = []
        self.envs[i].reset()
        self._hidden[i] = {p: self.wrapper.init_hidden()
                           for p in self.envs[i].players()}


class BatchedEvaluator:
    """Vectorized online evaluation: N concurrent matches of the trained
    model (greedy, one rotating seat per match) against configured
    opponents. Opponents may be host-side agents (random / rule-based) or
    model checkpoints ('eval: opponent: [models/5.ckpt]'): every
    model-driven seat — the trained seat and any model opponents — is
    batched across matches, one inference call per distinct model per step.
    The reference evaluates sequentially at B=1 (evaluation.py:159-177) and
    has no vectorized model-vs-model path at all."""

    MAIN = ''   # pool key of the trained model under evaluation

    def __init__(self, make_env_fn, wrapper, args: Dict[str, Any],
                 n_envs: int = 16):
        self.envs = [make_env_fn(i) for i in range(n_envs)]
        self.wrapper = wrapper
        self.args = args
        self.n_envs = n_envs
        self._seat_counter = 0
        self._opponents = (args.get('eval', {}).get('opponent', [])
                          or ['random'])
        self._model_pool: Dict[str, Any] = {self.MAIN: wrapper}
        # preload model opponents NOW: load_model resets the env it probes,
        # which must never happen once matches are in flight
        for spec in self._opponents:
            if self._host_agent(spec) is None:
                self._opponent_model(spec)
        self._slot_state: List[dict] = [None] * n_envs
        for i in range(n_envs):
            self._start_match(i)

    def _host_agent(self, name: str):
        """Host-side opponent for a spec name, or None if it names a model
        (same parser the worker-mode Evaluator uses)."""
        from .evaluation import build_agent
        return build_agent(name, self.envs[0])

    def _opponent_model(self, path: str):
        """Load (once) a checkpoint-file opponent into the model pool."""
        if path not in self._model_pool:
            from .evaluation import load_model
            model = load_model(path, self.envs[0])
            if not hasattr(model, 'batch_inference'):
                raise ValueError(
                    'evaluator model opponents must be .ckpt checkpoints '
                    '(batched inference); %r loads as %s'
                    % (path, type(model).__name__))
            self._model_pool[path] = model
        return self._model_pool[path]

    def _start_match(self, i: int):
        env = self.envs[i]
        env.reset()
        players = env.players()
        seat = players[self._seat_counter % len(players)]
        self._seat_counter += 1
        opponent = random.choice(self._opponents)

        agents: Dict[int, Any] = {}
        model_seats: Dict[int, dict] = {
            seat: {'key': self.MAIN, 'hidden': self.wrapper.init_hidden()}}
        for p in players:
            if p == seat:
                continue
            agent = self._host_agent(opponent)
            if agent is not None:
                agents[p] = agent
            else:
                opp = self._opponent_model(opponent)
                model_seats[p] = {'key': opponent,
                                  'hidden': opp.init_hidden()}
        self._slot_state[i] = {'seat': seat, 'opponent': opponent,
                               'agents': agents, 'model_seats': model_seats}

    def _batched_actions(self, jobs: List[tuple]) -> Dict[tuple, int]:
        """Greedy actions for (env_idx, player) model seats sharing one
        model: a single padded batch_inference call."""
        if not jobs:
            return {}
        key = self._slot_state[jobs[0][0]]['model_seats'][jobs[0][1]]['key']
        model = self._model_pool[key]
        obs_batch, _ = pad_to_bucket(
            [self.envs[i].observation(p) for i, p in jobs])
        seats = [self._slot_state[i]['model_seats'][p] for i, p in jobs]
        hidden_batch = None
        if seats[0]['hidden'] is not None:
            hidden_batch, _ = pad_to_bucket([s['hidden'] for s in seats])
        outputs = model.batch_inference(obs_batch, hidden_batch)
        policies = np.asarray(outputs['policy'])
        next_hidden = outputs.get('hidden', None)

        actions: Dict[tuple, int] = {}
        for row, (i, p) in enumerate(jobs):
            if next_hidden is not None:
                seats[row]['hidden'] = map_structure(
                    lambda a: np.asarray(a)[row], next_hidden)
            legal = self.envs[i].legal_actions(p)
            logits = policies[row]
            actions[(i, p)] = max(legal, key=lambda a: logits[a])  # greedy
        return actions

    def step(self) -> List[dict]:
        """Advance all matches one step; returns finished result records."""
        # group due model seats by model, one batched call per model
        due: Dict[str, List[tuple]] = {}
        for i, env in enumerate(self.envs):
            st = self._slot_state[i]
            for p in env.turns():
                seat_info = st['model_seats'].get(p)
                if seat_info is not None:
                    due.setdefault(seat_info['key'], []).append((i, p))
        model_actions: Dict[tuple, int] = {}
        for jobs in due.values():
            model_actions.update(self._batched_actions(jobs))

        finished = []
        for i, env in enumerate(self.envs):
            st = self._slot_state[i]
            actions = {}
            for p in env.turns():
                if p in st['model_seats']:
                    actions[p] = model_actions.get((i, p))
                else:
                    actions[p] = st['agents'][p].action(env, p)
            err = env.step(actions)
            if err:
                self._start_match(i)
                continue
            if env.terminal():
                outcome = env.outcome()
                eval_args = {'role': 'e', 'player': [st['seat']],
                             'model_id': {p: (-1 if p != st['seat'] else 0)
                                          for p in env.players()}}
                finished.append({'args': eval_args,
                                 'opponent': st['opponent'],
                                 'result': outcome})
                self._start_match(i)
        return finished
