"""EpisodeSpool: the training plane's episode write-ahead log.

Every episode the learner ADMITS (it passed the TaskLedger duplicate
screen and is about to be counted + fed to training) is first appended to
a segmented on-disk spool under ``model_dir/spool/`` — one CRC-framed
record (utils/fs.py framed-record vocabulary) per episode, written with a
single ``O_APPEND`` write so a SIGKILL can tear at most the final record.
A restarted learner replays every spooled episode at or past the newest
checkpoint's consumption horizon back into the buffer before serving the
fleet, so learner death costs zero admitted episodes — the training-side
twin of the serving fleet's zero-loss replay (docs/serving.md).

Anatomy:

* segments are ``%08d.wal`` files that rotate once they exceed
  ``segment_mb`` — rotation fsyncs and closes the old segment, so only
  the LIVE segment can ever hold a torn tail;
* each record's payload is ``connection.pack({'idx': N, 'episode': ...})``
  — ``idx`` is the learner's monotonic admission index, which makes
  recovery horizons and GC exact without a separate index file. Streaming
  ingest (docs/large_scale_training.md "Streaming ingest") reuses the same
  framing with a ``{'idx': N, 'chunk': ...}`` payload — partial-episode
  window chunks land here BEFORE the ledger journals their delivery, so
  SIGKILL recovery and duplicate screening extend to in-flight episodes;
* recovery (``recover``) scans segments in order, truncates a torn tail in
  place (os.truncate to the last good frame boundary), and yields the
  episodes with ``idx >= min_idx`` (chunk records ride the same scan; the
  learner screens them against the ledger's reassembly book — open
  assemblies hold the GC horizon back to their first spooled chunk, so a
  restart can always rebuild every partially-delivered episode);
* GC (``gc``) deletes closed segments whose newest record fell behind the
  checkpoint consumption horizon, always retaining the newest
  ``keep_segments`` closed segments as cushion — disk stays bounded.

Appends are NOT per-record fsynced: a process SIGKILL cannot lose bytes
the kernel accepted, and the fsync-per-episode cost would blow the ≤2%
ingest-bench budget. Segment rotation and ``close`` fsync, so the
machine-crash exposure is bounded to the live segment (documented in
docs/large_scale_training.md).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from . import telemetry
from .utils.fs import append_framed_record, open_append, read_framed_records

SEGMENT_SUFFIX = '.wal'


def spool_dir(model_dir: str) -> str:
    return os.path.join(model_dir, 'spool')


class EpisodeSpool:
    """Segmented append-only episode WAL under ``model_dir/spool/``.

    Single-threaded by design: the learner's server loop is the only
    writer (append/gc run inline with admission and the epoch sync), and
    recovery runs before the fleet is served.
    """

    def __init__(self, model_dir: str, segment_mb: float = 64.0,
                 keep_segments: int = 2):
        self.root = spool_dir(model_dir)
        self.segment_bytes = max(1, int(float(segment_mb) * 1024 * 1024))
        self.keep_segments = max(0, int(keep_segments))
        self._fd: Optional[int] = None
        self._live: Optional[str] = None      # live segment path
        self._live_bytes = 0
        self._seq = 0                         # next segment number
        self._max_idx: Dict[str, int] = {}    # closed segment -> newest idx
        self._live_max_idx = -1
        self._m_bytes = telemetry.counter('spool_bytes_total')
        self._m_segments = telemetry.gauge('spool_segments')
        self._m_recovered = telemetry.counter('spool_recovered_episodes_total')
        self._m_gc = telemetry.counter('spool_gc_segments_total')

    # -- write path --------------------------------------------------------

    def _segments(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.root)
                           if n.endswith(SEGMENT_SUFFIX))
        except OSError:
            return []
        return [os.path.join(self.root, n) for n in names]

    def _open_segment(self):
        os.makedirs(self.root, exist_ok=True)
        path = os.path.join(self.root, '%08d%s' % (self._seq, SEGMENT_SUFFIX))
        self._seq += 1
        self._fd = open_append(path)
        self._live = path
        self._live_bytes = 0
        self._live_max_idx = -1
        self._m_segments.set(len(self._segments()))

    def _close_segment(self, fsync: bool = True):
        if self._fd is None:
            return
        if fsync:
            try:
                os.fsync(self._fd)
            except OSError:
                pass
        os.close(self._fd)
        if self._live is not None and self._live_max_idx >= 0:
            self._max_idx[self._live] = self._live_max_idx
        self._fd = None
        self._live = None

    def append(self, idx: int, payload: bytes) -> int:
        """Spool one admitted episode (already connection.pack-ed, idx
        included in the payload by the caller); returns bytes written."""
        if self._fd is None:
            self._open_segment()
        n = append_framed_record(self._fd, payload)
        self._live_bytes += n
        self._live_max_idx = max(self._live_max_idx, int(idx))
        self._m_bytes.inc(n)
        if self._live_bytes >= self.segment_bytes:
            self._close_segment()
        return n

    # -- recovery ----------------------------------------------------------

    def recover(self, min_idx: int, unpack) -> List[dict]:
        """Replay spooled records with ``idx >= min_idx`` in admission
        order, truncating any torn tail in place. ``unpack`` decodes one
        payload (connection.unpack); undecodable records are skipped —
        the frame CRC already screened corruption, so a decode failure
        means a format change, not bit rot."""
        out = []
        for path in self._segments():
            records, valid_bytes, torn = read_framed_records(path)
            if torn:
                os.truncate(path, valid_bytes)
            seg_max = -1
            for payload in records:
                try:
                    rec = unpack(payload)
                    idx = int(rec['idx'])
                except Exception:
                    continue
                seg_max = max(seg_max, idx)
                if idx >= int(min_idx):
                    out.append(rec)
            if seg_max >= 0:
                self._max_idx[path] = seg_max
        out.sort(key=lambda rec: rec['idx'])
        if out:
            self._m_recovered.inc(len(out))
        # appends resume in a FRESH segment past every existing one, so a
        # double restart never interleaves generations within a segment
        existing = self._segments()
        if existing:
            tail = os.path.basename(existing[-1])[:-len(SEGMENT_SUFFIX)]
            try:
                self._seq = int(tail) + 1
            except ValueError:
                self._seq = len(existing)
        self._m_segments.set(len(existing))
        return out

    # -- GC ----------------------------------------------------------------

    def gc(self, horizon: int) -> int:
        """Delete closed segments whose episodes all fell behind the
        checkpoint consumption ``horizon`` (every idx < horizon), keeping
        the newest ``keep_segments`` closed segments regardless; returns
        the number of segments removed."""
        closed = [p for p in self._segments() if p != self._live]
        victims = [p for p in closed
                   if self._max_idx.get(p, horizon) < int(horizon)]
        if self.keep_segments:
            victims = victims[:-self.keep_segments] or []
        removed = 0
        for path in victims:
            try:
                os.unlink(path)
            except OSError:
                continue
            self._max_idx.pop(path, None)
            removed += 1
        if removed:
            self._m_gc.inc(removed)
        self._m_segments.set(len(self._segments()))
        return removed

    def close(self):
        self._close_segment()
