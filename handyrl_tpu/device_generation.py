"""Device-resident self-play: the entire act/sample/step loop inside one jit.

The BatchedGenerator (generation.py) still crosses the host boundary once
per ply (observations up, policies down). For environments implemented as
pure JAX functions (envs/jax_tictactoe.py, envs/jax_hungry_geese.py), this
engine runs K plies of N environments as ONE compiled program — inference,
legal masking, categorical sampling, transition, termination detection and
auto-reset all stay in HBM; the host receives a (K, N, ...) trajectory chunk
and only splices completed episodes into the standard episode records (the
same wire/batch format as every other generator, generation.py:84-91 in the
reference).

Two env protocols:
  * turn-based (jax_tictactoe): observe -> (N, ...) side-to-move view,
    step((N,) actions), turn -> (N,) acting seat;
  * simultaneous (SIMULTANEOUS=True, jax_hungry_geese): observe ->
    (N, P, ...) per-player views, step((N, P) actions), acting -> (N, P)
    mask of players that act this ply.

This is the throughput ceiling path: on a TPU the per-ply cost is one fused
program dispatch regardless of N.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from . import telemetry
from .generation import (Generator, _blank_moment, _finalize_episode,
                         bucketed_inference, build_chunk, masked_sample,
                         pad_to_bucket, sample_seed, seed_env_rng)
from .ops.batch import compress_moments
from .utils.tree import map_structure


def obs_leading(obs) -> int:
    """Leading (env) dimension of an observation pytree."""
    return jax.tree_util.tree_leaves(obs)[0].shape[0]


def _blank(players):
    return {key: {p: None for p in players} for key in
            ('observation', 'selected_prob', 'action_mask', 'action',
             'value', 'reward', 'return')}


def _ply_inference(env_mod, apply_fn, recurrent, simultaneous,
                   params, state, hidden):
    """Shared per-ply plumbing for the device rollout engines (generation
    and evaluation): observe, run the net — with the recurrent hidden
    gather/scatter for turn-based envs and the (N, P)->(N*P) fold for
    simultaneous ones — and build the illegal-action mask.

    Returns (obs, logits, amask, hidden, out): logits/amask are (N, P, A)
    for simultaneous envs, (N, A) turn-based; ``out`` is the raw model
    output dict with 'hidden' already popped.
    """
    obs = env_mod.observe(state)
    legal = env_mod.legal_mask(state)
    amask = (1.0 - legal) * 1e32
    if simultaneous:
        N, P = obs.shape[:2]
        flat = obs.reshape((N * P,) + obs.shape[2:])
        if recurrent:
            # every player's hidden advances each ply (they all observe);
            # fold (N, P) into the batch dim
            h_in = jax.tree_util.tree_map(
                lambda h: h.reshape((N * P,) + h.shape[2:]), hidden)
            out = dict(apply_fn(params, flat, h_in))
            nh = out.pop('hidden')
            hidden = jax.tree_util.tree_map(
                lambda h: h.reshape((N, P) + h.shape[1:]), nh)
        else:
            out = dict(apply_fn(params, flat, None))
        logits = out['policy'].reshape(N, P, -1) - amask
    else:
        if recurrent:
            # gather the acting player's hidden slot, run the net, scatter
            # the new state back (mirrors the omask-gated training carry)
            rows = jnp.arange(obs_leading(obs))
            player = env_mod.turn(state)
            h_in = jax.tree_util.tree_map(
                lambda h: h[rows, player], hidden)
            out = dict(apply_fn(params, obs, h_in))
            nh = out.pop('hidden')
            hidden = jax.tree_util.tree_map(
                lambda h, x: h.at[rows, player].set(x), hidden, nh)
        else:
            out = dict(apply_fn(params, obs, None))
        logits = out['policy'] - amask
    return obs, logits, amask, hidden, out


def _reset_hidden_where_done(hidden, done):
    """Fresh episodes start with zero recurrent state."""
    return jax.tree_util.tree_map(
        lambda h: jnp.where(done.reshape((-1,) + (1,) * (h.ndim - 1)),
                            jnp.zeros_like(h), h), hidden)


class _RecordPacker:
    """Flatten a records pytree into ONE f32 device array and back.

    On a tunneled TPU each distinct array fetch pays a full host round trip
    (~140 ms measured) while bandwidth is cheap, so the splice path packs
    every record leaf into a single transfer instead of one per leaf. The
    pack runs as its own tiny jitted program (async dispatch, ~4 ms);
    unpack restores shapes/dtypes exactly (int/bool values are small enough
    to round-trip through f32 losslessly)."""

    def __init__(self, records):
        leaves, self.treedef = jax.tree_util.tree_flatten(records)
        self.shapes = [l.shape for l in leaves]
        self.dtypes = [l.dtype for l in leaves]
        self._fn = jax.jit(lambda ls: jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1) for l in ls]))

    def pack(self, records):
        return self._fn(jax.tree_util.tree_leaves(records))

    def unpack(self, flat):
        flat = np.asarray(flat)   # the one transfer
        out, pos = [], 0
        for shape, dtype in zip(self.shapes, self.dtypes):
            n = int(np.prod(shape)) if shape else 1
            out.append(flat[pos:pos + n].reshape(shape).astype(dtype))
            pos += n
        return jax.tree_util.tree_unflatten(self.treedef, out)


# NOTE on observation=True for turn-based envs (the geister-device config):
# the reference generator runs inference ONLY for ``turn_players +
# observers`` each ply (reference generation.py:37-41), and no reference env
# ever overrides ``observers()`` (it defaults to [] — reference
# environment.py:84); the eval-side Agent likewise advances its hidden only
# on its own turns (reference evaluation.py:97-101). So even with
# observation=True, exactly the acting seat observes per ply — the flag only
# widens the BATCH layout to the full player axis (reference train.py:65-68)
# with observation_mask marking the acting seat. The acting-seat-only
# recording below is therefore already reference-exact; an earlier
# "observe-all" helper that ran inference for every seat per ply was removed
# as anti-parity (tests/test_geister_device_parity.py pins the semantics).


def _init_rollout_engine(engine, env_mod, wrapper, n_envs: int, seed: int):
    """Shared env/model bootstrapping for the device rollout engines: env
    state vector, PRNG key, simultaneous/recurrent detection, and the
    per-env recurrent hidden pytree."""
    engine.env_mod = env_mod
    engine.wrapper = wrapper
    engine.n_envs = n_envs
    engine.simultaneous = bool(getattr(env_mod, 'SIMULTANEOUS', False))
    try:
        engine.state = env_mod.init_state(n_envs, seed)
    except TypeError:
        engine.state = env_mod.init_state(n_envs)
    engine.rng = jax.random.PRNGKey(seed)
    engine.recurrent = hasattr(wrapper.module, 'init_hidden')
    engine.hidden = (wrapper.module.init_hidden(
        (n_envs, env_mod.NUM_PLAYERS)) if engine.recurrent else None)


def make_gen_body(env_mod, apply_fn, recurrent: bool, simultaneous: bool):
    """The one self-play ply: inference, sampling, transition, record.

    Shared between DeviceGenerator's standalone rollout program and the
    fused generate+ingest+train pipeline (ops/fused_pipeline.py) so the
    recorded trajectory semantics have exactly one definition.
    Carry is (env_state, hidden, rng); emits the per-ply record dict.

    The ply body is (re)defined inside ``rollout_chunk`` so it closes over
    the CURRENT trace's params: lax.scan caches traced bodies by function
    identity, and a body shared across traces would smuggle one trace's
    param tracers into the next (UnexpectedTracerError).
    """
    def rollout_chunk(params, state, hidden, rng, chunk_steps: int):
        def body(carry, _):
            state, hidden, rng = carry
            obs, logits, amask, hidden, out = _ply_inference(
                env_mod, apply_fn, recurrent, simultaneous,
                params, state, hidden)
            rng, key = jax.random.split(rng)
            actions = jax.random.categorical(key, logits)
            probs = jax.nn.softmax(logits, axis=-1)
            sel = jnp.take_along_axis(probs, actions[..., None],
                                      axis=-1)[..., 0]
            if simultaneous:
                N, P = obs.shape[:2]
                value = out.get('value')
                if value is not None:
                    value = value.reshape(N, P, -1)
                act_mask = env_mod.acting(state)           # (N, P)
                nstate = env_mod.step(state, actions)
                done = env_mod.terminal(nstate)
                record = {'obs': obs, 'action': actions, 'prob': sel,
                          'amask': amask, 'value': value,
                          'acting': act_mask, 'done': done,
                          'outcome': env_mod.outcome(nstate)}
            else:
                player = env_mod.turn(state)
                nstate = env_mod.step(state, actions)
                done = env_mod.terminal(nstate)
                record = {'obs': obs, 'action': actions, 'prob': sel,
                          'amask': amask, 'value': out.get('value'),
                          'player': player, 'done': done,
                          'outcome': env_mod.outcome(nstate)}
            if hasattr(env_mod, 'rewards'):
                record['reward'] = env_mod.rewards(nstate)   # (N, P)
            nstate = env_mod.auto_reset(nstate, done)
            if recurrent:
                hidden = _reset_hidden_where_done(hidden, done)
            return (nstate, hidden, rng), record

        (state, hidden, rng), records = jax.lax.scan(
            body, (state, hidden, rng), None, length=chunk_steps)
        return state, hidden, rng, dict(records)

    return rollout_chunk


class DeviceGenerator:
    """Runs chunks of device-resident self-play for a pure-JAX env module.

    Dispatch is PIPELINED one chunk deep: each ``step_chunk*`` call enqueues
    the NEXT rollout program before fetching the previous chunk's results,
    so the host-visible round-trip latency (dominant on a tunneled TPU)
    overlaps with device execution of the following chunk. Callers see a
    one-chunk delay in episode accounting, nothing else.
    """

    pipelined = True    # step_chunk* returns the PREVIOUS dispatch's chunk

    def __init__(self, env_mod, wrapper, args: Dict[str, Any],
                 n_envs: int = 256, chunk_steps: int = 16, seed: int = 0):
        self.args = args
        self.chunk_steps = chunk_steps
        _init_rollout_engine(self, env_mod, wrapper, n_envs, seed)
        self._partials: List[List[dict]] = [[] for _ in range(n_envs)]
        self._pending = None
        self._acct_pack = None
        self._full_pack = None
        self.dispatches = 0

        rollout_chunk = make_gen_body(env_mod, wrapper.module.apply,
                                      self.recurrent, self.simultaneous)

        @jax.jit
        def rollout(params, state, hidden, rng):
            return rollout_chunk(params, state, hidden, rng, chunk_steps)

        self._rollout = rollout

    def _dispatch(self):
        self.state, self.hidden, self.rng, records = self._rollout(
            self.wrapper.params, self.state, self.hidden, self.rng)
        self.dispatches += 1
        return dict(records)

    def _dispatch_acct(self):
        """Dispatch rollout + the tiny done/outcome pack (one fetchable)."""
        records = self._dispatch()
        if self._acct_pack is None:
            self._acct_pack = _RecordPacker(
                {'done': records['done'], 'outcome': records['outcome']})
        return records, self._acct_pack.pack(
            {'done': records['done'], 'outcome': records['outcome']})

    def step_chunk_records(self):
        """Run one compiled chunk, keeping the trajectory ON DEVICE.

        For the device-ingest pipeline (ops/device_windows.py): returns the
        raw records pytree (device arrays, leading axes (K, N)) plus host
        copies of ONLY the tiny done/outcome arrays for episode accounting,
        fetched as ONE packed array (a fetch costs a tunnel round trip).
        The heavy leaves (observations, masks) never reach the host.
        """
        if self._pending is None:
            self._pending = self._dispatch_acct()
        (records, pack), self._pending = self._pending, self._dispatch_acct()
        acct = self._acct_pack.unpack(pack)
        return records, acct['done'], acct['outcome']

    def drain_records(self):
        """Fetch the in-flight speculative chunk at loop shutdown (device-
        ingest mode); returns (records, done, outcome) or None."""
        if self._pending is None:
            return None
        (records, pack), self._pending = self._pending, None
        acct = self._acct_pack.unpack(pack)
        return records, acct['done'], acct['outcome']

    # -- host-side episode splicing ---------------------------------------
    def _dispatch_full(self):
        """Dispatch rollout + the full-record pack (splice mode fetches
        EVERY leaf; packed, that is one transfer instead of one per leaf)."""
        records = self._dispatch()
        if self._full_pack is None:
            self._full_pack = _RecordPacker(records)
        return self._full_pack.pack(records)

    def step_chunk(self) -> List[dict]:
        """Run one compiled chunk; return episodes completed within it."""
        if self._pending is None:
            self._pending = self._dispatch_full()
        pack, self._pending = self._pending, self._dispatch_full()
        return self._splice(self._full_pack.unpack(pack))

    def drain_episodes(self) -> List[dict]:
        """Splice the in-flight speculative chunk at loop shutdown."""
        if self._pending is None:
            return []
        pack, self._pending = self._pending, None
        return self._splice(self._full_pack.unpack(pack))

    def _splice(self, rec) -> List[dict]:
        players = list(range(self.env_mod.NUM_PLAYERS))
        episodes: List[dict] = []
        for k in range(self.chunk_steps):
            for i in range(self.n_envs):
                if self.simultaneous:
                    moment = self._moment_simultaneous(rec, k, i, players)
                else:
                    moment = self._moment_turn_based(rec, k, i, players)
                self._partials[i].append(moment)
                if rec['done'][k, i]:
                    episodes.append(self._finalize(i, rec, k, players))
        return episodes

    def _moment_turn_based(self, rec, k, i, players):
        player = int(rec['player'][k, i])
        moment = _blank(players)
        moment['observation'][player] = map_structure(
            lambda v: v[k, i], rec['obs'])
        moment['selected_prob'][player] = float(rec['prob'][k, i])
        moment['action_mask'][player] = rec['amask'][k, i]
        moment['action'][player] = int(rec['action'][k, i])
        if rec.get('value') is not None:
            moment['value'][player] = rec['value'][k, i]
        moment['reward'] = self._rewards(rec, k, i, players)
        moment['turn'] = [player]
        return moment

    def _rewards(self, rec, k, i, players):
        if rec.get('reward') is None:
            return {p: None for p in players}
        return {p: float(rec['reward'][k, i, p]) for p in players}

    def _moment_simultaneous(self, rec, k, i, players):
        moment = _blank(players)
        turn_players = []
        for p in players:
            if not rec['acting'][k, i, p]:
                continue
            turn_players.append(p)
            moment['observation'][p] = map_structure(
                lambda v: v[k, i, p], rec['obs'])
            moment['selected_prob'][p] = float(rec['prob'][k, i, p])
            moment['action_mask'][p] = rec['amask'][k, i, p]
            moment['action'][p] = int(rec['action'][k, i, p])
            if rec.get('value') is not None:
                moment['value'][p] = rec['value'][k, i, p]
        moment['reward'] = self._rewards(rec, k, i, players)
        moment['turn'] = turn_players
        return moment

    def _finalize(self, i, rec, k, players):
        moments = self._partials[i]
        self._partials[i] = []
        outcome = {p: float(rec['outcome'][k, i, p]) for p in players}
        for p in players:
            ret = 0.0
            for t in range(len(moments) - 1, -1, -1):
                ret = (moments[t]['reward'][p] or 0) + self.args['gamma'] * ret
                moments[t]['return'][p] = ret
        return {
            'args': {'role': 'g', 'player': players,
                     'model_id': {p: -1 for p in players}},
            'steps': len(moments),
            'outcome': outcome,
            'moment': compress_moments(moments, self.args['compress_steps']),
        }


class DeviceEvaluator:
    """Device-resident online evaluation vs a roster of opponents.

    The host BatchedEvaluator pays one inference dispatch per ply of every
    match; on a dispatch-latency-heavy backend that makes evaluation the
    dominant cost of the epoch loop (it needs ~10x more dispatches than
    chunked device generation for the same ply count). When every opponent
    is 'random' or a checkpoint path (league play) and the env has a
    pure-JAX twin, the whole match runs on device instead: envs split into
    one contiguous block per opponent, one rotating seat per env plays the
    trained model greedily (the same temperature-0 policy as
    BatchedEvaluator / reference agent.py Agent), the other seats either
    sample uniformly ('random') or play their checkpoint's greedy policy —
    inferenced inside the same compiled ply — and the host receives only
    (done, outcome, seat) per ply, K plies of N matches per dispatch.
    'rulebase' also runs on device when the env twin vectorizes its agent
    (``greedy_action``, e.g. jax_hungry_geese); otherwise it stays on the
    host evaluator (train.py device_eval_ok). Checkpoint opponents for
    recurrent nets carry their own hidden tree through the scan, so e.g.
    Geister league eval keeps the one-dispatch-per-chunk budget.
    """

    def __init__(self, env_mod, wrapper, args: Dict[str, Any],
                 n_envs: int = 64, chunk_steps: int = 16, seed: int = 77,
                 mesh=None, opponents=None):
        self.args = args
        self.chunk_steps = chunk_steps
        _init_rollout_engine(self, env_mod, wrapper, n_envs, seed)
        # one evaluated seat per env, rotated on every reset so first/second
        # (and every goose slot) are balanced like evaluate_mp's scheduler
        self.seat = jnp.arange(n_envs, dtype=jnp.int32) % env_mod.NUM_PLAYERS

        # opponent roster: envs are split into one contiguous block per
        # opponent (league play stays one-dispatch-per-chunk — the round-2
        # device evaluator silently fell back to the per-ply host evaluator
        # for anything but 'random'). 'random' plays uniform; a checkpoint
        # path plays its own greedy policy, inferenced inside the same
        # compiled ply (recurrent checkpoints carry opp_hidden, below).
        self.opponents = [str(o) for o in (opponents or ['random'])]
        assert n_envs >= len(self.opponents), \
            'need at least one eval env per opponent'
        self._opp_params: List[Any] = []
        bounds = np.linspace(0, n_envs, len(self.opponents) + 1).astype(int)
        self._opp_bounds = [(int(a), int(b), name)
                            for a, b, name in zip(bounds[:-1], bounds[1:],
                                                  self.opponents)]
        self._env_opp = np.empty(n_envs, dtype=object)
        for a, b, name in self._opp_bounds:
            self._env_opp[a:b] = name
        if 'rulebase' in self.opponents:
            assert hasattr(env_mod, 'greedy_action'), \
                'device rulebase eval needs the env twin to vectorize it'
        model_opps = [o for o in self.opponents
                      if o not in ('random', 'rulebase')]
        if model_opps:
            # the trained wrapper's params are the ready-made template for
            # msgpack deserialization (same module, same tree)
            from flax import serialization
            for path in model_opps:
                with open(path, 'rb') as f:
                    self._opp_params.append(jax.device_put(
                        serialization.from_bytes(wrapper.params, f.read())))
        # recurrent checkpoint opponents carry their own hidden tree through
        # the scan (gathered/scattered exactly like the main model's); the
        # env blocks are disjoint so ONE tree serves every opponent slice
        self.opp_hidden = (wrapper.module.init_hidden(
            (n_envs, env_mod.NUM_PLAYERS))
            if self.recurrent and model_opps else None)
        if mesh is not None:
            # eval envs sharded over 'data' alongside the fused trainer
            # (params arrive replicated); the plain-jit rollout partitions
            # under GSPMD — eval is embarrassingly parallel over envs
            from .parallel.mesh import replicated_sharding, shard_batch
            self.state = shard_batch(mesh, self.state)
            if self.hidden is not None:
                self.hidden = shard_batch(mesh, self.hidden)
            if self.opp_hidden is not None:
                self.opp_hidden = shard_batch(mesh, self.opp_hidden)
            self.seat = shard_batch(mesh, self.seat)
            self.rng = jax.device_put(self.rng, replicated_sharding(mesh))
        self._pending = None
        self._pack = None
        self.dispatches = 0

        apply_fn = wrapper.module.apply
        simultaneous = self.simultaneous
        recurrent = self.recurrent

        opp_bounds = self._opp_bounds
        model_ix = {name: i for i, name in enumerate(
            o for o in self.opponents if o not in ('random', 'rulebase'))}
        any_rulebase = any(name == 'rulebase' for _, _, name in opp_bounds)

        @jax.jit
        def rollout(params, opp_params, state, hidden, opp_hidden, seat,
                    rng):
            def body(carry, _):
                state, hidden, opp_hidden, seat, rng = carry
                obs, logits, amask, hidden, _ = _ply_inference(
                    env_mod, apply_fn, recurrent, simultaneous,
                    params, state, hidden)
                greedy = jnp.argmax(logits, axis=-1)
                rng, key = jax.random.split(rng)
                opp_act = jax.random.categorical(key, -amask)
                if any_rulebase:   # the env's vectorized rulebase agent
                    rng, rkey = jax.random.split(rng)
                    rule_act = env_mod.greedy_action(state, rkey)
                # opponent blocks: checkpoint policies (greedy) and the
                # rulebase agent, traced into this one program (static
                # slices). Recurrent checkpoints gather/scatter their own
                # hidden tree the same way _ply_inference does the main
                # model's — the blocks are disjoint slices of opp_hidden.
                for a, b, name in opp_bounds:
                    if name == 'random' or a == b:
                        continue
                    if name == 'rulebase':
                        opp_act = opp_act.at[a:b].set(rule_act[a:b])
                        continue
                    pg = opp_params[model_ix[name]]
                    # observations may be a pytree (e.g. geister's
                    # {'scalar', 'board'}): slice every leaf
                    o = jax.tree_util.tree_map(lambda x: x[a:b], obs)
                    if simultaneous:
                        No, Po = jax.tree_util.tree_leaves(o)[0].shape[:2]
                        flat = jax.tree_util.tree_map(
                            lambda x: x.reshape((No * Po,) + x.shape[2:]),
                            o)
                        if recurrent:
                            h_in = jax.tree_util.tree_map(
                                lambda h: h[a:b].reshape((No * Po,)
                                                         + h.shape[2:]),
                                opp_hidden)
                            out_o = dict(apply_fn(pg, flat, h_in))
                            nh = out_o.pop('hidden')
                            opp_hidden = jax.tree_util.tree_map(
                                lambda h, x: h.at[a:b].set(
                                    x.reshape((No, Po) + x.shape[1:])),
                                opp_hidden, nh)
                        else:
                            out_o = dict(apply_fn(pg, flat, None))
                        lg = (out_o['policy'].reshape(No, Po, -1)
                              - amask[a:b])
                    else:
                        if recurrent:
                            rows = jnp.arange(b - a)
                            pl = env_mod.turn(state)[a:b]
                            h_in = jax.tree_util.tree_map(
                                lambda h: h[a:b][rows, pl], opp_hidden)
                            out_o = dict(apply_fn(pg, o, h_in))
                            nh = out_o.pop('hidden')
                            opp_hidden = jax.tree_util.tree_map(
                                lambda h, x: h.at[a + rows, pl].set(x),
                                opp_hidden, nh)
                        else:
                            out_o = dict(apply_fn(pg, o, None))
                        lg = out_o['policy'] - amask[a:b]
                    opp_act = opp_act.at[a:b].set(jnp.argmax(lg, axis=-1))
                if simultaneous:
                    P2 = logits.shape[1]
                    is_main = (jnp.arange(P2)[None, :] == seat[:, None])
                else:
                    is_main = env_mod.turn(state) == seat
                actions = jnp.where(is_main, greedy, opp_act)
                nstate = env_mod.step(state, actions)
                done = env_mod.terminal(nstate)
                record = {'done': done, 'seat': seat,
                          'outcome': env_mod.outcome(nstate)}
                nstate = env_mod.auto_reset(nstate, done)
                seat = jnp.where(done,
                                 (seat + 1) % env_mod.NUM_PLAYERS, seat)
                if recurrent:
                    hidden = _reset_hidden_where_done(hidden, done)
                    if opp_hidden is not None:
                        opp_hidden = _reset_hidden_where_done(
                            opp_hidden, done)
                return (nstate, hidden, opp_hidden, seat, rng), record

            (state, hidden, opp_hidden, seat, rng), records = jax.lax.scan(
                body, (state, hidden, opp_hidden, seat, rng), None,
                length=chunk_steps)
            return state, hidden, opp_hidden, seat, rng, records

        self._rollout = rollout

    # results arrive one dispatch late: Learner.feed_results must use the
    # dispatch-time epoch for attribution
    pipelined = True

    def _dispatch(self):
        """Dispatch a chunk + its packed (done, seat, outcome) fetchable."""
        (self.state, self.hidden, self.opp_hidden, self.seat, self.rng,
         records) = \
            self._rollout(self.wrapper.params, tuple(self._opp_params),
                          self.state, self.hidden, self.opp_hidden,
                          self.seat, self.rng)
        self.dispatches += 1
        records = dict(records)
        if self._pack is None:
            self._pack = _RecordPacker(records)
        return self._pack.pack(records)

    def step(self) -> List[dict]:
        """One compiled chunk; returns finished eval result records (the
        same shape Learner.feed_results consumes from BatchedEvaluator).
        Pipelined one chunk deep like DeviceGenerator: the next chunk is
        enqueued before the previous one's outcome arrays are fetched (as
        ONE packed array — a fetch costs a tunnel round trip)."""
        if self._pending is None:
            self._pending = self._dispatch()
        pack, self._pending = self._pending, self._dispatch()
        return self._collect(self._pack.unpack(pack))

    def drain(self) -> List[dict]:
        """Collect the in-flight speculative chunk at loop shutdown."""
        if self._pending is None:
            return []
        pack, self._pending = self._pending, None
        return self._collect(self._pack.unpack(pack))

    def _collect(self, rec) -> List[dict]:
        done, seats, outcomes = rec['done'], rec['seat'], rec['outcome']
        players = list(range(self.env_mod.NUM_PLAYERS))
        results: List[dict] = []
        for k, i in zip(*np.nonzero(done)):
            seat = int(seats[k, i])
            results.append({
                'args': {'role': 'e', 'player': [seat],
                         'model_id': {p: (0 if p == seat else -1)
                                      for p in players}},
                'opponent': self._env_opp[i],
                'result': {p: float(outcomes[k, i, p]) for p in players},
            })
        return results


# ---------------------------------------------------------------------------
# device actor backend (generation.backend: device): a gather that OWNS an
# accelerator serves ledger tasks with fused on-device rollouts instead of a
# worker fleet. One compiled program plays every pairing the learner
# stamps — self-play, league PFSP opponents, rating matches — by stacking
# up to ``device_actor_slots`` parameter sets as pytree leaves and
# selecting each seat's logits by a per-(lane, seat) slot index, so a new
# opponent mix is a new params UPLOAD, never a retrace.

# per-seat policies inside the compiled ply (device arrays, not python):
#   SAMPLE  — sample the seat's slot policy (generation 'g' seats)
#   GREEDY  — argmax the seat's slot policy (evaluation model seats,
#             reference agent.py Agent at temperature 0)
#   UNIFORM — uniform over legal actions (mid-0 / 'random' seats; matches
#             RandomModel + masked_sample over a zero policy)
#   FIRST   — first legal action (Agent(RandomModel): argmax of zeros-mask)
#   RULEBASE— the env twin's vectorized ``greedy_action`` heuristic
MODE_SAMPLE, MODE_GREEDY, MODE_UNIFORM, MODE_FIRST, MODE_RULEBASE = range(5)


class Divergence(Exception):
    """A device-played action disagrees with the host sampling contract
    (float-boundary collision between the f32 on-device inverse-CDF and the
    f64 host cumsum); the episode reruns on the host path."""


def resolve_record_mode(env_mod, recurrent: bool, requested: str = '') -> str:
    """Resolve the device-actor record mode for an env twin.

    'strict' — device episodes are verified against the host sampling
    contract at splice time and uploaded BYTE-IDENTICAL to worker/engine
    records (divergent lanes rerun on the host); requires the env to be
    deterministic given the action sequence (``RNG_COMPAT == 'strict'``),
    turn-based, and the model non-recurrent (a hidden-state chain cannot be
    recomputed as one batched call). 'device' — episodes are spliced from
    the on-device trajectory and stamped ``record_version: 1``; never
    silently divergent. '' auto-selects strict whenever legal."""
    compat = str(getattr(env_mod, 'RNG_COMPAT', 'device'))
    simultaneous = bool(getattr(env_mod, 'SIMULTANEOUS', False))
    strict_ok = compat == 'strict' and not recurrent and not simultaneous
    if requested == 'strict':
        if not strict_ok:
            raise ValueError(
                'device_actor_record=strict requires a turn-based env twin '
                "with RNG_COMPAT == 'strict' and a non-recurrent model "
                '(got compat=%r, recurrent=%s, simultaneous=%s)'
                % (compat, recurrent, simultaneous))
        return 'strict'
    if requested == 'device':
        return 'device'
    return 'strict' if strict_ok else 'device'


def _tree_where(cond, a, b):
    """Per-lane select over a state pytree (cond broadcast to each leaf)."""
    return jax.tree_util.tree_map(
        lambda x, y: jnp.where(
            cond.reshape((-1,) + (1,) * (x.ndim - 1)), x, y), a, b)


def _u_pick(weights, legal, u):
    """Inverse-CDF draw matching generation.masked_sample's searchsorted:
    the first legal action whose inclusive cumulative weight exceeds
    ``u * total``; the last legal action when rounding pushes u past the
    end. Rows whose weights are all zero (frozen lanes) fall through to
    the last-legal clamp and are discarded by the caller's live mask."""
    legalb = legal > 0
    c = jnp.cumsum(weights * legal, axis=-1)
    total = c[:, -1:]
    cond = (c > u[:, None] * total) & legalb
    acts = legal.shape[-1]
    last_legal = (acts - 1) - jnp.argmax(legalb[:, ::-1], axis=-1)
    return jnp.where(cond.any(axis=-1), jnp.argmax(cond, axis=-1),
                     last_legal).astype(jnp.int32)


class DeviceActorEngine:
    """Fused Anakin-style rollout engine behind the gather task loop.

    ``run_block`` takes a list of server-stamped ledger tasks ('g' episode
    and 'e' evaluation assignments, one lane each), plays them ALL inside
    chunked invocations of ONE jitted scan — inference for every slot's
    params, per-seat action modes, transition, termination — and splices
    the finished lanes into standard upload payloads. Lanes freeze when
    their episode ends (block-synchronous; no auto-reset), so a task's
    record is exactly one episode, attributable to its task_id.

    Tasks the program cannot express (unknown opponents, slot overflow
    beyond the compiled stack, missing sample keys in strict mode) are
    returned for the caller's host fallback instead of forcing a retrace.
    """

    def __init__(self, env_mod, vault, host_env, args: Dict[str, Any],
                 n_envs: int = 64, chunk_steps: int = 16, slots: int = 2,
                 record_mode: str = '', seed: int = 0):
        self.args = args
        self.vault = vault
        self.host_env = host_env
        self.n_envs = int(n_envs)
        self.chunk_steps = int(chunk_steps)
        self.slots = max(1, int(slots))
        self.seed = int(seed)
        self.env_mod = env_mod
        self.num_players = int(env_mod.NUM_PLAYERS)
        self.simultaneous = bool(getattr(env_mod, 'SIMULTANEOUS', False))
        self.max_steps = int(getattr(env_mod, 'MAX_STEPS', 1000))
        self._has_rule = hasattr(env_mod, 'greedy_action')
        # recurrence is architecture-structural: the env's registered net
        # decides it before any snapshot arrives
        self.recurrent = hasattr(host_env.net(), 'init_hidden')
        self.record_mode = resolve_record_mode(env_mod, self.recurrent,
                                               str(record_mode or ''))
        # streaming ingest sink (set by DeviceActorGather when the
        # streaming: block is on): lanes in 'device' record mode flush
        # fixed-T windows through it mid-block instead of holding the
        # finished episode. 'strict' lanes never stream — their byte
        # contract is only proven by the END-of-episode host replay.
        self.emit = None
        self.blocks = 0
        self._built = None          # wrapper the program was traced from
        self._rollout = None
        self._pack = None
        self._stack_key = None
        self._stacked = None
        self._gen = None            # lazy host Generator for strict reruns
        self._m_plies = telemetry.counter('device_actor_plies_total')
        self._m_episodes = telemetry.counter('device_actor_episodes_total')
        self._m_results = telemetry.counter('device_actor_results_total')
        self._m_divergence = telemetry.counter(
            'device_actor_divergence_total')
        self._m_chunk = telemetry.REGISTRY.histogram(
            'device_actor_chunk_seconds')
        self._m_fill = telemetry.gauge('device_actor_fill_ratio')
        telemetry.install_jax_monitoring()

    # -- task classification ----------------------------------------------

    def _classify(self, task) -> Dict[str, Any]:
        """Map one ledger task onto per-seat (mode, slot-mid) vectors, or
        None when the compiled program cannot express it (host fallback)."""
        role = (task or {}).get('role')
        P = self.num_players
        raw = (task or {}).get('model_id') or {}
        mids = {p: int(raw.get(p, -1)) for p in range(P)}
        modes = [MODE_FIRST] * P
        slot_mids = []
        if role == 'g':
            if self.record_mode == 'strict' \
                    and task.get('sample_key') is None:
                return None     # no server key => no byte contract to keep
            for p in range(P):
                if mids[p] >= 1:
                    modes[p] = MODE_SAMPLE
                    slot_mids.append(mids[p])
                elif mids[p] == 0:
                    modes[p] = MODE_UNIFORM
                else:
                    return None
            return {'task': task, 'kind': 'episode', 'modes': modes,
                    'mids': mids, 'slot_mids': slot_mids, 'opponent': None}
        if role == 'e':
            seat = int(task['player'][0])
            opponent = task.get('opponent')
            if not opponent:
                opponents = (self.args.get('eval') or {}).get('opponent', [])
                skey = task.get('sample_key')
                if opponents and skey is not None:
                    # the Evaluator's namespace-2 pool draw, replicated so
                    # the opponent identity matches a host re-issue exactly
                    seq = sample_seed(self.args.get('seed', 0),
                                      (2, int(skey)), 0)
                    opponent = opponents[int(
                        np.random.default_rng(seq).integers(len(opponents)))]
                elif opponents:
                    return None   # unkeyed pool draw: host decides
                else:
                    opponent = 'random'
            for p in range(P):
                if p == seat:
                    modes[p] = MODE_GREEDY if mids[p] >= 1 else MODE_FIRST
                    if mids[p] >= 1:
                        slot_mids.append(mids[p])
                elif mids[p] >= 1:
                    modes[p] = MODE_GREEDY
                    slot_mids.append(mids[p])
                elif opponent == 'random':
                    modes[p] = MODE_UNIFORM
                elif str(opponent).startswith('rulebase') and self._has_rule:
                    modes[p] = MODE_RULEBASE
                else:
                    return None   # checkpoint/serving opponents: host path
            return {'task': task, 'kind': 'result', 'modes': modes,
                    'mids': mids, 'slot_mids': slot_mids,
                    'opponent': opponent}
        return None

    # -- compiled program ---------------------------------------------------

    def _build(self, wrapper):
        """Trace the one chunk program from the first materialized wrapper.
        Everything that varies per block — the stacked params, the per-seat
        slot/mode tables, the precomputed sampling draws, liveness — is a
        program INPUT of fixed shape, so league pairings and model updates
        never retrace."""
        assert hasattr(wrapper.module, 'init_hidden') == self.recurrent, \
            'env net() and snapshot disagree on recurrence'
        env_mod, M = self.env_mod, self.slots
        N, P = self.n_envs, self.num_players
        simultaneous, recurrent = self.simultaneous, self.recurrent
        strict = self.record_mode == 'strict'
        full = self.record_mode == 'device'
        has_rule, has_rew = self._has_rule, hasattr(env_mod, 'rewards')
        apply_fn = wrapper.module.apply

        def chunk(stacked, state, hidden, u_tab, seat_slot, seat_mode,
                  live, t, rng):
            def body(carry, _):
                state, hidden, live, t, rng = carry
                rows = jnp.arange(N)
                per_slot = []
                for m in range(M):
                    pm = jax.tree_util.tree_map(lambda x: x[m], stacked)
                    per_slot.append(_ply_inference(
                        env_mod, apply_fn, recurrent, simultaneous,
                        pm, state, hidden))
                obs, amask = per_slot[0][0], per_slot[0][2]
                legal = (amask <= 0).astype(jnp.float32)
                logitsM = jnp.stack([s[1] for s in per_slot])
                valM = None
                if per_slot[0][4].get('value') is not None:
                    valM = jnp.stack(
                        [s[4]['value'].reshape((N, P, -1))
                         if simultaneous else s[4]['value']
                         for s in per_slot])
                rng, k1, k2, k3 = jax.random.split(rng, 4)
                if simultaneous:
                    cols = jnp.arange(P)[None, :]
                    rows2 = rows[:, None]
                    logits = logitsM[seat_slot, rows2, cols]   # (N, P, A)
                    value = (valM[seat_slot, rows2, cols]
                             if valM is not None else None)
                    mode = seat_mode
                    a_sample = jax.random.categorical(k1, logits)
                    a_unif = jax.random.categorical(k2, -amask)
                else:
                    player = env_mod.turn(state)               # (N,)
                    slot_act = seat_slot[rows, player]
                    logits = logitsM[slot_act, rows]           # (N, A)
                    value = (valM[slot_act, rows]
                             if valM is not None else None)
                    mode = seat_mode[rows, player]
                    if strict:
                        idx = jnp.minimum(t, u_tab.shape[1] - 1)
                        u = u_tab[rows, idx]
                        probs_u = jax.nn.softmax(logits, axis=-1)
                        a_sample = _u_pick(probs_u, legal, u)
                        a_unif = _u_pick(jnp.ones_like(legal), legal, u)
                    else:
                        a_sample = jax.random.categorical(k1, logits)
                        a_unif = jax.random.categorical(k2, -amask)
                    if recurrent:
                        hidden = jax.tree_util.tree_map(
                            lambda *hs: jnp.stack(hs)[slot_act, rows],
                            *[s[3] for s in per_slot])
                if simultaneous and recurrent:
                    cols = jnp.arange(P)[None, :]
                    rows2 = rows[:, None]
                    hidden = jax.tree_util.tree_map(
                        lambda *hs: jnp.stack(hs)[seat_slot, rows2, cols],
                        *[s[3] for s in per_slot])
                probs = jax.nn.softmax(logits, axis=-1)
                a_greedy = jnp.argmax(logits, axis=-1)
                a_first = jnp.argmax(legal, axis=-1)
                action = a_first
                action = jnp.where(mode == MODE_SAMPLE, a_sample, action)
                action = jnp.where(mode == MODE_GREEDY, a_greedy, action)
                action = jnp.where(mode == MODE_UNIFORM, a_unif, action)
                if has_rule:
                    a_rule = env_mod.greedy_action(state, k3)
                    action = jnp.where(mode == MODE_RULEBASE, a_rule, action)
                action = action.astype(jnp.int32)
                sel = jnp.take_along_axis(probs, action[..., None],
                                          axis=-1)[..., 0]
                gate = live[:, None] if simultaneous else live
                action = jnp.where(gate, action, 0)
                nstate = env_mod.step(state, action)
                nstate = _tree_where(live, nstate, state)     # freeze done
                done_now = env_mod.terminal(nstate) & live
                record = {'action': action, 'live': live, 'done': done_now,
                          'outcome': env_mod.outcome(nstate)}
                if simultaneous:
                    record['acting'] = env_mod.acting(state)
                else:
                    record['player'] = env_mod.turn(state)
                if full:
                    record['obs'] = obs
                    record['prob'] = sel
                    record['amask'] = amask
                    if value is not None:
                        record['value'] = value
                    if has_rew:
                        record['reward'] = env_mod.rewards(nstate)
                t = t + live.astype(jnp.int32)
                live = live & ~done_now
                return (nstate, hidden, live, t, rng), record

            (state, hidden, live, t, rng), records = jax.lax.scan(
                body, (state, hidden, live, t, rng), None,
                length=self.chunk_steps)
            return state, hidden, live, t, rng, dict(records)

        self._rollout = jax.jit(chunk)
        self._built = wrapper

    def _stack_params(self, assign: Dict[int, int]):
        """Stack each slot's params as pytree leaves (unused slots padded
        with the first real params so the tree is dense). Cached on the
        slot->mid map: re-serving the same pairing costs nothing."""
        by_slot = [None] * self.slots
        for mid, slot in assign.items():
            by_slot[slot] = mid
        key = tuple(by_slot)
        if key == self._stack_key:
            return self._stacked
        pad = self.vault.params(next(iter(assign)))
        trees = [self.vault.params(mid) if mid is not None else pad
                 for mid in by_slot]
        self._stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *trees)
        self._stack_key = key
        return self._stacked

    # -- block execution ----------------------------------------------------

    def run_block(self, tasks):
        """Serve one block of ledger tasks on device.

        Returns ``(uploads, deferred)``: uploads are ``(kind, payload)``
        pairs ready for the gather's upload box (payload None for a lane
        that failed — the ledger's deadline re-issues it); deferred tasks
        need the host fallback path."""
        deferred, plan = [], []
        for task in tasks:
            if task.get('role') == 'idle':
                continue
            cls = self._classify(task)
            (plan if cls is not None else deferred).append(cls or task)
        if len(plan) > self.n_envs:
            # more tasks than lanes: overflow rides the host fallback
            deferred.extend(cls['task'] for cls in plan[self.n_envs:])
            plan = plan[:self.n_envs]
        if not plan:
            return [], deferred

        # slot planning: league.plan_slots admits tasks in order until the
        # compiled stack is full; overflow rides the host fallback
        from .league import plan_slots
        assign, admitted = plan_slots(
            [cls['slot_mids'] for cls in plan], self.slots)
        kept = []
        for cls, ok in zip(plan, admitted):
            (kept if ok else deferred).append(cls if ok else cls['task'])
        plan = kept
        if not assign or not plan:
            # nothing slot-backed to run (epoch 0, or pure overflow):
            # the program needs at least one real params tree
            deferred.extend(cls['task'] for cls in plan)
            return [], deferred

        if self._rollout is None:
            self._build(self.vault.model(next(iter(assign))))
        stacked = self._stack_params(assign)

        N, P = self.n_envs, self.num_players
        strict = self.record_mode == 'strict'
        seat_slot = np.zeros((N, P), np.int32)
        seat_mode = np.full((N, P), MODE_FIRST, np.int32)
        live = np.zeros((N,), bool)
        u_len = self.max_steps if strict else 1
        u_tab = np.zeros((N, u_len), np.float32)
        base_seed = self.args.get('seed', 0)
        for i, cls in enumerate(plan):
            live[i] = True
            for p in range(P):
                seat_mode[i, p] = cls['modes'][p]
                mid = cls['mids'][p]
                if cls['modes'][p] in (MODE_SAMPLE, MODE_GREEDY):
                    seat_slot[i, p] = assign[mid]
            if strict:
                skey = cls['task'].get('sample_key')
                if cls['kind'] == 'episode':
                    ekey, d0 = (0, int(skey)), 0
                else:
                    # eval lanes carry no byte contract; draw 0 named the
                    # opponent, so per-ply draws continue the same stream
                    ekey, d0 = (2, int(skey if skey is not None else i)), 1
                for tt in range(u_len):
                    seq = sample_seed(base_seed, ekey, d0 + tt)
                    u_tab[i, tt] = np.random.default_rng(seq).random()

        block_seed = self.seed + 7919 * self.blocks
        self.blocks += 1
        try:
            state = self.env_mod.init_state(N, block_seed)
        except TypeError:
            state = self.env_mod.init_state(N)
        hidden = (self._built.module.init_hidden((N, P))
                  if self.recurrent else None)
        live_d = jnp.asarray(live)
        t_d = jnp.zeros((N,), jnp.int32)
        rng = jax.random.PRNGKey(block_seed)
        u_d = jnp.asarray(u_tab)
        slot_d = jnp.asarray(seat_slot)
        mode_d = jnp.asarray(seat_mode)

        # streaming ingest: per-lane window buffers, flushed through
        # self.emit as each fixed-T window fills (device record mode only:
        # these records are attempt-scoped, so every chunk is stamped and
        # keyed by task_id learner-side)
        stream = None
        if self.emit is not None and not strict \
                and (self.args.get('streaming') or {}).get('enabled'):
            stream = {
                'T': int((self.args.get('streaming') or {})
                         .get('chunk_steps', 32)),
                'lanes': [dict(moments=[], flushed=0, chunk=0, done=False)
                          if cls['kind'] == 'episode' else None
                          for cls in plan],
            }

        chunks, plies_run = [], 0
        n_chunks_cap = max(2, -(-self.max_steps // self.chunk_steps) + 2)
        for _ in range(n_chunks_cap):
            t0 = time.perf_counter()
            state, hidden, live_d, t_d, rng, records = self._rollout(
                stacked, state, hidden, u_d, slot_d, mode_d,
                live_d, t_d, rng)
            if self._pack is None:
                self._pack = _RecordPacker(records)
            rec = self._pack.unpack(self._pack.pack(records))
            self._m_chunk.observe(time.perf_counter() - t0)
            chunks.append(rec)
            plies_run += int(rec['live'].sum())
            if stream is not None:
                self._stream_lanes(plan, rec, stream)
            if not (rec['live'][-1] & ~rec['done'][-1]).any():
                break
        if stream is not None:
            # block cap reached: flush the unfinished lanes' partial tails
            # as non-final windows (the gather's clean-exit flush ships
            # them) — the learner trains on the exposed prefix while the
            # deadline re-issue regenerates the episode under a new task
            for i, st in enumerate(stream['lanes']):
                if st is None or st['done'] \
                        or len(st['moments']) <= st['flushed']:
                    continue
                self._emit_lane_chunk(plan[i], st, final=False)
        self._m_plies.inc(plies_run)
        scheduled = len(chunks) * self.chunk_steps * max(1, len(plan))
        self._m_fill.set(plies_run / max(1, scheduled))
        # observations can be dict pytrees (e.g. Geister) — concat per leaf
        rec = jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *chunks)

        uploads = []
        for i, cls in enumerate(plan):
            if stream is not None and stream['lanes'][i] is not None:
                # every window of this lane (final chunk included, when it
                # finished) already rode the emit sink; an unfinished lane
                # re-issues on deadline like a failed one
                continue
            ks = np.nonzero(rec['live'][:, i])[0]
            finished = len(ks) > 0 and bool(rec['done'][ks[-1], i])
            payload = None
            if finished:
                try:
                    if cls['kind'] == 'result':
                        payload = self._result_record(cls, i, rec, ks)
                    elif strict:
                        payload = self._splice_strict(cls, i, rec, ks)
                    else:
                        payload = self._splice_device(cls, i, rec, ks)
                except Exception:
                    import traceback
                    traceback.print_exc()
                    payload = None
            uploads.append((cls['kind'], payload))
        if self.blocks == 1:
            telemetry.mark_steady_state(note='device actor warmup complete')
        return uploads, deferred

    # -- streaming ----------------------------------------------------------

    def _stream_lanes(self, plan, rec, stream):
        """Fold one dispatch's records into the per-lane chunk streams,
        flushing every filled fixed-T window through the emit sink. A lane
        whose episode terminated emits its final chunk (tail + outcome)
        and stops accumulating."""
        players = list(range(self.num_players))
        for i, cls in enumerate(plan):
            st = stream['lanes'][i]
            if st is None or st['done']:
                continue
            try:
                ks = np.nonzero(rec['live'][:, i])[0]
                for k in ks:
                    if self.simultaneous:
                        st['moments'].append(
                            self._lane_moment_simultaneous(
                                rec, k, i, players))
                    else:
                        st['moments'].append(
                            self._lane_moment_turn_based(rec, k, i, players))
                while len(st['moments']) - st['flushed'] >= stream['T']:
                    self._emit_lane_chunk(cls, st, final=False,
                                          upto=st['flushed'] + stream['T'])
                if len(ks) > 0 and bool(rec['done'][ks[-1], i]):
                    outcome = {p: float(rec['outcome'][ks[-1], i, p])
                               for p in players}
                    self._emit_lane_chunk(cls, st, final=True,
                                          outcome=outcome)
                    st['done'] = True
                    telemetry.counter('episodes_generated_total').inc()
                    telemetry.counter('generation_steps_total').inc(
                        len(st['moments']))
                    self._m_episodes.inc()
            except Exception:
                import traceback
                traceback.print_exc()
                # stop streaming this lane; the already-emitted prefix
                # stays usable and the deadline re-issues the task
                st['done'] = True
                telemetry.counter('worker_task_failures_total').inc()

    def _emit_lane_chunk(self, cls, st, final, outcome=None, upto=None):
        """Ship one window of a streamed lane, stamped ``record_version``
        (device records carry no host byte contract; the assembler keys
        stamped streams by task_id so attempts never merge)."""
        upto = len(st['moments']) if upto is None else upto
        window = st['moments'][st['flushed']:upto]
        chunk = build_chunk(cls['task'], st['chunk'], st['flushed'], window,
                            self.args, final=final, outcome=outcome)
        chunk['record_version'] = 1
        st['flushed'] = upto
        st['chunk'] += 1
        self.emit(chunk)

    # -- splicing -----------------------------------------------------------

    def _result_record(self, cls, lane, rec, ks):
        """Evaluation lanes upload outcome-only records (the Evaluator's
        ``{'args', 'opponent', 'result'}`` contract)."""
        k = ks[-1]
        players = list(range(self.num_players))
        self._m_results.inc()
        return {'args': cls['task'], 'opponent': cls['opponent'],
                'result': {p: float(rec['outcome'][k, lane, p])
                           for p in players}}

    def _splice_device(self, cls, lane, rec, ks):
        """Assemble a ``record_version: 1`` episode from the on-device
        trajectory (the DeviceGenerator moment layout, one lane)."""
        task = cls['task']
        players = list(range(self.num_players))
        moments = []
        for k in ks:
            if self.simultaneous:
                moments.append(self._lane_moment_simultaneous(
                    rec, k, lane, players))
            else:
                moments.append(self._lane_moment_turn_based(
                    rec, k, lane, players))
        k = ks[-1]
        outcome = {p: float(rec['outcome'][k, lane, p]) for p in players}
        for p in players:
            ret = 0.0
            for t in range(len(moments) - 1, -1, -1):
                ret = ((moments[t]['reward'][p] or 0)
                       + self.args['gamma'] * ret)
                moments[t]['return'][p] = ret
        telemetry.counter('episodes_generated_total').inc()
        telemetry.counter('generation_steps_total').inc(len(moments))
        self._m_episodes.inc()
        return {
            'args': task, 'steps': len(moments), 'outcome': outcome,
            'moment': compress_moments(
                moments, self.args['compress_steps'],
                level=self.args.get('compress_level', 9)),
            # records from this path follow the device rng contract, not
            # the host byte contract: stamped, never silently divergent
            'record_version': 1,
        }

    def _lane_moment_turn_based(self, rec, k, i, players):
        player = int(rec['player'][k, i])
        moment = _blank(players)
        moment['observation'][player] = map_structure(
            lambda v: v[k, i], rec['obs'])
        moment['selected_prob'][player] = float(rec['prob'][k, i])
        moment['action_mask'][player] = rec['amask'][k, i]
        moment['action'][player] = int(rec['action'][k, i])
        if rec.get('value') is not None:
            moment['value'][player] = rec['value'][k, i]
        moment['reward'] = self._lane_rewards(rec, k, i, players)
        moment['turn'] = [player]
        return moment

    def _lane_moment_simultaneous(self, rec, k, i, players):
        moment = _blank(players)
        turn_players = []
        for p in players:
            if not rec['acting'][k, i, p]:
                continue
            turn_players.append(p)
            moment['observation'][p] = map_structure(
                lambda v: v[k, i, p], rec['obs'])
            moment['selected_prob'][p] = float(rec['prob'][k, i, p])
            moment['action_mask'][p] = rec['amask'][k, i, p]
            moment['action'][p] = int(rec['action'][k, i, p])
            if rec.get('value') is not None:
                moment['value'][p] = rec['value'][k, i, p]
        moment['reward'] = self._lane_rewards(rec, k, i, players)
        moment['turn'] = turn_players
        return moment

    def _lane_rewards(self, rec, k, i, players):
        if rec.get('reward') is None:
            return {p: None for p in players}
        return {p: float(rec['reward'][k, i, p]) for p in players}

    def _splice_strict(self, cls, lane, rec, ks):
        """Replay the lane's device actions through the HOST env + sampling
        contract and verify every draw. A verified lane's moments are, by
        construction, the ones the host Generator would have produced —
        the record is byte-identical and carries no version stamp. Any
        mismatch (f32/f64 cumsum boundary collision) falls back to a full
        host Generator rerun: correctness is unconditional, the device
        speedup is probabilistic."""
        task = cls['task']
        try:
            episode = self._replay_strict(task, lane, rec, ks)
        except Divergence:
            episode = None
        if episode is None:
            self._m_divergence.inc()
            episode = self._host_rerun(task)
        else:
            self._m_episodes.inc()
        return episode

    def _replay_strict(self, task, lane, rec, ks):
        env = self.host_env
        args = self.args
        base_seed = args.get('seed', 0)
        episode_key = (0, int(task['sample_key']))
        seed_env_rng(env, base_seed, episode_key)
        if env.reset():
            raise Divergence
        device_actions = [int(a) for a in rec['action'][ks, lane]]
        plies = []      # [player, obs, legal, seed_seq, reward, action]
        draws = 0
        for a_dev in device_actions:
            if env.terminal():
                raise Divergence             # device episode ran long
            turn_players = env.turns()
            if len(turn_players) != 1:
                raise Divergence             # strict is turn-based only
            p = turn_players[0]
            obs = env.observation(p)
            seed_seq = sample_seed(base_seed, episode_key, draws)
            draws += 1
            legal = env.legal_actions(p)
            if a_dev not in legal:
                raise Divergence
            if env.step({p: a_dev}):
                raise Divergence
            plies.append([p, obs, legal, seed_seq, env.reward(), a_dev])
        if not env.terminal():
            raise Divergence                 # device episode ended early

        # batched recompute per distinct model, chunked to the SAME bucket
        # the Generator's per-ply bucketed_inference dispatches (bucket 8):
        # rows within one bucket are row-independent, but the same row CAN
        # stray across bucket SIZES on some device meshes, so byte parity
        # requires never escalating to a larger bucket here
        models = self.vault.obtain(dict(task['model_id']))
        outputs = [None] * len(plies)
        groups: Dict[int, list] = {}
        for j, ply in enumerate(plies):
            groups.setdefault(id(models[ply[0]]), []).append(j)
        with telemetry.expected_compile('device-actor strict recompute'):
            for idxs in groups.values():
                model = models[plies[idxs[0]][0]]
                if not hasattr(model, 'batch_inference'):
                    for j in idxs:           # RandomModel: zero outputs
                        outputs[j] = bucketed_inference(model, plies[j][1])
                    continue
                for lo in range(0, len(idxs), 8):
                    chunk = idxs[lo:lo + 8]
                    obs_b, _ = pad_to_bucket(
                        [plies[j][1] for j in chunk])
                    out = model.batch_inference(obs_b, None)
                    policy = np.asarray(out['policy'])
                    value = (np.asarray(out['value'])
                             if out.get('value') is not None else None)
                    for row, j in enumerate(chunk):
                        outputs[j] = {
                            'policy': policy[row],
                            'value': (value[row]
                                      if value is not None else None)}

        moments = []
        for j, (p, obs, legal, seed_seq, reward, a_dev) in enumerate(plies):
            action, prob, mask = masked_sample(
                outputs[j]['policy'], legal, seed_seq)
            if action != a_dev:
                raise Divergence             # boundary collision: rerun
            moment = _blank_moment(env.players())
            moment['observation'][p] = obs
            moment['value'][p] = outputs[j].get('value')
            moment['selected_prob'][p] = prob
            moment['action_mask'][p] = mask
            moment['action'][p] = action
            for player in env.players():
                moment['reward'][player] = reward.get(player, None)
            moment['turn'] = [p]
            moments.append(moment)
        return _finalize_episode(env, moments, args, task)

    def _host_rerun(self, task):
        """Byte-exact fallback: the standard host Generator replays the
        task from its server-stamped key (same record any worker would
        upload)."""
        if self._gen is None:
            self._gen = Generator(self.host_env, self.args,
                                  namespace=-1)
        models = self.vault.obtain(dict(task['model_id']))
        with telemetry.expected_compile('device-actor host rerun'):
            return self._gen.execute(models, task)
