"""Device-resident self-play: the entire act/sample/step loop inside one jit.

The BatchedGenerator (generation.py) still crosses the host boundary once
per ply (observations up, policies down). For environments implemented as
pure JAX functions (envs/jax_tictactoe.py, envs/jax_hungry_geese.py), this
engine runs K plies of N environments as ONE compiled program — inference,
legal masking, categorical sampling, transition, termination detection and
auto-reset all stay in HBM; the host receives a (K, N, ...) trajectory chunk
and only splices completed episodes into the standard episode records (the
same wire/batch format as every other generator, generation.py:84-91 in the
reference).

Two env protocols:
  * turn-based (jax_tictactoe): observe -> (N, ...) side-to-move view,
    step((N,) actions), turn -> (N,) acting seat;
  * simultaneous (SIMULTANEOUS=True, jax_hungry_geese): observe ->
    (N, P, ...) per-player views, step((N, P) actions), acting -> (N, P)
    mask of players that act this ply.

This is the throughput ceiling path: on a TPU the per-ply cost is one fused
program dispatch regardless of N.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .ops.batch import compress_moments
from .utils.tree import map_structure


def obs_leading(obs) -> int:
    """Leading (env) dimension of an observation pytree."""
    return jax.tree_util.tree_leaves(obs)[0].shape[0]


def _blank(players):
    return {key: {p: None for p in players} for key in
            ('observation', 'selected_prob', 'action_mask', 'action',
             'value', 'reward', 'return')}


def _ply_inference(env_mod, apply_fn, recurrent, simultaneous,
                   params, state, hidden):
    """Shared per-ply plumbing for the device rollout engines (generation
    and evaluation): observe, run the net — with the recurrent hidden
    gather/scatter for turn-based envs and the (N, P)->(N*P) fold for
    simultaneous ones — and build the illegal-action mask.

    Returns (obs, logits, amask, hidden, out): logits/amask are (N, P, A)
    for simultaneous envs, (N, A) turn-based; ``out`` is the raw model
    output dict with 'hidden' already popped.
    """
    obs = env_mod.observe(state)
    legal = env_mod.legal_mask(state)
    amask = (1.0 - legal) * 1e32
    if simultaneous:
        N, P = obs.shape[:2]
        flat = obs.reshape((N * P,) + obs.shape[2:])
        if recurrent:
            # every player's hidden advances each ply (they all observe);
            # fold (N, P) into the batch dim
            h_in = jax.tree_util.tree_map(
                lambda h: h.reshape((N * P,) + h.shape[2:]), hidden)
            out = dict(apply_fn(params, flat, h_in))
            nh = out.pop('hidden')
            hidden = jax.tree_util.tree_map(
                lambda h: h.reshape((N, P) + h.shape[1:]), nh)
        else:
            out = dict(apply_fn(params, flat, None))
        logits = out['policy'].reshape(N, P, -1) - amask
    else:
        if recurrent:
            # gather the acting player's hidden slot, run the net, scatter
            # the new state back (mirrors the omask-gated training carry)
            rows = jnp.arange(obs_leading(obs))
            player = env_mod.turn(state)
            h_in = jax.tree_util.tree_map(
                lambda h: h[rows, player], hidden)
            out = dict(apply_fn(params, obs, h_in))
            nh = out.pop('hidden')
            hidden = jax.tree_util.tree_map(
                lambda h, x: h.at[rows, player].set(x), hidden, nh)
        else:
            out = dict(apply_fn(params, obs, None))
        logits = out['policy'] - amask
    return obs, logits, amask, hidden, out


def _reset_hidden_where_done(hidden, done):
    """Fresh episodes start with zero recurrent state."""
    return jax.tree_util.tree_map(
        lambda h: jnp.where(done.reshape((-1,) + (1,) * (h.ndim - 1)),
                            jnp.zeros_like(h), h), hidden)


class _RecordPacker:
    """Flatten a records pytree into ONE f32 device array and back.

    On a tunneled TPU each distinct array fetch pays a full host round trip
    (~140 ms measured) while bandwidth is cheap, so the splice path packs
    every record leaf into a single transfer instead of one per leaf. The
    pack runs as its own tiny jitted program (async dispatch, ~4 ms);
    unpack restores shapes/dtypes exactly (int/bool values are small enough
    to round-trip through f32 losslessly)."""

    def __init__(self, records):
        leaves, self.treedef = jax.tree_util.tree_flatten(records)
        self.shapes = [l.shape for l in leaves]
        self.dtypes = [l.dtype for l in leaves]
        self._fn = jax.jit(lambda ls: jnp.concatenate(
            [l.astype(jnp.float32).reshape(-1) for l in ls]))

    def pack(self, records):
        return self._fn(jax.tree_util.tree_leaves(records))

    def unpack(self, flat):
        flat = np.asarray(flat)   # the one transfer
        out, pos = [], 0
        for shape, dtype in zip(self.shapes, self.dtypes):
            n = int(np.prod(shape)) if shape else 1
            out.append(flat[pos:pos + n].reshape(shape).astype(dtype))
            pos += n
        return jax.tree_util.tree_unflatten(self.treedef, out)


# NOTE on observation=True for turn-based envs (the geister-device config):
# the reference generator runs inference ONLY for ``turn_players +
# observers`` each ply (reference generation.py:37-41), and no reference env
# ever overrides ``observers()`` (it defaults to [] — reference
# environment.py:84); the eval-side Agent likewise advances its hidden only
# on its own turns (reference evaluation.py:97-101). So even with
# observation=True, exactly the acting seat observes per ply — the flag only
# widens the BATCH layout to the full player axis (reference train.py:65-68)
# with observation_mask marking the acting seat. The acting-seat-only
# recording below is therefore already reference-exact; an earlier
# "observe-all" helper that ran inference for every seat per ply was removed
# as anti-parity (tests/test_geister_device_parity.py pins the semantics).


def _init_rollout_engine(engine, env_mod, wrapper, n_envs: int, seed: int):
    """Shared env/model bootstrapping for the device rollout engines: env
    state vector, PRNG key, simultaneous/recurrent detection, and the
    per-env recurrent hidden pytree."""
    engine.env_mod = env_mod
    engine.wrapper = wrapper
    engine.n_envs = n_envs
    engine.simultaneous = bool(getattr(env_mod, 'SIMULTANEOUS', False))
    try:
        engine.state = env_mod.init_state(n_envs, seed)
    except TypeError:
        engine.state = env_mod.init_state(n_envs)
    engine.rng = jax.random.PRNGKey(seed)
    engine.recurrent = hasattr(wrapper.module, 'init_hidden')
    engine.hidden = (wrapper.module.init_hidden(
        (n_envs, env_mod.NUM_PLAYERS)) if engine.recurrent else None)


def make_gen_body(env_mod, apply_fn, recurrent: bool, simultaneous: bool):
    """The one self-play ply: inference, sampling, transition, record.

    Shared between DeviceGenerator's standalone rollout program and the
    fused generate+ingest+train pipeline (ops/fused_pipeline.py) so the
    recorded trajectory semantics have exactly one definition.
    Carry is (env_state, hidden, rng); emits the per-ply record dict.

    The ply body is (re)defined inside ``rollout_chunk`` so it closes over
    the CURRENT trace's params: lax.scan caches traced bodies by function
    identity, and a body shared across traces would smuggle one trace's
    param tracers into the next (UnexpectedTracerError).
    """
    def rollout_chunk(params, state, hidden, rng, chunk_steps: int):
        def body(carry, _):
            state, hidden, rng = carry
            obs, logits, amask, hidden, out = _ply_inference(
                env_mod, apply_fn, recurrent, simultaneous,
                params, state, hidden)
            rng, key = jax.random.split(rng)
            actions = jax.random.categorical(key, logits)
            probs = jax.nn.softmax(logits, axis=-1)
            sel = jnp.take_along_axis(probs, actions[..., None],
                                      axis=-1)[..., 0]
            if simultaneous:
                N, P = obs.shape[:2]
                value = out.get('value')
                if value is not None:
                    value = value.reshape(N, P, -1)
                act_mask = env_mod.acting(state)           # (N, P)
                nstate = env_mod.step(state, actions)
                done = env_mod.terminal(nstate)
                record = {'obs': obs, 'action': actions, 'prob': sel,
                          'amask': amask, 'value': value,
                          'acting': act_mask, 'done': done,
                          'outcome': env_mod.outcome(nstate)}
            else:
                player = env_mod.turn(state)
                nstate = env_mod.step(state, actions)
                done = env_mod.terminal(nstate)
                record = {'obs': obs, 'action': actions, 'prob': sel,
                          'amask': amask, 'value': out.get('value'),
                          'player': player, 'done': done,
                          'outcome': env_mod.outcome(nstate)}
            if hasattr(env_mod, 'rewards'):
                record['reward'] = env_mod.rewards(nstate)   # (N, P)
            nstate = env_mod.auto_reset(nstate, done)
            if recurrent:
                hidden = _reset_hidden_where_done(hidden, done)
            return (nstate, hidden, rng), record

        (state, hidden, rng), records = jax.lax.scan(
            body, (state, hidden, rng), None, length=chunk_steps)
        return state, hidden, rng, dict(records)

    return rollout_chunk


class DeviceGenerator:
    """Runs chunks of device-resident self-play for a pure-JAX env module.

    Dispatch is PIPELINED one chunk deep: each ``step_chunk*`` call enqueues
    the NEXT rollout program before fetching the previous chunk's results,
    so the host-visible round-trip latency (dominant on a tunneled TPU)
    overlaps with device execution of the following chunk. Callers see a
    one-chunk delay in episode accounting, nothing else.
    """

    pipelined = True    # step_chunk* returns the PREVIOUS dispatch's chunk

    def __init__(self, env_mod, wrapper, args: Dict[str, Any],
                 n_envs: int = 256, chunk_steps: int = 16, seed: int = 0):
        self.args = args
        self.chunk_steps = chunk_steps
        _init_rollout_engine(self, env_mod, wrapper, n_envs, seed)
        self._partials: List[List[dict]] = [[] for _ in range(n_envs)]
        self._pending = None
        self._acct_pack = None
        self._full_pack = None
        self.dispatches = 0

        rollout_chunk = make_gen_body(env_mod, wrapper.module.apply,
                                      self.recurrent, self.simultaneous)

        @jax.jit
        def rollout(params, state, hidden, rng):
            return rollout_chunk(params, state, hidden, rng, chunk_steps)

        self._rollout = rollout

    def _dispatch(self):
        self.state, self.hidden, self.rng, records = self._rollout(
            self.wrapper.params, self.state, self.hidden, self.rng)
        self.dispatches += 1
        return dict(records)

    def _dispatch_acct(self):
        """Dispatch rollout + the tiny done/outcome pack (one fetchable)."""
        records = self._dispatch()
        if self._acct_pack is None:
            self._acct_pack = _RecordPacker(
                {'done': records['done'], 'outcome': records['outcome']})
        return records, self._acct_pack.pack(
            {'done': records['done'], 'outcome': records['outcome']})

    def step_chunk_records(self):
        """Run one compiled chunk, keeping the trajectory ON DEVICE.

        For the device-ingest pipeline (ops/device_windows.py): returns the
        raw records pytree (device arrays, leading axes (K, N)) plus host
        copies of ONLY the tiny done/outcome arrays for episode accounting,
        fetched as ONE packed array (a fetch costs a tunnel round trip).
        The heavy leaves (observations, masks) never reach the host.
        """
        if self._pending is None:
            self._pending = self._dispatch_acct()
        (records, pack), self._pending = self._pending, self._dispatch_acct()
        acct = self._acct_pack.unpack(pack)
        return records, acct['done'], acct['outcome']

    def drain_records(self):
        """Fetch the in-flight speculative chunk at loop shutdown (device-
        ingest mode); returns (records, done, outcome) or None."""
        if self._pending is None:
            return None
        (records, pack), self._pending = self._pending, None
        acct = self._acct_pack.unpack(pack)
        return records, acct['done'], acct['outcome']

    # -- host-side episode splicing ---------------------------------------
    def _dispatch_full(self):
        """Dispatch rollout + the full-record pack (splice mode fetches
        EVERY leaf; packed, that is one transfer instead of one per leaf)."""
        records = self._dispatch()
        if self._full_pack is None:
            self._full_pack = _RecordPacker(records)
        return self._full_pack.pack(records)

    def step_chunk(self) -> List[dict]:
        """Run one compiled chunk; return episodes completed within it."""
        if self._pending is None:
            self._pending = self._dispatch_full()
        pack, self._pending = self._pending, self._dispatch_full()
        return self._splice(self._full_pack.unpack(pack))

    def drain_episodes(self) -> List[dict]:
        """Splice the in-flight speculative chunk at loop shutdown."""
        if self._pending is None:
            return []
        pack, self._pending = self._pending, None
        return self._splice(self._full_pack.unpack(pack))

    def _splice(self, rec) -> List[dict]:
        players = list(range(self.env_mod.NUM_PLAYERS))
        episodes: List[dict] = []
        for k in range(self.chunk_steps):
            for i in range(self.n_envs):
                if self.simultaneous:
                    moment = self._moment_simultaneous(rec, k, i, players)
                else:
                    moment = self._moment_turn_based(rec, k, i, players)
                self._partials[i].append(moment)
                if rec['done'][k, i]:
                    episodes.append(self._finalize(i, rec, k, players))
        return episodes

    def _moment_turn_based(self, rec, k, i, players):
        player = int(rec['player'][k, i])
        moment = _blank(players)
        moment['observation'][player] = map_structure(
            lambda v: v[k, i], rec['obs'])
        moment['selected_prob'][player] = float(rec['prob'][k, i])
        moment['action_mask'][player] = rec['amask'][k, i]
        moment['action'][player] = int(rec['action'][k, i])
        if rec.get('value') is not None:
            moment['value'][player] = rec['value'][k, i]
        moment['reward'] = self._rewards(rec, k, i, players)
        moment['turn'] = [player]
        return moment

    def _rewards(self, rec, k, i, players):
        if rec.get('reward') is None:
            return {p: None for p in players}
        return {p: float(rec['reward'][k, i, p]) for p in players}

    def _moment_simultaneous(self, rec, k, i, players):
        moment = _blank(players)
        turn_players = []
        for p in players:
            if not rec['acting'][k, i, p]:
                continue
            turn_players.append(p)
            moment['observation'][p] = map_structure(
                lambda v: v[k, i, p], rec['obs'])
            moment['selected_prob'][p] = float(rec['prob'][k, i, p])
            moment['action_mask'][p] = rec['amask'][k, i, p]
            moment['action'][p] = int(rec['action'][k, i, p])
            if rec.get('value') is not None:
                moment['value'][p] = rec['value'][k, i, p]
        moment['reward'] = self._rewards(rec, k, i, players)
        moment['turn'] = turn_players
        return moment

    def _finalize(self, i, rec, k, players):
        moments = self._partials[i]
        self._partials[i] = []
        outcome = {p: float(rec['outcome'][k, i, p]) for p in players}
        for p in players:
            ret = 0.0
            for t in range(len(moments) - 1, -1, -1):
                ret = (moments[t]['reward'][p] or 0) + self.args['gamma'] * ret
                moments[t]['return'][p] = ret
        return {
            'args': {'role': 'g', 'player': players,
                     'model_id': {p: -1 for p in players}},
            'steps': len(moments),
            'outcome': outcome,
            'moment': compress_moments(moments, self.args['compress_steps']),
        }


class DeviceEvaluator:
    """Device-resident online evaluation vs a roster of opponents.

    The host BatchedEvaluator pays one inference dispatch per ply of every
    match; on a dispatch-latency-heavy backend that makes evaluation the
    dominant cost of the epoch loop (it needs ~10x more dispatches than
    chunked device generation for the same ply count). When every opponent
    is 'random' or a checkpoint path (league play) and the env has a
    pure-JAX twin, the whole match runs on device instead: envs split into
    one contiguous block per opponent, one rotating seat per env plays the
    trained model greedily (the same temperature-0 policy as
    BatchedEvaluator / reference agent.py Agent), the other seats either
    sample uniformly ('random') or play their checkpoint's greedy policy —
    inferenced inside the same compiled ply — and the host receives only
    (done, outcome, seat) per ply, K plies of N matches per dispatch.
    'rulebase' also runs on device when the env twin vectorizes its agent
    (``greedy_action``, e.g. jax_hungry_geese); otherwise it stays on the
    host evaluator (train.py device_eval_ok). Checkpoint opponents for
    recurrent nets carry their own hidden tree through the scan, so e.g.
    Geister league eval keeps the one-dispatch-per-chunk budget.
    """

    def __init__(self, env_mod, wrapper, args: Dict[str, Any],
                 n_envs: int = 64, chunk_steps: int = 16, seed: int = 77,
                 mesh=None, opponents=None):
        self.args = args
        self.chunk_steps = chunk_steps
        _init_rollout_engine(self, env_mod, wrapper, n_envs, seed)
        # one evaluated seat per env, rotated on every reset so first/second
        # (and every goose slot) are balanced like evaluate_mp's scheduler
        self.seat = jnp.arange(n_envs, dtype=jnp.int32) % env_mod.NUM_PLAYERS

        # opponent roster: envs are split into one contiguous block per
        # opponent (league play stays one-dispatch-per-chunk — the round-2
        # device evaluator silently fell back to the per-ply host evaluator
        # for anything but 'random'). 'random' plays uniform; a checkpoint
        # path plays its own greedy policy, inferenced inside the same
        # compiled ply (recurrent checkpoints carry opp_hidden, below).
        self.opponents = [str(o) for o in (opponents or ['random'])]
        assert n_envs >= len(self.opponents), \
            'need at least one eval env per opponent'
        self._opp_params: List[Any] = []
        bounds = np.linspace(0, n_envs, len(self.opponents) + 1).astype(int)
        self._opp_bounds = [(int(a), int(b), name)
                            for a, b, name in zip(bounds[:-1], bounds[1:],
                                                  self.opponents)]
        self._env_opp = np.empty(n_envs, dtype=object)
        for a, b, name in self._opp_bounds:
            self._env_opp[a:b] = name
        if 'rulebase' in self.opponents:
            assert hasattr(env_mod, 'greedy_action'), \
                'device rulebase eval needs the env twin to vectorize it'
        model_opps = [o for o in self.opponents
                      if o not in ('random', 'rulebase')]
        if model_opps:
            # the trained wrapper's params are the ready-made template for
            # msgpack deserialization (same module, same tree)
            from flax import serialization
            for path in model_opps:
                with open(path, 'rb') as f:
                    self._opp_params.append(jax.device_put(
                        serialization.from_bytes(wrapper.params, f.read())))
        # recurrent checkpoint opponents carry their own hidden tree through
        # the scan (gathered/scattered exactly like the main model's); the
        # env blocks are disjoint so ONE tree serves every opponent slice
        self.opp_hidden = (wrapper.module.init_hidden(
            (n_envs, env_mod.NUM_PLAYERS))
            if self.recurrent and model_opps else None)
        if mesh is not None:
            # eval envs sharded over 'data' alongside the fused trainer
            # (params arrive replicated); the plain-jit rollout partitions
            # under GSPMD — eval is embarrassingly parallel over envs
            from .parallel.mesh import replicated_sharding, shard_batch
            self.state = shard_batch(mesh, self.state)
            if self.hidden is not None:
                self.hidden = shard_batch(mesh, self.hidden)
            if self.opp_hidden is not None:
                self.opp_hidden = shard_batch(mesh, self.opp_hidden)
            self.seat = shard_batch(mesh, self.seat)
            self.rng = jax.device_put(self.rng, replicated_sharding(mesh))
        self._pending = None
        self._pack = None
        self.dispatches = 0

        apply_fn = wrapper.module.apply
        simultaneous = self.simultaneous
        recurrent = self.recurrent

        opp_bounds = self._opp_bounds
        model_ix = {name: i for i, name in enumerate(
            o for o in self.opponents if o not in ('random', 'rulebase'))}
        any_rulebase = any(name == 'rulebase' for _, _, name in opp_bounds)

        @jax.jit
        def rollout(params, opp_params, state, hidden, opp_hidden, seat,
                    rng):
            def body(carry, _):
                state, hidden, opp_hidden, seat, rng = carry
                obs, logits, amask, hidden, _ = _ply_inference(
                    env_mod, apply_fn, recurrent, simultaneous,
                    params, state, hidden)
                greedy = jnp.argmax(logits, axis=-1)
                rng, key = jax.random.split(rng)
                opp_act = jax.random.categorical(key, -amask)
                if any_rulebase:   # the env's vectorized rulebase agent
                    rng, rkey = jax.random.split(rng)
                    rule_act = env_mod.greedy_action(state, rkey)
                # opponent blocks: checkpoint policies (greedy) and the
                # rulebase agent, traced into this one program (static
                # slices). Recurrent checkpoints gather/scatter their own
                # hidden tree the same way _ply_inference does the main
                # model's — the blocks are disjoint slices of opp_hidden.
                for a, b, name in opp_bounds:
                    if name == 'random' or a == b:
                        continue
                    if name == 'rulebase':
                        opp_act = opp_act.at[a:b].set(rule_act[a:b])
                        continue
                    pg = opp_params[model_ix[name]]
                    # observations may be a pytree (e.g. geister's
                    # {'scalar', 'board'}): slice every leaf
                    o = jax.tree_util.tree_map(lambda x: x[a:b], obs)
                    if simultaneous:
                        No, Po = jax.tree_util.tree_leaves(o)[0].shape[:2]
                        flat = jax.tree_util.tree_map(
                            lambda x: x.reshape((No * Po,) + x.shape[2:]),
                            o)
                        if recurrent:
                            h_in = jax.tree_util.tree_map(
                                lambda h: h[a:b].reshape((No * Po,)
                                                         + h.shape[2:]),
                                opp_hidden)
                            out_o = dict(apply_fn(pg, flat, h_in))
                            nh = out_o.pop('hidden')
                            opp_hidden = jax.tree_util.tree_map(
                                lambda h, x: h.at[a:b].set(
                                    x.reshape((No, Po) + x.shape[1:])),
                                opp_hidden, nh)
                        else:
                            out_o = dict(apply_fn(pg, flat, None))
                        lg = (out_o['policy'].reshape(No, Po, -1)
                              - amask[a:b])
                    else:
                        if recurrent:
                            rows = jnp.arange(b - a)
                            pl = env_mod.turn(state)[a:b]
                            h_in = jax.tree_util.tree_map(
                                lambda h: h[a:b][rows, pl], opp_hidden)
                            out_o = dict(apply_fn(pg, o, h_in))
                            nh = out_o.pop('hidden')
                            opp_hidden = jax.tree_util.tree_map(
                                lambda h, x: h.at[a + rows, pl].set(x),
                                opp_hidden, nh)
                        else:
                            out_o = dict(apply_fn(pg, o, None))
                        lg = out_o['policy'] - amask[a:b]
                    opp_act = opp_act.at[a:b].set(jnp.argmax(lg, axis=-1))
                if simultaneous:
                    P2 = logits.shape[1]
                    is_main = (jnp.arange(P2)[None, :] == seat[:, None])
                else:
                    is_main = env_mod.turn(state) == seat
                actions = jnp.where(is_main, greedy, opp_act)
                nstate = env_mod.step(state, actions)
                done = env_mod.terminal(nstate)
                record = {'done': done, 'seat': seat,
                          'outcome': env_mod.outcome(nstate)}
                nstate = env_mod.auto_reset(nstate, done)
                seat = jnp.where(done,
                                 (seat + 1) % env_mod.NUM_PLAYERS, seat)
                if recurrent:
                    hidden = _reset_hidden_where_done(hidden, done)
                    if opp_hidden is not None:
                        opp_hidden = _reset_hidden_where_done(
                            opp_hidden, done)
                return (nstate, hidden, opp_hidden, seat, rng), record

            (state, hidden, opp_hidden, seat, rng), records = jax.lax.scan(
                body, (state, hidden, opp_hidden, seat, rng), None,
                length=chunk_steps)
            return state, hidden, opp_hidden, seat, rng, records

        self._rollout = rollout

    # results arrive one dispatch late: Learner.feed_results must use the
    # dispatch-time epoch for attribution
    pipelined = True

    def _dispatch(self):
        """Dispatch a chunk + its packed (done, seat, outcome) fetchable."""
        (self.state, self.hidden, self.opp_hidden, self.seat, self.rng,
         records) = \
            self._rollout(self.wrapper.params, tuple(self._opp_params),
                          self.state, self.hidden, self.opp_hidden,
                          self.seat, self.rng)
        self.dispatches += 1
        records = dict(records)
        if self._pack is None:
            self._pack = _RecordPacker(records)
        return self._pack.pack(records)

    def step(self) -> List[dict]:
        """One compiled chunk; returns finished eval result records (the
        same shape Learner.feed_results consumes from BatchedEvaluator).
        Pipelined one chunk deep like DeviceGenerator: the next chunk is
        enqueued before the previous one's outcome arrays are fetched (as
        ONE packed array — a fetch costs a tunnel round trip)."""
        if self._pending is None:
            self._pending = self._dispatch()
        pack, self._pending = self._pending, self._dispatch()
        return self._collect(self._pack.unpack(pack))

    def drain(self) -> List[dict]:
        """Collect the in-flight speculative chunk at loop shutdown."""
        if self._pending is None:
            return []
        pack, self._pending = self._pending, None
        return self._collect(self._pack.unpack(pack))

    def _collect(self, rec) -> List[dict]:
        done, seats, outcomes = rec['done'], rec['seat'], rec['outcome']
        players = list(range(self.env_mod.NUM_PLAYERS))
        results: List[dict] = []
        for k, i in zip(*np.nonzero(done)):
            seat = int(seats[k, i])
            results.append({
                'args': {'role': 'e', 'player': [seat],
                         'model_id': {p: (0 if p == seat else -1)
                                      for p in players}},
                'opponent': self._env_opp[i],
                'result': {p: float(outcomes[k, i, p]) for p in players},
            })
        return results
