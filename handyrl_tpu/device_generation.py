"""Device-resident self-play: the entire act/sample/step loop inside one jit.

The BatchedGenerator (generation.py) still crosses the host boundary once
per ply (observations up, policies down). For environments implemented as
pure JAX functions (envs/jax_tictactoe.py), this engine runs K plies of N
environments as ONE compiled program — inference, legal masking, categorical
sampling, transition, win detection and auto-reset all stay in HBM; the host
receives a (K, N, ...) trajectory chunk and only splices completed episodes
into the standard episode records (the same wire/batch format as every other
generator, generation.py:84-91 in the reference).

This is the throughput ceiling path: on a TPU the per-ply cost is one fused
program dispatch regardless of N.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .ops.batch import compress_moments
from .utils.tree import map_structure


class DeviceGenerator:
    """Runs chunks of device-resident self-play for a pure-JAX env module.

    env_mod must expose: init_state(n), observe(state), legal_mask(state),
    step(state, actions), terminal(state), turn(state), outcome(state),
    auto_reset(state, done), NUM_PLAYERS, N_ACTIONS.
    """

    def __init__(self, env_mod, wrapper, args: Dict[str, Any],
                 n_envs: int = 256, chunk_steps: int = 16, seed: int = 0):
        self.env_mod = env_mod
        self.wrapper = wrapper
        self.args = args
        self.n_envs = n_envs
        self.chunk_steps = chunk_steps
        self.state = env_mod.init_state(n_envs)
        self.rng = jax.random.PRNGKey(seed)
        self._partials: List[List[dict]] = [[] for _ in range(n_envs)]

        apply_fn = wrapper.module.apply

        @partial(jax.jit, static_argnums=())
        def rollout(params, state, rng):
            def body(carry, _):
                state, rng = carry
                obs = env_mod.observe(state)
                out = apply_fn(params, obs, None)
                legal = env_mod.legal_mask(state)
                amask = (1.0 - legal) * 1e32
                logits = out['policy'] - amask
                rng, key = jax.random.split(rng)
                actions = jax.random.categorical(key, logits)
                probs = jax.nn.softmax(logits, axis=-1)
                sel_prob = jnp.take_along_axis(
                    probs, actions[:, None], axis=-1)[:, 0]
                player = env_mod.turn(state)
                nstate = env_mod.step(state, actions)
                done = env_mod.terminal(nstate)
                record = {
                    'obs': obs, 'action': actions, 'prob': sel_prob,
                    'amask': amask, 'value': out.get('value'),
                    'player': player, 'done': done,
                    'outcome': env_mod.outcome(nstate),
                }
                nstate = env_mod.auto_reset(nstate, done)
                return (nstate, rng), record

            (state, rng), records = jax.lax.scan(
                body, (state, rng), None, length=chunk_steps)
            return state, rng, records

        self._rollout = rollout

    def step_chunk(self) -> List[dict]:
        """Run one compiled chunk; return episodes completed within it."""
        self.state, self.rng, records = self._rollout(
            self.wrapper.params, self.state, self.rng)
        rec = map_structure(np.asarray, dict(records))

        players = list(range(self.env_mod.NUM_PLAYERS))
        episodes = []
        for k in range(self.chunk_steps):
            for i in range(self.n_envs):
                player = int(rec['player'][k, i])
                moment = {key: {p: None for p in players} for key in
                          ('observation', 'selected_prob', 'action_mask',
                           'action', 'value', 'reward', 'return')}
                moment['observation'][player] = rec['obs'][k, i]
                moment['selected_prob'][player] = float(rec['prob'][k, i])
                moment['action_mask'][player] = rec['amask'][k, i]
                moment['action'][player] = int(rec['action'][k, i])
                if rec.get('value') is not None:
                    moment['value'][player] = rec['value'][k, i]
                moment['reward'] = {p: None for p in players}
                moment['turn'] = [player]
                self._partials[i].append(moment)

                if rec['done'][k, i]:
                    moments = self._partials[i]
                    self._partials[i] = []
                    outcome = {p: float(rec['outcome'][k, i, p])
                               for p in players}
                    for p in players:
                        ret = 0.0
                        for t in range(len(moments) - 1, -1, -1):
                            ret = (moments[t]['reward'][p] or 0) \
                                + self.args['gamma'] * ret
                            moments[t]['return'][p] = ret
                    episodes.append({
                        'args': {'role': 'g', 'player': players,
                                 'model_id': {p: -1 for p in players}},
                        'steps': len(moments),
                        'outcome': outcome,
                        'moment': compress_moments(
                            moments, self.args['compress_steps']),
                    })
        return episodes
