"""Process/host communication substrate.

Counterpart of the reference's connection layer (connection.py): 4-byte
big-endian length-framed messages over TCP sockets plus mp.Pipe fan-out for
same-host workers, thread-multiplexed into queues.

Payloads are serialized with pickle — only ever our own episode/result dicts
of numpy arrays between our own processes. Model parameters specifically are
shipped as msgpack bytes + architecture name inside those dicts (see
model.ModelWrapper.snapshot), never as pickled code objects, so a model
snapshot cannot execute anything on load.
"""

from __future__ import annotations

import io
import multiprocessing as mp
import multiprocessing.connection as mp_connection
import pickle
import queue
import socket
import struct
import threading
from typing import Callable, Iterator, List, Optional


def send_recv(conn, data):
    conn.send(data)
    return conn.recv()


def force_cpu_backend():
    """Pin this (sub)process's JAX to the CPU backend.

    Worker/eval processes must never claim the TPU: the learner holds the
    single device, and the TPU plugin blocks a second client forever. Called
    at the top of every child-process entry point. The explicit config
    update is required because the axon site hook overrides JAX_PLATFORMS at
    import time.
    """
    import os
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass


class FramedConnection:
    """Length-framed messages over a stream socket."""

    def __init__(self, sock: socket.socket):
        self.conn: Optional[socket.socket] = sock

    def __del__(self):
        self.close()

    def close(self):
        if self.conn is not None:
            self.conn.close()
            self.conn = None

    def fileno(self) -> int:
        return self.conn.fileno()

    def _recv_exact(self, size: int) -> bytes:
        buf = io.BytesIO()
        while size > 0:
            chunk = self.conn.recv(size)
            if len(chunk) == 0:
                raise ConnectionResetError
            size -= len(chunk)
            buf.write(chunk)
        return buf.getvalue()

    def recv(self):
        (size,) = struct.unpack('!i', self._recv_exact(4))
        return pickle.loads(self._recv_exact(size))

    def send(self, msg):
        payload = pickle.dumps(msg)
        self.conn.sendall(struct.pack('!i', len(payload)) + payload)


def open_socket_connection(port: int) -> socket.socket:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind(('', int(port)))
    return sock


def connect_socket_connection(host: str, port: int) -> FramedConnection:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.connect((host, int(port)))
    except ConnectionRefusedError:
        print('failed to connect %s %d' % (host, port))
    return FramedConnection(sock)


def accept_socket_connections(port: int, timeout: Optional[float] = None,
                              maxsize: int = 1024
                              ) -> Iterator[Optional[FramedConnection]]:
    sock = open_socket_connection(port)
    sock.listen(maxsize)
    sock.settimeout(timeout)
    count = 0
    while count < maxsize:
        try:
            conn, _ = sock.accept()
            count += 1
            yield FramedConnection(conn)
        except socket.timeout:
            yield None


def open_multiprocessing_connections(num_process: int, target: Callable,
                                     args_func: Callable) -> List:
    """Start ``num_process`` workers, each holding one end of an mp.Pipe;
    returns the parent-side ends.

    Uses the 'spawn' context: a forked child would inherit the parent's
    initialized JAX backend (possibly the exclusive TPU client); a spawned
    child starts clean and pins itself to CPU via force_cpu_backend().
    """
    ctx = mp.get_context('spawn')
    parent_conns = []
    for i in range(num_process):
        conn0, conn1 = ctx.Pipe(duplex=True)
        ctx.Process(target=target, args=args_func(i, conn1)).start()
        conn1.close()
        parent_conns.append(conn0)
    return parent_conns


class MultiProcessJobExecutor:
    """Round-robin job fan-out over worker processes.

    A sender thread feeds the next item from ``send_generator`` to any free
    worker; a receiver thread multiplexes results into a bounded queue.
    """

    def __init__(self, func: Callable, send_generator, num_workers: int,
                 postprocess: Optional[Callable] = None, out_maxsize: int = 8):
        self.send_generator = send_generator
        self.postprocess = postprocess
        self.conns: List = []
        self.waiting_conns: queue.Queue = queue.Queue()
        self.output_queue: queue.Queue = queue.Queue(maxsize=out_maxsize)

        ctx = mp.get_context('spawn')   # never fork a TPU-holding parent
        for i in range(num_workers):
            conn0, conn1 = ctx.Pipe(duplex=True)
            ctx.Process(target=func, args=(conn1, i), daemon=True).start()
            conn1.close()
            self.conns.append(conn0)
            self.waiting_conns.put(conn0)

    def recv(self):
        return self.output_queue.get()

    def start(self):
        threading.Thread(target=self._sender, daemon=True).start()
        threading.Thread(target=self._receiver, daemon=True).start()

    def _sender(self):
        while True:
            data = next(self.send_generator)
            conn = self.waiting_conns.get()
            conn.send(data)

    def _receiver(self):
        while True:
            for conn in mp_connection.wait(self.conns):
                data = conn.recv()
                self.waiting_conns.put(conn)
                if self.postprocess is not None:
                    data = self.postprocess(data)
                self.output_queue.put(data)


class QueueCommunicator:
    """Bidirectional multiplexer over a dynamic set of connections.

    Dead connections (reset/EOF/broken pipe) are dropped silently — workers
    are elastic by design; the server keys only on connection_count().
    """

    def __init__(self, conns: Optional[List] = None, maxsize: int = 256):
        self.input_queue: queue.Queue = queue.Queue(maxsize=maxsize)
        self.output_queue: queue.Queue = queue.Queue(maxsize=maxsize)
        self.conns: set = set()
        for conn in conns or []:
            self.add_connection(conn)
        threading.Thread(target=self._send_thread, daemon=True).start()
        threading.Thread(target=self._recv_thread, daemon=True).start()

    def connection_count(self) -> int:
        return len(self.conns)

    def recv(self, timeout: Optional[float] = None):
        return self.input_queue.get(timeout=timeout)

    def send(self, conn, data):
        self.output_queue.put((conn, data))

    def add_connection(self, conn):
        self.conns.add(conn)

    def disconnect(self, conn):
        print('disconnected')
        self.conns.discard(conn)

    def _send_thread(self):
        while True:
            conn, data = self.output_queue.get()
            try:
                conn.send(data)
            except (TimeoutError, ConnectionResetError, BrokenPipeError):
                self.disconnect(conn)

    def _recv_thread(self):
        while True:
            conns = mp_connection.wait(self.conns, timeout=0.3)
            for conn in conns:
                try:
                    data = conn.recv()
                except (TimeoutError, ConnectionResetError, EOFError, OSError):
                    self.disconnect(conn)
                    continue
                self.input_queue.put((conn, data))
