"""Transport substrate: framed sockets, pipe workers, and an event-loop hub.

Round-2 redesign of the communication layer. The wire format keeps the
reference-compatible 4-byte big-endian length framing (reference
connection.py:45-69 uses the same header), but everything else is built
differently:

* **Data-only codec.** Socket payloads are msgpack with an ndarray
  extension type instead of pickle. A crafted frame from a network peer can
  only ever decode to plain data — never to a code object — which closes the
  remote-code-execution hole pickle leaves open on the public worker/eval
  ports (9999/9998/9876). Same-host ``mp.Pipe`` endpoints keep mp's native
  transport (kernel-mediated, same-user only).

* **One event loop, not thread pairs.** ``Hub`` multiplexes any number of
  heterogeneous endpoints (sockets and pipes) on a single ``selectors`` loop
  with a self-wake pipe, per-endpoint outboxes, and command-queue attach /
  detach — replacing the reference's two-threads-plus-0.3s-poll
  QueueCommunicator design. Dead peers are detached on read/write errors;
  peers are elastic by design.

* **Demand-driven job dispatch.** ``JobPool`` primes each spawned worker
  with one job and hands out the next the moment a result returns — a single
  dispatcher thread with backpressure from the bounded result queue, instead
  of separate sender/receiver threads with a free-connection queue.
"""

from __future__ import annotations

import os
import queue
import select
import selectors
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import msgpack
import numpy as np

from . import telemetry

_HEADER = struct.Struct('!i')
_EXT_NDARRAY = 1

# transport-level flow counters (no-ops when telemetry is disabled): every
# framed socket send/recv in the process adds here, so a gather's heartbeat
# snapshot carries its true wire traffic and the learner sees fleet totals
_NET_TX = telemetry.counter('net_bytes_sent_total')
_NET_RX = telemetry.counter('net_bytes_recv_total')
_NET_FRAMES_TX = telemetry.counter('net_frames_sent_total')
_LOG = telemetry.get_logger('connection')


# ---------------------------------------------------------------------------
# codec


def _encode_ext(obj):
    if isinstance(obj, np.ndarray):
        header = msgpack.packb([obj.dtype.str, list(obj.shape)],
                               use_bin_type=True)
        return msgpack.ExtType(
            _EXT_NDARRAY, header + np.ascontiguousarray(obj).tobytes())
    if isinstance(obj, np.generic):      # numpy scalar -> python scalar
        return obj.item()
    raise TypeError('refusing to serialize %r (data-only codec)' % type(obj))


def _decode_ext(code, data):
    if code == _EXT_NDARRAY:
        unpacker = msgpack.Unpacker(use_list=True, raw=False)
        unpacker.feed(data)
        dtype_str, shape = unpacker.unpack()
        arr = np.frombuffer(data[unpacker.tell():], dtype=np.dtype(dtype_str))
        return arr.reshape(shape).copy()
    return msgpack.ExtType(code, data)


def pack(msg) -> bytes:
    """Serialize a message for the wire (msgpack + an ndarray extension).

    Tuples normalize to lists across a socket hop — every protocol message
    is a ``(kind, payload)`` pair and all receive sites sequence-unpack, so
    the normalization is observable but harmless by design.
    """
    return msgpack.packb(msg, default=_encode_ext, use_bin_type=True)


def unpack(payload: bytes):
    """Inverse of :func:`pack`. Decodes only data — never code objects."""
    return msgpack.unpackb(payload, ext_hook=_decode_ext, raw=False,
                           strict_map_key=False, use_list=True)


# ---------------------------------------------------------------------------
# endpoints


MAX_FRAME_BYTES = 256 * (1 << 20)   # largest legal payload (256 MiB)


class FrameParser:
    """Incremental splitter of a byte stream into length-framed payloads.

    Frame lengths are attacker-controlled on the public ports, so they are
    validated before any buffering commitment: a negative or oversized
    header is a protocol violation and poisons the connection (the caller's
    error handling detaches the peer) instead of letting a crafted header
    pin gigabytes per connection or desync the stream."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        frames = []
        while True:
            if len(self._buf) < _HEADER.size:
                break
            (n,) = _HEADER.unpack_from(self._buf)
            if n < 0 or n > MAX_FRAME_BYTES:
                raise ConnectionResetError(
                    'protocol violation: frame length %d' % n)
            if len(self._buf) < _HEADER.size + n:
                break
            frames.append(bytes(self._buf[_HEADER.size:_HEADER.size + n]))
            del self._buf[:_HEADER.size + n]
        return frames


class FramedConnection:
    """Duplex message endpoint over a stream socket.

    Blocking ``send``/``recv`` serve call-response clients; ``drain`` serves
    the Hub's non-blocking read path via the incremental FrameParser.
    """

    def __init__(self, sock: socket.socket):
        self.sock: Optional[socket.socket] = sock
        self._parser = FrameParser()
        self._ready: deque = deque()
        # serialize concurrent senders (e.g. a gather's main RPC loop and
        # its heartbeat thread): interleaved sendall calls would splice two
        # frames together and desync the stream
        self._send_lock = threading.Lock()

    def fileno(self) -> int:
        return self.sock.fileno()

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    __del__ = close

    def send(self, msg):
        payload = pack(msg)
        if len(payload) > MAX_FRAME_BYTES:
            raise ValueError('message of %d bytes exceeds the frame limit'
                             % len(payload))
        with self._send_lock:
            self.sock.sendall(_HEADER.pack(len(payload)) + payload)
        _NET_TX.inc(_HEADER.size + len(payload))
        _NET_FRAMES_TX.inc()

    @staticmethod
    def _decode(payload: bytes):
        """A frame that passed the length check can still carry garbage; any
        decode failure poisons the connection (callers detach/close) rather
        than leaking arbitrary exceptions into multiplexer threads."""
        try:
            return unpack(payload)
        except Exception as exc:
            raise ConnectionResetError('undecodable frame (%s: %s)'
                                       % (type(exc).__name__,
                                          str(exc)[:80])) from exc

    def recv(self):
        if self._ready:
            return self._decode(self._ready.popleft())
        while not self._ready:
            chunk = self.sock.recv(1 << 16)
            if not chunk:
                raise ConnectionResetError('peer closed')
            _NET_RX.inc(len(chunk))
            self._ready.extend(self._parser.feed(chunk))
        return self._decode(self._ready.popleft())

    def poll(self, timeout: float = 0.0) -> bool:
        """True when a recv() would find data (a complete frame already
        buffered, or socket bytes ready within ``timeout`` seconds) — the
        deadline primitive the timeout-bounded clients (EngineClient's
        remote-service path, ServiceClient) build on, matching the
        ``mp.Connection.poll`` surface PipeEndpoint exposes."""
        if self._ready:
            return True
        if self.sock is None:
            return False
        readable, _, _ = select.select([self.sock], [], [],
                                       max(0.0, float(timeout)))
        return bool(readable)

    def drain(self) -> List[Any]:
        """Non-blocking read of everything currently available."""
        try:
            chunk = self.sock.recv(1 << 16, socket.MSG_DONTWAIT)
        except (BlockingIOError, InterruptedError):
            return []
        if not chunk:
            raise ConnectionResetError('peer closed')
        _NET_RX.inc(len(chunk))
        self._ready.extend(self._parser.feed(chunk))
        out = [self._decode(p) for p in self._ready]
        self._ready.clear()
        return out


class PipeEndpoint:
    """Adapter giving an ``mp.Connection`` the same endpoint surface."""

    def __init__(self, conn):
        self.conn = conn

    def fileno(self) -> int:
        return self.conn.fileno()

    def close(self):
        self.conn.close()

    def send(self, msg):
        self.conn.send(msg)

    def recv(self):
        return self.conn.recv()

    def drain(self) -> List[Any]:
        out = []
        while self.conn.poll(0):
            out.append(self.conn.recv())
        return out


def send_recv(conn, msg):
    conn.send(msg)
    return conn.recv()


# ---------------------------------------------------------------------------
# sockets


def open_socket_connection(port: int) -> socket.socket:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind(('', int(port)))
    return sock


def connect_socket_connection(host: str, port: int) -> FramedConnection:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.connect((host, int(port)))
    return FramedConnection(sock)


def accept_socket_connections(port: int, timeout: Optional[float] = None,
                              maxsize: int = 1024
                              ) -> Iterator[Optional[FramedConnection]]:
    """Yield one FramedConnection per accepted client; None on idle timeout."""
    sock = open_socket_connection(port)
    sock.listen(maxsize)
    sock.settimeout(timeout)
    accepted = 0
    while accepted < maxsize:
        try:
            conn, _ = sock.accept()
        except socket.timeout:
            yield None
            continue
        accepted += 1
        yield FramedConnection(conn)


# ---------------------------------------------------------------------------
# event-loop hub


_WRITER_EXIT = object()   # per-endpoint writer shutdown sentinel

# Heartbeat frames are a one-way liveness beacon from blocking RPC clients
# (gathers) to the Hub: the Hub refreshes the sender's liveness deadline,
# records the payload (client-side fleet stats), and never replies — a
# reply would land in the middle of the client's call-response stream.
HEARTBEAT_KIND = '__hb__'

# Inference-service frames (inference.py): an engine-mode worker's
# ``(INFER_KIND, request)`` rides its existing pipe to the host relay,
# multiplexed by the relay's Hub event loop alongside the task RPCs; the
# engine's reply is posted back through the same per-endpoint outbox AS A
# ``(INFER_KIND, reply)`` frame. Tagging replies matters for self-healing:
# a worker that timed out on a request and failed over to local inference
# may receive the engine's late answer at ANY later point — including in
# the middle of an args/episode/model call-response — and must be able to
# recognize and absorb it instead of mistaking it for the RPC's reply
# (inference.EngineClient.rpc does exactly that, via ``is_infer``).
INFER_KIND = '__infer__'

# Resume-token handshake (docs/large_scale_training.md "Zero-loss training
# plane"): a reconnecting gather's FIRST frame after a redial is a
# ``(RESUME_KIND, {gather, run_id, generation})`` RPC. A restarted learner
# that recognizes the run_id replies ``{'ok': True, 'run_id', 'generation'}``
# and the gather reattaches in place — resend buffer replayed, nothing
# respawned. A learner that predates the handshake (or a different run)
# answers with something else, which the gather treats as "cold respawn"
# — today's behavior, so mixed-version fleets keep working.
RESUME_KIND = '__resume__'

# Serving-path trace context rides INSIDE the INFER/admin body dict under
# this key (docs/observability.md, "Serving-path tracing"): extra dict keys
# are ignored by peers that predate it, so absent context simply means
# "unsampled" — no wire-format break, old and new peers interoperate.
TRACE_KEY = 'trace'


def is_heartbeat(msg) -> bool:
    return (isinstance(msg, (list, tuple)) and len(msg) == 2
            and msg[0] == HEARTBEAT_KIND)


def is_infer(msg) -> bool:
    """True for an inference-service frame (request or reply)."""
    return (isinstance(msg, (list, tuple)) and len(msg) == 2
            and msg[0] == INFER_KIND)


def _describe(endpoint) -> str:
    """Human identity of an endpoint for disconnect logs."""
    sock = getattr(endpoint, 'sock', None)
    if sock is not None:
        try:
            peer = sock.getpeername()
        except OSError:
            return 'socket peer (already closed)'
        if isinstance(peer, tuple) and len(peer) >= 2:   # AF_INET[6]
            return 'socket peer %s:%s' % peer[:2]
        return 'socket peer %r' % (peer,)                # AF_UNIX et al.
    try:
        return 'pipe fd %d' % endpoint.fileno()
    except Exception:
        return 'endpoint'


class Hub:
    """Message multiplexer: one selector read loop + one writer per endpoint.

    Incoming messages land in one inbox as ``(endpoint, message)``; outgoing
    messages are posted to a PER-ENDPOINT outbox drained by that endpoint's
    own writer thread, so a peer that stops consuming delays only its own
    sends — never another peer's RPC round trip. A stalled peer is detached
    when its socket send exceeds ``SEND_TIMEOUT`` (deadline set on attach)
    or its outbox backs up past ``OUTBOX_MAX`` queued messages. Endpoints
    may be attached / detached from any thread at any time (workers are
    elastic); a failed read or write detaches the endpoint.

    Liveness: socket endpoints additionally carry a per-endpoint deadline —
    a peer that sends NOTHING (not even a ``HEARTBEAT_KIND`` beacon) for
    ``LIVENESS_TIMEOUT`` seconds is presumed silently dead (half-open TCP:
    the remote host vanished without a FIN) and detached, instead of
    holding its slot until some future write happens to fail. Any received
    frame refreshes the deadline; heartbeat frames are filtered out of the
    inbox and their payloads retained per endpoint (``peer_info_snapshot``).
    Pipe endpoints are exempt — a dead pipe peer is always observable as an
    immediate EOF. Every disconnect is counted by reason in ``stats`` and
    journaled for ``drain_detach_events`` (the learner's task ledger feeds
    on it).
    """

    SEND_TIMEOUT = 30.0
    OUTBOX_MAX = 512
    LIVENESS_TIMEOUT = 60.0   # silent-socket-peer deadline; 0 disables

    def __init__(self, endpoints: Optional[List] = None, inbox_max: int = 256):
        self._inbox: queue.Queue = queue.Queue(maxsize=inbox_max)
        # every mutable map below is shared by the read loop, the per-
        # endpoint writers and arbitrary caller threads; one lock guards
        # them all (lexical discipline checked by graftlint GL004)
        self._lock = threading.Lock()
        self._outboxes: Dict[Any, queue.Queue] = {}        # guarded-by: _lock
        self._commands: deque = deque()                    # guarded-by: _lock
        self._liveness: Dict[Any, float] = {}              # guarded-by: _lock
        self._last_recv: Dict[Any, float] = {}             # guarded-by: _lock
        self._peer_info: Dict[Any, Any] = {}               # guarded-by: _lock
        self._detach_events: deque = deque(maxlen=4096)    # guarded-by: _lock
        self.stats: Dict[str, int] = {}                    # guarded-by: _lock
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._selector = selectors.DefaultSelector()
        self._selector.register(self._wake_r, selectors.EVENT_READ, None)
        for ep in endpoints or []:
            self.attach(ep)
        threading.Thread(target=self._read_loop, name='hub-read',
                         daemon=True).start()

    # -- public api (any thread) --

    def count(self) -> int:
        with self._lock:
            return len(self._outboxes)

    # QueueCommunicator-compatible alias used by the learner's server loop
    connection_count = count

    def _bump(self, key: str, n: int = 1):
        """Increment a stats counter (caller holds no lock)."""
        with self._lock:
            self.stats[key] = self.stats.get(key, 0) + n

    def stats_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.stats)

    def peer_info_snapshot(self) -> Dict[Any, Any]:
        """Latest heartbeat payload per live endpoint."""
        with self._lock:
            return dict(self._peer_info)

    def drain_detach_events(self) -> List[Tuple[Any, str, float]]:
        """Consume the (endpoint, reason, time) disconnect journal."""
        with self._lock:
            events = list(self._detach_events)
            self._detach_events.clear()
        return events

    def recv(self, timeout: Optional[float] = None) -> Tuple[Any, Any]:
        return self._inbox.get(timeout=timeout)

    def send(self, endpoint, msg):
        with self._lock:
            outbox = self._outboxes.get(endpoint)
        if outbox is None:      # already detached: drop, like a dead socket
            return
        try:
            outbox.put_nowait(msg)
        except queue.Full:      # peer hopelessly behind — treat as stalled
            self.detach(endpoint, reason='outbox_overflow')

    def attach(self, endpoint, liveness: Optional[float] = None):
        """Register ``endpoint``. ``liveness`` overrides the silent-peer
        deadline in seconds (0 disables); default: ``LIVENESS_TIMEOUT`` for
        socket endpoints, disabled for pipes."""
        sock = getattr(endpoint, 'sock', None)
        if sock is not None:
            sock.settimeout(self.SEND_TIMEOUT)   # bound writer stalls
        if liveness is None:
            liveness = self.LIVENESS_TIMEOUT if sock is not None else 0.0
        outbox: queue.Queue = queue.Queue(maxsize=self.OUTBOX_MAX)
        with self._lock:
            if endpoint in self._outboxes:
                return
            self._outboxes[endpoint] = outbox
            self._liveness[endpoint] = float(liveness or 0.0)
            self._last_recv[endpoint] = time.monotonic()
            self._commands.append(('+', endpoint))
            self.stats['attached'] = self.stats.get('attached', 0) + 1
            telemetry.gauge('hub_peers').set(len(self._outboxes))
        threading.Thread(target=self._write_loop, args=(endpoint, outbox),
                         name='hub-write', daemon=True).start()
        self._wake()

    # API name kept for operator familiarity with the reference logs
    add_connection = attach

    def detach(self, endpoint, reason: str = 'requested'):
        with self._lock:
            outbox = self._outboxes.pop(endpoint, None)
            if outbox is not None:
                self._liveness.pop(endpoint, None)
                self._last_recv.pop(endpoint, None)
                self._peer_info.pop(endpoint, None)
                self._commands.append(('-', endpoint))
                self.stats['detached'] = self.stats.get('detached', 0) + 1
                key = 'disconnect_' + reason
                self.stats[key] = self.stats.get(key, 0) + 1
                self._detach_events.append((endpoint, reason, time.time()))
                telemetry.gauge('hub_peers').set(len(self._outboxes))
        if outbox is None:
            return                        # already gone: count/log only once
        telemetry.counter('hub_disconnects_total', reason=reason).inc()
        _LOG.info('disconnected %s (%s)', _describe(endpoint), reason)
        try:                              # fast writer wake; the writer also
            outbox.put_nowait(_WRITER_EXIT)   # polls attachment, so a
        except queue.Full:                # full outbox can't wedge detach
            pass
        self._wake()

    # -- loop internals --

    def _wake(self):
        try:
            self._wake_w.send(b'.')
        except OSError:
            pass

    def _apply_commands(self):
        while True:
            with self._lock:
                if not self._commands:
                    return
                op, ep = self._commands.popleft()
            try:
                if op == '+':
                    self._selector.register(ep, selectors.EVENT_READ, ep)
                else:
                    self._selector.unregister(ep)
                    ep.close()
            except (KeyError, ValueError, OSError):
                pass

    def _write_loop(self, ep, outbox: queue.Queue):
        """Drain ONE endpoint's outbox; exit when it is detached."""
        while True:
            try:
                msg = outbox.get(timeout=1.0)
            except queue.Empty:
                with self._lock:
                    if self._outboxes.get(ep) is not outbox:
                        return        # detached while idle
                continue
            if msg is _WRITER_EXIT:
                return
            try:
                ep.send(msg)
            except (OSError, ValueError, TimeoutError, AttributeError) as exc:
                # AttributeError: closed while queued
                reason = ('send_timeout'
                          if isinstance(exc, (socket.timeout, TimeoutError))
                          else 'send_error')
                self.detach(ep, reason=reason)
                return

    def _check_liveness(self):
        now = time.monotonic()
        with self._lock:
            stale = [ep for ep, limit in self._liveness.items()
                     if limit > 0 and now - self._last_recv.get(ep, now) > limit]
        for ep in stale:
            self.detach(ep, reason='heartbeat_miss')

    def _read_loop(self):
        while True:
            events = self._selector.select(timeout=0.5)
            for key, _mask in events:
                if key.data is None:        # wake pipe
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                    continue
                ep = key.data
                try:
                    msgs = ep.drain()
                except (ConnectionResetError, EOFError, OSError):
                    self.detach(ep, reason='read_error')
                    continue
                if msgs:
                    with self._lock:
                        if ep in self._last_recv:
                            self._last_recv[ep] = time.monotonic()
                for msg in msgs:
                    if is_heartbeat(msg):
                        with self._lock:
                            self._peer_info[ep] = msg[1]
                            self.stats['heartbeats'] = (
                                self.stats.get('heartbeats', 0) + 1)
                        telemetry.counter('hub_heartbeats_total').inc()
                        continue
                    self._inbox.put((ep, msg))
            self._apply_commands()
            self._check_liveness()


# ---------------------------------------------------------------------------
# process fan-out


def force_cpu_backend():
    """Pin this (sub)process's JAX to the CPU backend.

    Worker/eval processes must never claim the TPU: the learner holds the
    single device, and the TPU plugin blocks a second client forever. Called
    at the top of every child-process entry point. The explicit config
    update is required because the axon site hook overrides JAX_PLATFORMS at
    import time.
    """
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
    # spawned children start with a fresh interpreter: re-enable the shared
    # compile cache so their (CPU) compiles are one-time across the fleet
    from . import setup_compile_cache
    setup_compile_cache()


def spawn_pipe_workers(count: int, target: Callable,
                       make_args: Callable[[int, Any], tuple],
                       daemon: bool = False) -> List[PipeEndpoint]:
    """Spawn ``count`` processes, each holding one end of a duplex pipe.

    Uses the 'spawn' context: a forked child would inherit the parent's
    initialized JAX backend (possibly the exclusive TPU client); a spawned
    child starts clean and pins itself to CPU via force_cpu_backend().
    Returns the parent-side pipe endpoints.
    """
    import multiprocessing as mp
    ctx = mp.get_context('spawn')
    parents = []
    for i in range(count):
        ours, theirs = ctx.Pipe(duplex=True)
        ctx.Process(target=target, args=make_args(i, theirs),
                    daemon=daemon).start()
        theirs.close()
        parents.append(PipeEndpoint(ours))
    return parents


class JobPool:
    """Fan jobs out to spawned worker processes, demand-driven.

    ``job_source`` is an iterator of job payloads; ``worker_fn(conn, idx)``
    is the child entry point (recv job -> send result, forever). One
    dispatcher thread keeps every child busy: each result immediately buys
    its sender the next job, then lands (optionally transformed) in
    ``results`` — whose bound provides the backpressure.
    """

    def __init__(self, worker_fn: Callable, job_source, num_workers: int,
                 transform: Optional[Callable] = None, results_max: int = 8):
        self._jobs = job_source
        self._transform = transform
        self.results: queue.Queue = queue.Queue(maxsize=results_max)
        self._endpoints = spawn_pipe_workers(
            num_workers, worker_fn, lambda i, c: (c, i), daemon=True)
        # mp.Connection.send is not thread-safe: the dispatcher thread and
        # out-of-band senders (send_to, e.g. shared-memory slot releases
        # from the trainer thread) serialize per endpoint
        self._send_locks = [threading.Lock() for _ in self._endpoints]

    # Batcher compatibility: the learner reads .output_queue
    @property
    def output_queue(self) -> queue.Queue:
        return self.results

    def start(self):
        threading.Thread(target=self._dispatch, name='jobpool-dispatch',
                         daemon=True).start()

    def recv(self):
        return self.results.get()

    def send_to(self, idx: int, msg):
        """Out-of-band message to worker ``idx`` (any thread); best-effort —
        a dead worker's pipe error is swallowed like a dead socket's."""
        try:
            with self._send_locks[idx]:
                self._endpoints[idx].send(msg)
        except (OSError, ValueError, BrokenPipeError):
            pass

    def _dispatch(self):
        import multiprocessing.connection as mpc
        for i, ep in enumerate(self._endpoints):
            with self._send_locks[i]:
                ep.send(next(self._jobs))
        live = {ep.conn: (i, ep) for i, ep in enumerate(self._endpoints)}
        while live:
            for conn in mpc.wait(list(live)):
                i, ep = live[conn]
                try:
                    result = ep.recv()
                except (EOFError, OSError):
                    del live[conn]
                    continue
                with self._send_locks[i]:     # refill before the maybe-block
                    ep.send(next(self._jobs))
                if self._transform is not None:
                    result = self._transform(result)
                self.results.put(result)
