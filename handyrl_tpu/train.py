"""Learner orchestration: trainer loop, batch prefetch, epoch cadence.

Architecture (counterpart of the reference train.py, reshaped for TPU):

  * ``Trainer`` — background thread owning the jit/pjit-compiled update step
    (ops/train_step.py). The Adam step, clipping, and losses all live on
    device; the host only feeds batches and the EMA-scheduled learning rate
    (lr = 3e-8 * data_cnt_ema / (1 + steps*1e-5), reference
    train.py:327-331,382-384). On a multi-device mesh the batch is sharded
    over 'data' and XLA all-reduces gradients over ICI (replacing
    nn.DataParallel).

  * ``Batcher`` — prefetch threads turning buffered episodes into batches
    (recency-biased window sampling, ops/batch.py) ahead of the update step.

  * ``Learner`` — episode/eval accounting, epoch cadence (update every
    ``update_episodes`` returned episodes), checkpointing
    (models/<epoch>.ckpt msgpack params — loading cannot execute code), and
    two generation front-ends:
      - in-process ``BatchedGenerator`` (TPU-first default): N envs against
        one batched device inference;
      - the 4-RPC worker protocol ('args'/'episode'/'result'/'model') over
        WorkerCluster (local processes) or WorkerServer (remote hosts),
        wire-compatible in shape with the reference (train.py:541-627).

Log line formats (epoch / win rate / generation stats / loss / updated
model) match the reference so its plot tooling carries over (SURVEY.md §5.5).
"""

from __future__ import annotations

import json
import os
import queue
import random
import threading
import time
import warnings
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import psutil

from . import guard as guard_mod
from . import league as league_mod
from . import telemetry
from .connection import RESUME_KIND
from .connection import pack as conn_pack
from .connection import unpack as conn_unpack
from .environment import make_env, prepare_env
from .fault import FleetController, LedgerJournal, TaskLedger
from .generation import BatchedEvaluator, BatchedGenerator
from .model import ModelWrapper
from .ops.batch import make_batch, select_episode
from .ops.losses import LossConfig
from .ops.train_step import TrainState, build_update_step, init_train_state
from .parallel.mesh import make_mesh, shard_batch
from .spool import EpisodeSpool
from .utils.fetch import put_tree
from .utils.fs import append_jsonl, atomic_write_bytes, \
    checksummed_write_bytes, rotate_file
from .worker import WorkerCluster, WorkerServer

_LOG = telemetry.get_logger('train')


class TracedBatch:
    """A built batch plus the sampled episode trace ids of the windows in
    it — the thread-batcher's counterpart of SharedBatch.trace_ids, wrapped
    only while episode tracing is active so the hot path stays untouched
    when it is off."""

    __slots__ = ('batch', 'trace_ids')

    def __init__(self, batch, trace_ids):
        self.batch = batch
        self.trace_ids = trace_ids


def _selected_trace_ids(selected) -> List[str]:
    """Deduplicated, deterministically-sampled trace ids of the episodes a
    batch's windows were selected from (recency bias repeats episodes)."""
    out = []
    for sel in selected:
        tid = telemetry.episode_trace_id(sel.get('args') or {})
        if tid and telemetry.trace_sampled(tid):
            out.append(tid)
    return sorted(set(out))


def _batcher_process(conn, bid: int):
    """Child-process batch builder (config: batcher_processes=True)."""
    from .connection import force_cpu_backend
    force_cpu_backend()
    from .ops.batch import make_block_cache
    telemetry.set_process_label('batcher-%d' % bid)
    _LOG.info('started batcher process %d', bid)
    cache, have_cache = None, False
    while True:
        selected, args = conn.recv()
        if not have_cache:
            cache, have_cache = make_block_cache(args), True
        conn.send(make_batch(selected, args, cache=cache))


_SHM_SLOTS = 4   # in-flight shared-memory batches per batcher child


def _is_free_msg(msg) -> bool:
    return (isinstance(msg, tuple) and len(msg) == 2
            and msg[0] == '__free__')


def _batcher_process_shm(conn, bid: int):
    """Child-process batch builder writing into shared-memory arenas
    (config: batcher_processes + batcher_shared_memory).

    Batches are assembled IN PLACE in a small ring of SharedMemory slots;
    only a slot descriptor crosses the pipe — no pickle, no copy. The first
    batch bootstraps the layout: it is built host-side, sized into the ring
    (spec + segment names ride along in its descriptor), and copied in
    once. A slot is reused only after the trainer's ``('__free__', slot)``
    message confirms the staged device transfer read it.
    """
    from .connection import force_cpu_backend
    force_cpu_backend()
    from .ops.shm_batch import ArenaRing, batch_spec, copy_into
    from .utils.timing import StageTimer
    telemetry.set_process_label('batcher-%d' % bid)
    _LOG.info('started shm batcher process %d', bid)
    from .ops.batch import make_block_cache
    ring = None
    timer = StageTimer()
    cache, have_cache = None, False

    def recv_job():
        while True:
            msg = conn.recv()
            if _is_free_msg(msg):
                ring.release(msg[1])
                continue
            return msg

    def acquire_slot():
        slot = ring.acquire()
        while slot is None:   # all slots in flight: block on a free message
            msg = conn.recv()
            if not _is_free_msg(msg):
                raise RuntimeError('expected a slot-free message, got %r'
                                   % (msg,))
            ring.release(msg[1])
            slot = ring.acquire()
        return slot

    try:
        while True:
            selected, args = recv_job()
            desc = {'bid': bid}
            if not have_cache:
                cache, have_cache = make_block_cache(args), True
            if ring is None:
                batch = make_batch(selected, args, timer=timer, cache=cache)
                ring = ArenaRing(batch_spec(batch), slots=_SHM_SLOTS)
                slot = ring.acquire()
                copy_into(ring.views[slot], batch)
                desc['spec'] = ring.spec
                desc['names'] = ring.names
            else:
                slot = acquire_slot()
                make_batch(selected, args, out=ring.views[slot], timer=timer,
                           cache=cache)
            desc['slot'] = slot
            desc['timing'] = timer.snapshot(reset=True)
            if telemetry.trace_enabled():
                # sampled episode ids of this slot's windows: the trainer's
                # train_step trace event links back through them
                desc['trace'] = _selected_trace_ids(selected)
            conn.send(desc)
    finally:
        # this process OWNS the segments: unlink them on any exit (pipe
        # EOF, crash, ...) so an aborted run strands nothing in /dev/shm
        if ring is not None:
            ring.close()


class Batcher:
    """Batch prefetcher over the shared episode deque.

    Default: prefetch threads (bz2/numpy release the GIL for the heavy
    parts). With ``batcher_processes: True``, window selection stays in the
    learner process and make_batch fans out to spawned CPU processes via
    JobPool — the reference's num_batchers subprocess layout
    (train.py:270-318). ``batcher_shared_memory: True`` additionally swaps
    the pickled batch-over-pipe return for shared-memory arenas the
    children fill in place (ops/shm_batch.py): ``batch()`` then yields
    ``SharedBatch`` wrappers whose ``release()`` hands the slot back.

    ``timer`` (utils.timing.StageTimer) aggregates the select/decode/
    assemble stage breakdown across all batcher threads/processes;
    ``build_fn`` swaps the batch builder (bench.py's ingest benchmark pins
    the reference builder as its denominator through the SAME machinery).
    """

    def __init__(self, args: Dict[str, Any], episodes: deque,
                 timer=None, build_fn=None):
        self.args = args
        self.episodes = episodes
        self.timer = timer
        self.build_fn = build_fn or make_batch
        # decoded-block LRU shared by every batcher THREAD (each spawned
        # process keeps its own); recency-biased selection re-reads the
        # same episodes constantly, so steady-state decode cost ~vanishes
        from .ops.batch import make_block_cache
        self.cache = make_block_cache(args)
        self.output_queue: queue.Queue = queue.Queue(maxsize=8)
        self._started = False
        self.stop_flag = False
        self._threads: List[threading.Thread] = []
        self._executor = None
        self._arena_map = None
        self._shm_layouts: Dict[int, tuple] = {}
        # policy-lag accounting: window SELECTION is the consumption point,
        # so lag-in-epochs (learner epoch - the model_id that generated the
        # episode) and age-in-seconds (now - learner ingest stamp) are
        # observed here, for every selection path (threads and processes).
        # ``epoch_fn`` is installed by the Learner (it owns model_epoch).
        self.epoch_fn = None
        self._m_lag = telemetry.REGISTRY.histogram(
            'policy_lag_epochs', buckets=telemetry.LAG_EPOCH_BUCKETS)
        self._m_age = telemetry.REGISTRY.histogram(
            'sample_age_seconds', buckets=telemetry.AGE_SECOND_BUCKETS)

    def _observe_lag(self, selected):
        fn = self.epoch_fn
        if fn is None or not telemetry.enabled():
            return
        epoch, now = int(fn()), time.time()
        for sel in selected:
            args = sel.get('args') or {}
            for mid in (args.get('model_id') or {}).values():
                if mid is None or mid < 0:
                    continue
                self._m_lag.observe(max(0, epoch - int(mid)))
            rt = sel.get('recv_time')
            if rt is not None:
                self._m_age.observe(max(0.0, now - float(rt)))

    def _selector(self):
        while True:
            t0 = time.perf_counter()
            try:
                selected = [select_episode(self.episodes, self.args)
                            for _ in range(self.args['batch_size'])]
            except (IndexError, ValueError):   # buffer transiently empty
                time.sleep(0.1)
                continue
            if self.timer is not None:
                self.timer.add('select', time.perf_counter() - t0)
            self._observe_lag(selected)
            # strip non-picklable/irrelevant entries from the job payload
            job_args = {k: v for k, v in self.args.items()
                        if k in ('turn_based_training', 'observation',
                                 'forward_steps', 'burn_in_steps',
                                 'compress_steps', 'maximum_episodes',
                                 'decode_cache_blocks')}
            yield (selected, job_args)

    def run(self):
        if self._started:
            return
        self._started = True
        if self.args.get('batcher_processes'):
            from .connection import JobPool
            if self.args.get('batcher_shared_memory'):
                from .ops.shm_batch import ArenaMap
                self._arena_map = ArenaMap()
                self._executor = JobPool(
                    _batcher_process_shm, self._selector(),
                    self.args['num_batchers'], transform=self._map_shm)
            else:
                self._executor = JobPool(
                    _batcher_process, self._selector(),
                    self.args['num_batchers'])
            self._executor.start()
            return
        for i in range(self.args['num_batchers']):
            t = threading.Thread(target=self._worker, args=(i,),
                                 name='batcher-%d' % i, daemon=True)
            t.start()
            self._threads.append(t)

    def _map_shm(self, desc):
        """Turn a child's slot descriptor into a zero-copy SharedBatch
        (runs in the JobPool dispatcher thread)."""
        from .ops.shm_batch import SharedBatch
        bid = desc['bid']
        if 'spec' in desc:
            self._shm_layouts[bid] = (desc['spec'], desc['names'])
        spec, names = self._shm_layouts[bid]
        views = self._arena_map.attach(names[desc['slot']], spec)
        if self.timer is not None and desc.get('timing'):
            for stage, row in desc['timing'].items():
                self.timer.add(stage, row['s'], int(row['n']))
        pool, slot = self._executor, desc['slot']
        return SharedBatch(views,
                           lambda: pool.send_to(bid, ('__free__', slot)),
                           trace_ids=desc.get('trace'))

    def _worker(self, bid: int):
        _LOG.info('started batcher %d', bid)
        while not self.stop_flag:
            try:
                t0 = time.perf_counter()
                selected = [select_episode(self.episodes, self.args)
                            for _ in range(self.args['batch_size'])]
                if self.timer is not None:
                    self.timer.add('select', time.perf_counter() - t0)
                self._observe_lag(selected)
                batch = self.build_fn(selected, self.args, timer=self.timer,
                                      cache=self.cache)
                if telemetry.trace_enabled():
                    batch = TracedBatch(batch, _selected_trace_ids(selected))
            except (IndexError, ValueError):
                time.sleep(0.1)
                continue
            while not self.stop_flag:
                try:
                    self.output_queue.put(batch, timeout=0.5)
                    break
                except queue.Full:
                    continue

    def batch(self, timeout: Optional[float] = None):
        q = (self._executor.output_queue if self._executor is not None
             else self.output_queue)
        telemetry.gauge('batcher_queue_depth').set(q.qsize())
        return q.get(timeout=timeout)

    def stop(self):
        self.stop_flag = True
        for t in self._threads:
            t.join(timeout=5)
        # NOTE: the shared-memory mappings (_arena_map) are deliberately NOT
        # closed here — the trainer thread may still be staging a mapped
        # batch (device_put reads the pages) when shutdown begins, and
        # unmapping under it is a segfault. The set of segments is small
        # and fixed (num_batchers x _SHM_SLOTS); the OS reclaims them at
        # process exit, and the children's resource trackers unlink the
        # names when the (daemon) children die with us.


class Trainer:
    """SGD loop thread: compiled update step + EMA learning-rate schedule."""

    def __init__(self, args: Dict[str, Any], wrapper: ModelWrapper):
        self.args = args
        self.wrapper = wrapper
        self.episodes: deque = deque()
        self.cfg = LossConfig.from_args(args)
        self.device_cfg = self.cfg   # may be relayered by the ingest gate

        # mesh construction: the 'data' axis carries the batch, the 'model'
        # axis (config parallel.model_parallel) is reserved for tensor-
        # parallel partition rules. jax.devices() is the GLOBAL set, so on
        # a multi-host job (parallel/multihost.py initialized by
        # train_main) the mesh spans every process's devices.
        par = args.get('parallel') or {}
        model_parallel = max(1, int(par.get('model_parallel') or 1))
        n_dev = len(jax.devices())
        self.mesh = None
        if n_dev > 1:
            data_size = n_dev // model_parallel
            if n_dev % model_parallel != 0:
                _LOG.warning('parallel.model_parallel %d does not divide '
                             '%d devices; training on a single device',
                             model_parallel, n_dev)
            elif args['batch_size'] % data_size == 0:
                self.mesh = make_mesh(model_parallel=model_parallel)
            else:
                _LOG.warning('batch_size %d not divisible by the %d-way '
                             'data axis; training on a single device',
                             args['batch_size'], data_size)
        self.state: Optional[TrainState] = None
        if wrapper.params is not None:
            own_params = jax.tree_util.tree_map(jnp.array, wrapper.params)
            self.state = init_train_state(own_params)
        # partition rules (parallel/partition.py): regex over the named
        # param/optimizer/batch-stats pytree -> replicate-vs-sharded specs.
        # The derived NamedSharding pytree types the compiled train steps'
        # inputs AND outputs, and is what checkpoints describe in their
        # layout manifest.
        from .parallel.partition import rules_from_config, tree_shardings
        self.partition_rules = rules_from_config(args)
        self.state_sharding = None
        if self.mesh is not None and self.state is not None:
            self.state_sharding = tree_shardings(self.mesh, self.state,
                                                 self.partition_rules)
        # IMPACT clipped target network (streaming.target_clip > 0): the
        # update step takes a frozen params copy whose ratios drive the
        # V-Trace targets (ops/losses.py). Deliberately NOT checkpointed:
        # at restart it re-initializes from the loaded params — one epoch
        # of target lag lost, no checkpoint format change. The fused replay
        # trainer has no target variant, so replay mode ignores the knob.
        stm = args.get('streaming') or {}
        self._use_target = float(stm.get('target_clip') or 0.0) > 0
        if self._use_target and args.get('device_replay'):
            _LOG.warning('streaming.target_clip is ignored in device_replay '
                         'mode (the fused trainer has no target variant)')
            self._use_target = False
        self.target_params = None
        self.target_sync_epochs = max(
            1, int(stm.get('target_sync_epochs') or 1))
        self._target_age_epochs = 0
        # the step donates its input state (params/opt buffers reused in
        # place); the actor-facing wrapper keeps its own copy of the params,
        # refreshed only at epoch boundaries
        self.update_step = build_update_step(
            wrapper.module, self.cfg, self.mesh, donate=True,
            state_shardings=self.state_sharding,
            use_target=self._use_target)

        self.default_lr = 3e-8
        self.data_cnt_ema = args['batch_size'] * args['forward_steps']
        self.steps = 0
        # per-stage ingest-path accounting (select/decode/assemble/ipc/h2d/
        # compute/drain), shared by the batcher threads/processes and the
        # trainer loop; printed per epoch under HANDYRL_TPU_TIMING=1 and
        # reported by bench.py's BENCH_MODE=ingest
        from .utils.timing import StageTimer
        self.ingest_timer = StageTimer(registry=telemetry.REGISTRY)
        self.batcher = Batcher(args, self.episodes, timer=self.ingest_timer)
        # depth of the device staging ring: how many batches are held as
        # in-flight device uploads ahead of the compiled step (config
        # 'prefetch_depth'; 1 = the old single-slot overlap)
        self.prefetch_depth = max(1, int(args.get('prefetch_depth') or 1))

        # optional HBM-resident replay: new episodes are windowed once on
        # the host and pushed to a device ring; every SGD step then samples
        # its batch on device (ops/replay.py)
        self.replay = None
        self.ingest_queue: Optional[queue.Queue] = None
        if args.get('device_replay'):
            from .ops.replay import DeviceReplay
            # ring capacity budget per episode: how many training windows a
            # typical episode contributes; override via config
            # 'replay_windows_per_episode' (default assumes ~64-step episodes)
            windows_per_ep = (args.get('replay_windows_per_episode')
                              or max(1, 64 // args['forward_steps']))
            # hard cap on total ring windows: long-episode envs (200-ply
            # geese at forward_steps 4 => 50 windows/ep) must not scale the
            # HBM ring past a few GB; 49152 geese windows ~= 4 GB fp32
            self.replay = DeviceReplay(
                capacity=min(min(args['maximum_episodes'], 4096)
                             * windows_per_ep, 49152),
                mesh=self.mesh)
            self.ingest_queue = queue.Queue(maxsize=1024)
            self._pending_rows: List[Dict[str, Any]] = []
            self._sample_key = jax.random.PRNGKey(args.get('seed', 0) + 1)
            # K SGD steps per program dispatch: sampling, LR schedule and
            # update all stay on device inside one lax.scan, so replay-mode
            # throughput is bounded by compute, not dispatch latency
            self.fused_steps = max(1, int(args.get('replay_fused_steps') or 8))
            self.replay_update = self.build_replay_update(self.cfg)
            # observability: audited by metrics JSONL (replay_* fields)
            self.replay_stats = {'dropped_episodes': 0,
                                 'windows_ingested': 0,
                                 'samples_drawn': 0}
            # device-ingest mode (ops/device_windows.py): the learner
            # installs a DeviceWindower when the env/config supports it;
            # rollout chunks then arrive as device arrays on chunk_queue
            # and windows are assembled straight into the ring in HBM
            self.windower = None
            self.chunk_queue: queue.Queue = queue.Queue(maxsize=4)
            self.seen_episodes = 0     # learner-fed count (no host deque)
            self._ring = None
            self._ring_state = None
            self._ring_cursor = None
            self._ring_size = None
            self._ring_ready = False
            self._ingest_key = jax.random.PRNGKey(args.get('seed', 0) + 2)
            self._pending_ingest: List[Any] = []
        self.update_flag = False
        self.update_queue: queue.Queue = queue.Queue(maxsize=1)
        self._loss_sum: Dict[str, float] = {}
        # learning-dynamics accumulators: 'diag_'-prefixed device metrics
        # (rho/c clip counts, importance-ratio moments, grad norm) folded
        # out of the lazy metric fetch, summarized per epoch into
        # ``last_dynamics`` (metrics_jsonl + gauges + the TIMING line)
        self._diag_sum: Dict[str, float] = {}
        self.last_dynamics: Dict[str, float] = {}
        self.shutdown_flag = False
        self.failed = False
        self.failed_reason = ''
        self.started = False

        # non-finite guard: the device update step skips bad steps in place
        # (train_step.py); this side counts them and escalates per policy.
        # rollback_source is installed by the Learner (it owns the
        # checkpoint files); rollback_epoch hands the model-pool rewind
        # back to the Learner's loop after an in-place state restore.
        self.guard = guard_mod.NonFiniteGuard(args.get('guard') or {})
        self.chaos_nan = guard_mod.ChaosNaN()
        self.rollback_source = None
        self.rollback_epoch: Optional[int] = None

        # throughput + profiling (the reference has no tracing at all —
        # SURVEY.md §5.1; here per-epoch step rate is tracked and a JAX
        # profiler trace can be captured via train_args['profile_dir'])
        self.last_steps_per_sec = 0.0
        self._profile_dir = args.get('profile_dir') or ''
        self._profiled = False
        self._trace_active = False

    def build_replay_update(self, cfg: LossConfig):
        """The fused K-step replay trainer for ``cfg`` — the ONE place its
        geometry is defined (the ingest gate rebuilds it when the device
        'turn' layout serves an observation=True config)."""
        from .ops.train_step import build_replay_update
        return build_replay_update(
            self.wrapper.module, cfg, capacity=self.replay.capacity,
            batch_size=self.args['batch_size'], num_steps=self.fused_steps,
            default_lr=self.default_lr, mesh=self.mesh,
            state_shardings=self.state_sharding,
            # window shapes resolved at trace time (first update): by
            # then either the windower ring (device ingest) or the
            # DeviceReplay (host push) has seen its first windows
            spec_fn=lambda: (
                (self.windower.window_spec, None)
                if getattr(self, 'windower', None) is not None
                else (self.replay.window_spec, self.replay.treedef)))

    def _lr(self) -> float:
        return self.default_lr * self.data_cnt_ema / (1 + self.steps * 1e-5)

    # -- profiler trace lifecycle -----------------------------------------
    # stop_trace is reached from several paths (replay loop, threaded loop,
    # abort/shutdown); jax raises on a second stop, so the state lives in
    # ONE idempotent pair instead of per-path bookkeeping.
    def _start_trace(self):
        jax.profiler.start_trace(self._profile_dir)
        self._profiled = True
        self._trace_active = True

    def _stop_trace(self):
        """Idempotent, exception-safe stop: safe to call from any path, any
        number of times, including after an abort inside the profiled
        window (where jax may have torn the trace down already)."""
        if not self._trace_active:
            return
        self._trace_active = False
        try:
            jax.profiler.stop_trace()
        except Exception as exc:
            _LOG.warning('profiler stop_trace failed (%s: %s)',
                         type(exc).__name__, str(exc)[:120])
        else:
            _LOG.info('profiler trace written to %s', self._profile_dir)

    # -- full-state checkpointing (params + optimizer + schedule) ---------
    # The reference checkpoints the model only (optimizer state and RNG are
    # lost on resume, docs/parameters.md:76-82); here the whole TrainState
    # round-trips so restarts continue the same optimization trajectory.
    def state_bytes(self, host_state: Optional[TrainState] = None) -> bytes:
        from flax import serialization
        from .utils.fetch import fetch_tree
        # fetch the whole state in one packed transfer first: serialization
        # walks leaves with np.asarray, which on a tunneled TPU would pay a
        # round trip per leaf
        state = host_state if host_state is not None else fetch_tree(self.state)
        payload = {'state': state, 'steps': self.steps,
                   'data_cnt_ema': self.data_cnt_ema}
        return serialization.to_bytes(payload)

    def place_state(self, state: TrainState) -> TrainState:
        """Lay a (host or misplaced) TrainState out per the partition
        rules — the layout the compiled steps' in_shardings expect. The
        serialized checkpoint holds full host arrays, so this is also what
        makes restores mesh-shape-portable: whatever mesh wrote the bytes,
        placement happens under the CURRENT mesh."""
        if self.mesh is None:
            return state
        from .parallel.mesh import replicated_sharding
        return jax.device_put(state, self.state_sharding
                              or replicated_sharding(self.mesh))

    def load_state_bytes(self, raw: bytes):
        from flax import serialization
        template = {'state': self.state, 'steps': self.steps,
                    'data_cnt_ema': self.data_cnt_ema}
        payload = serialization.from_bytes(template, raw)
        # build everything before mutating: a parse/convert failure must
        # leave the live state untouched (resume falls back instead).
        # copy=True is load-bearing: from_bytes leaves are numpy VIEWS into
        # ``raw``, and the CPU backend zero-copy-aliases aligned numpy
        # arrays — the compiled update step then DONATES these buffers, so
        # an aliased leaf means XLA reclaiming memory it does not own
        # (non-finite garbage, then a segfault once ``raw`` is collected)
        state = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), payload['state'])
        if isinstance(state, tuple):
            state = TrainState(*state)
        self.state = self.place_state(state)
        self.steps = int(payload['steps'])
        self.data_cnt_ema = float(payload['data_cnt_ema'])
        # the IMPACT target network is not part of the checkpoint: drop any
        # stale copy so the next epoch re-syncs it from the loaded params
        self.target_params = None

    def update(self, timeout: Optional[float] = None):
        """Called by the learner at each epoch boundary; blocks until the
        trainer hands over (params, steps, full-state blob). The blob is
        serialized inside the trainer loop — the state buffers are donated
        to the next compiled step, so nobody may touch them afterwards.
        ``timeout`` (preemption flush) raises queue.Empty when the trainer
        cannot reach a safe point in time."""
        self.update_flag = True
        params, steps, state_blob = self.update_queue.get(timeout=timeout)
        return params, steps, state_blob

    def train(self):
        if self.state is None:   # non-parametric model
            time.sleep(0.1)
            return self.wrapper.params

        batch_cnt, data_cnt = 0, 0
        pending_metrics: List[Dict[str, jnp.ndarray]] = []
        epoch_t0 = time.time()

        # target-network sync at the epoch boundary: a genuine device copy
        # (jnp.copy) because the live params buffer is donated every step.
        # Also (re)materializes after a restart/rollback replaced the state.
        if self._use_target and (
                self.target_params is None
                or self._target_age_epochs >= self.target_sync_epochs):
            self.target_params = jax.tree_util.tree_map(
                jnp.copy, self.state.params)
            self._target_age_epochs = 0

        if self._profile_dir and not self._profiled and self.steps > 0:
            self._start_trace()
            profile_stop_at = self.steps + 20
        else:
            profile_stop_at = -1

        # device staging ring: up to ``prefetch_depth`` batches held as
        # in-flight device uploads ahead of the compiled step (the old code
        # was the depth-1 special case). Persisted on the instance so
        # batches staged across an epoch boundary are consumed, not dropped.
        if not hasattr(self, '_staged'):
            self._staged = deque()
        staged = self._staged
        timer = self.ingest_timer

        def stage_next():
            t0 = time.perf_counter()
            try:
                nxt = self.batcher.batch(timeout=1.0)
            except queue.Empty:
                timer.add('ipc', time.perf_counter() - t0)
                return None
            timer.add('ipc', time.perf_counter() - t0)
            release = None
            # episode tracing: both wrapper flavors (TracedBatch from the
            # thread batcher, SharedBatch from the shm children) carry the
            # sampled trace ids of the windows in the batch
            tids = getattr(nxt, 'trace_ids', None)
            if hasattr(nxt, 'release'):      # shared-memory slot wrapper
                nxt, release = nxt.batch, nxt.release
            elif tids is not None:           # TracedBatch (thread batcher)
                nxt = nxt.batch
            t0 = time.perf_counter()
            if self.mesh is not None:
                dev = shard_batch(self.mesh, nxt)
            else:
                dev = jax.tree_util.tree_map(jnp.asarray, nxt)
            if release is not None:
                # the batcher child may reuse the slot only once the upload
                # has read the shared pages (device_put copies; this waits
                # for that copy, never for compute)
                jax.block_until_ready(dev)
                release()
            timer.add('h2d', time.perf_counter() - t0)
            return dev, tids

        def top_up():
            while len(staged) < self.prefetch_depth:
                nxt = stage_next()
                if nxt is None:
                    break
                staged.append(nxt)

        while (data_cnt == 0 or not self.update_flag) and not self.shutdown_flag:
            if self.replay is not None:
                # fused path: one dispatch = fused_steps SGD steps, with
                # batch sampling, LR schedule and PRNG advance all on device
                if self.windower is not None:
                    self._ingest_device_chunks()
                    if not self._ring_ready:
                        time.sleep(0.1)
                        continue
                    buffers = self._ring
                    size, cursor = self._ring_size, self._ring_cursor
                else:
                    self._ingest_new_episodes()
                    if self.replay.size == 0:
                        time.sleep(0.1)
                        continue
                    buffers = self.replay.buffers
                    size = jnp.asarray(self.replay.size, jnp.int32)
                    cursor = jnp.asarray(self.replay.cursor, jnp.int32)
                # optional replay-ratio cap: the threaded trainer otherwise
                # free-spins as fast as dispatch allows (implicit, hardware-
                # dependent reuse — the reference's behavior); with
                # max_sample_reuse the trainer waits for fresh windows once
                # samples-drawn / windows-ingested would exceed the cap,
                # pinning off-policyness to a known ratio
                cap = self.args.get('max_sample_reuse')
                if cap and not self.update_flag:
                    # never throttle an epoch that is waiting to close: the
                    # loop must make >=1 dispatch per epoch to hand back
                    drawn_next = (self.replay_stats['samples_drawn']
                                  + self.args['batch_size'] * self.fused_steps)
                    if drawn_next > float(cap) * max(
                            1, self.replay_stats['windows_ingested']):
                        time.sleep(0.05)
                        continue
                ema = self.data_cnt_ema
                if self.chaos_nan.due(self.steps, self.fused_steps):
                    _LOG.warning('chaos: injecting non-finite update at '
                                 'step %d', self.steps)
                    ema = float('nan')   # poisons the on-device lr schedule
                t_dispatch = time.perf_counter()
                self.state, self._sample_key, metrics = self.replay_update(
                    self.state, buffers, self._sample_key, size, cursor,
                    jnp.asarray(ema, jnp.float32))
                timer.add('dispatch', time.perf_counter() - t_dispatch)
                self.replay_stats['samples_drawn'] += (
                    self.args['batch_size'] * self.fused_steps)
                pending_metrics.append(metrics)
                batch_cnt += self.fused_steps
                self.steps += self.fused_steps
                # drain every 4 dispatches (a fetch costs a device sync) —
                # but immediately when an epoch is waiting to close, so the
                # close needs ONE dispatch, not four (matters when
                # max_sample_reuse throttles the loop)
                if len(pending_metrics) >= 4 or self.update_flag:
                    t_block = time.perf_counter()
                    data_cnt += self._drain_metrics(pending_metrics)
                    timer.add('host_block', time.perf_counter() - t_block)
                    pending_metrics = []
                if 0 <= profile_stop_at <= self.steps:
                    jax.block_until_ready(metrics['total'])
                    self._stop_trace()
                    profile_stop_at = -1
                continue
            if not staged:
                top_up()
                if not staged:
                    continue
            batch, batch_tids = staged.popleft()
            lr_val = self._lr()
            if self.chaos_nan.due(self.steps):
                _LOG.warning('chaos: injecting non-finite update at step %d',
                             self.steps)
                lr_val = float('nan')
            lr = jnp.asarray(lr_val, jnp.float32)
            t_wall = time.time()
            t_dispatch = time.perf_counter()
            if self._use_target:
                self.state, metrics = self.update_step(
                    self.state, batch, lr, self.target_params)
            else:
                self.state, metrics = self.update_step(self.state, batch, lr)
            dt_dispatch = time.perf_counter() - t_dispatch
            timer.add('dispatch', dt_dispatch)
            if batch_tids:
                # the gradient end of the episode trace: one event per
                # update, linking every sampled episode whose window this
                # batch consumed (ids already passed deterministic sampling)
                telemetry.trace_event('train_step', ts=t_wall,
                                      dur=dt_dispatch, always=True,
                                      trace_ids=batch_tids, steps=self.steps)
            # the ring refills (device_put of the next batches) while the
            # dispatched step runs on device
            top_up()
            pending_metrics.append(metrics)
            batch_cnt += 1
            # data_count is a device scalar; fetch lazily every few steps to
            # avoid a sync per update
            if len(pending_metrics) >= 8:
                t_block = time.perf_counter()
                data_cnt += self._drain_metrics(pending_metrics)
                timer.add('host_block', time.perf_counter() - t_block)
                pending_metrics = []
            self.steps += 1
            if self.steps == profile_stop_at:
                jax.block_until_ready(metrics['total'])
                self._stop_trace()

        if pending_metrics:
            t_block = time.perf_counter()
            data_cnt += self._drain_metrics(pending_metrics)
            timer.add('host_block', time.perf_counter() - t_block)

        if batch_cnt > 0:   # zero only when interrupted by shutdown
            loss_sum = self._loss_sum
            self._loss_sum = {}
            print('loss = %s' % ' '.join(
                [k + ':' + '%.3f' % (l / max(data_cnt, 1))
                 for k, l in loss_sum.items()]))
            self.data_cnt_ema = (self.data_cnt_ema * 0.8
                                 + data_cnt / (1e-2 + batch_cnt) * 0.2)
            self.last_steps_per_sec = batch_cnt / max(time.time() - epoch_t0, 1e-9)
            if self._use_target:
                self._target_age_epochs += 1
            self.last_dynamics = self._epoch_dynamics(loss_sum, data_cnt,
                                                      batch_cnt)
            # the epoch's per-stage seconds feed the device-utilization
            # proxy (host_block / total ingest time) whether or not the
            # timing line is printed
            line = self.ingest_timer.snapshot(reset=True)
            util = telemetry.utilization_from_stages(line)
            telemetry.set_utilization_proxy(util)
            if os.environ.get('HANDYRL_TPU_TIMING') == '1':
                # one line per epoch: seconds + event counts per ingest
                # stage ('dispatch' is async-issue time; 'host_block' is
                # the device sync), plus the epoch's dynamics summary
                if util is not None:
                    line['util'] = round(util, 4)
                if self.last_dynamics:
                    line['dynamics'] = self.last_dynamics
                print('ingest timing: %s' % json.dumps(line))
        from .utils.fetch import fetch_tree
        return fetch_tree(self.state.params)

    def _ingest_device_chunks(self):
        """Drain rollout-record chunks (device arrays) into the HBM ring via
        the windower's compiled ingest program. First chunk allocates the
        history and ring buffers from the observed record shapes. This
        thread is the single owner of ring/history state, so the program
        donates them in place."""
        ingested = 0
        while ingested < 8:
            try:
                records = self.chunk_queue.get_nowait()
            except queue.Empty:
                break
            ingested += 1
            if self._ring is None:
                self._ring_state = self.windower.init_state(records)
                self._ring = self.windower.init_ring(records)
                self._ring_cursor = jnp.zeros((), jnp.int32)
                self._ring_size = jnp.zeros((), jnp.int32)
            (self._ring_state, self._ring, self._ring_cursor,
             self._ring_size, self._ingest_key, _n_done, n_win) = \
                self.windower.ingest(records, self._ring_state, self._ring,
                                     self._ring_cursor, self._ring_size,
                                     self._ingest_key)
            self._pending_ingest.append(n_win)
        # fetch window counts lazily; the startup gate needs a real sync,
        # and a configured reuse cap needs a CURRENT windows_ingested or it
        # over-throttles by the un-flushed backlog
        if self._pending_ingest and (not self._ring_ready
                                     or len(self._pending_ingest) >= 8
                                     or self.args.get('max_sample_reuse')):
            total = int(sum(int(x) for x in self._pending_ingest))
            self._pending_ingest = []
            self.replay_stats['windows_ingested'] += total
            # host mirror of the device ring size: other threads (metrics)
            # must never touch _ring_size itself — it is donated in flight
            self._ring_size_host = min(
                getattr(self, '_ring_size_host', 0) + total,
                self.replay.capacity)
            if total > 0:
                self._ring_ready = True

    def ring_occupancy(self) -> float:
        if self.replay is None:
            return 0.0
        if getattr(self, 'windower', None) is not None:
            return (getattr(self, '_ring_size_host', 0)
                    / self.replay.capacity)
        return self.replay.size / self.replay.capacity

    PUSH_CHUNK = 8   # fixed ring-push size => one XLA scatter compile

    def _ingest_new_episodes(self):
        """Window freshly generated episodes and push them into the device
        ring. Each episode is decompressed ONCE; ~steps/forward_steps random
        windows are sliced from the decoded moments; windows accumulate into
        fixed-size chunks so the ring's scatter compiles exactly once."""
        from .ops.batch import build_window, decompress_moments, stack_windows

        ingested = 0
        while ingested < 64:
            try:
                ep = self.ingest_queue.get_nowait()
            except queue.Empty:
                break
            ingested += 1
            moments = decompress_moments(ep['moment'])
            fs, bi = self.args['forward_steps'], self.args['burn_in_steps']
            for _ in range(max(1, ep['steps'] // fs)):
                train_st = random.randrange(1 + max(0, ep['steps'] - fs))
                st = max(0, train_st - bi)
                ed = min(train_st + fs, ep['steps'])
                meta = {'outcome': ep['outcome'], 'start': st, 'end': ed,
                        'train_start': train_st, 'total': ep['steps']}
                self._pending_rows.append(
                    build_window(moments[st:ed], meta, self.args))
        while len(self._pending_rows) >= self.PUSH_CHUNK:
            chunk = self._pending_rows[:self.PUSH_CHUNK]
            self._pending_rows = self._pending_rows[self.PUSH_CHUNK:]
            self.replay.push(stack_windows(chunk))
            self.replay_stats['windows_ingested'] += self.PUSH_CHUNK

    def _drain_metrics(self, pending: List[Dict[str, Any]]) -> int:
        """Fetch queued metric dicts in ONE packed transfer (per-scalar
        float() costs a tunnel round trip each) and fold them into the
        epoch's loss sums. Returns the summed data_count. The 'nonfinite'
        skip counts ride the same fetch into the guard — escalation costs
        no extra device sync."""
        from .utils.fetch import fetch_tree
        data_cnt = 0
        bad = 0
        total_sum = 0.0
        for m in fetch_tree(pending):
            for k, v in m.items():
                if k == 'data_count':
                    data_cnt += int(v)
                elif k == 'nonfinite':
                    bad += int(v)
                elif k.startswith('diag_'):
                    # learning-dynamics diagnostics: summarized per epoch
                    # by _epoch_dynamics, never on the reference loss line
                    self._diag_sum[k] = self._diag_sum.get(k, 0.0) + float(v)
                else:
                    if k == 'total':
                        total_sum += float(v)
                    self._loss_sum[k] = self._loss_sum.get(k, 0.0) + float(v)
        per_dispatch = self.fused_steps if self.replay is not None else 1
        n_updates = len(pending) * per_dispatch
        self._guard_observe(bad, n_updates - bad,
                            total_sum / data_cnt if data_cnt else None)
        return data_cnt

    def _epoch_dynamics(self, loss_sum: Dict[str, float], data_cnt: int,
                        n_updates: int) -> Dict[str, float]:
        """Reduce the epoch's accumulated ``diag_*`` device metrics into
        the learning-dynamics summary: V-Trace rho/c clip fractions,
        importance-ratio mean/std, policy entropy per acting sample, and
        mean global grad norm per update. Values are mirrored onto gauges
        (live Prometheus exposition) and returned for metrics_jsonl + the
        HANDYRL_TPU_TIMING line."""
        d, self._diag_sum = self._diag_sum, {}
        dc, nu = max(1, data_cnt), max(1, n_updates)
        out: Dict[str, float] = {}
        if 'ent' in loss_sum:
            out['entropy'] = loss_sum['ent'] / dc
        if 'diag_rho_clip' in d:
            out['rho_clip_fraction'] = d['diag_rho_clip'] / dc
            out['c_clip_fraction'] = d.get('diag_c_clip', 0.0) / dc
        if 'diag_rho_sum' in d:
            mean = d['diag_rho_sum'] / dc
            out['importance_ratio_mean'] = mean
            var = max(0.0, d.get('diag_rho_sq_sum', 0.0) / dc - mean * mean)
            out['importance_ratio_std'] = var ** 0.5
        if 'diag_target_clip' in d:
            # IMPACT target-network dynamics (losses.py target_clip):
            # clip fraction + mean of the target/behavior ratio, and the
            # mean current-vs-target log-prob gap (how far the live policy
            # has drifted from the frozen target since the last sync)
            out['target_clip_fraction'] = d['diag_target_clip'] / dc
            out['target_ratio_mean'] = (
                d.get('diag_target_ratio_sum', 0.0) / dc)
            out['target_gap_mean'] = d.get('diag_target_gap_sum', 0.0) / dc
        if 'diag_grad_norm' in d:
            out['grad_norm'] = d['diag_grad_norm'] / nu
        out = {k: round(float(v), 6) for k, v in out.items()}
        for k, v in out.items():
            telemetry.gauge(k).set(v)
        return out

    # -- non-finite guard --------------------------------------------------
    def _guard_observe(self, bad: int, good: int,
                       loss_mean: Optional[float] = None):
        """Fold one drained metrics group into the guard; skip is counted,
        rollback restores the last good checkpoint in place, abort raises
        (the run()-level handler turns that into the failed path)."""
        if bad:
            telemetry.counter('guard_nonfinite_total').inc(bad)
        action = self.guard.observe(bad, good, loss_mean)
        if action == 'abort':
            raise RuntimeError(
                'guard: %d non-finite update(s) under nonfinite_policy='
                'abort' % bad)
        if action == 'rollback':
            self._do_rollback()
        elif bad:
            _LOG.warning('guard: skipped %d non-finite update(s) '
                         '(%d consecutive)', bad, self.guard.consecutive)

    def _do_rollback(self):
        """Restore the last good checkpoint IN PLACE (TrainState + step
        counter + lr EMA) and hand the model-pool epoch rewind to the
        Learner via ``rollback_epoch``. Safe here: called only between
        dispatches, when self.state is a settled value."""
        src = self.rollback_source() if self.rollback_source else None
        if src is None:
            _LOG.error('guard: rollback tripped but no valid checkpoint '
                       'exists yet; continuing with skipped updates')
            self.guard.reset_streak()
            return
        epoch, blob = src
        self.load_state_bytes(blob)
        self.guard.reset_streak()
        self.guard.rollbacks += 1
        self.rollback_epoch = epoch
        telemetry.counter('guard_rollbacks_total').inc()
        _LOG.error('guard: non-finite training burst — rolled back to '
                   'checkpoint epoch %d (steps %d)', epoch, self.steps)

    def run(self):
        _LOG.info('waiting training')
        while (len(self.episodes) < self.args['minimum_episodes']
               and getattr(self, 'seen_episodes', 0)
               < self.args['minimum_episodes']
               and not self.shutdown_flag):
            if getattr(self, 'windower', None) is not None:
                # keep consuming rollout chunks while waiting: generation
                # blocks on the chunk queue (stream contiguity), so the ring
                # must fill during warmup too
                self._ingest_device_chunks()
            time.sleep(0.1)
        if self.state is not None and not self.shutdown_flag:
            if self.replay is None:
                self.batcher.run()
            self.started = True
            _LOG.info('started training')
        while not self.shutdown_flag:
            try:
                if not self.failed:
                    params = self.train()
                    state_blob = (self.state_bytes()
                                  if self.state is not None else None)
                else:
                    time.sleep(0.5)
                    params, state_blob = None, None
            except Exception as exc:
                # deliver (None, ...) instead of deadlocking the learner
                # (it blocks on update_queue at every epoch boundary); the
                # learner sees `failed` and shuts the run down — a dead
                # optimizer must not keep minting checkpoint epochs
                import traceback
                traceback.print_exc()
                # an abort inside the profiled window must not strand an
                # open trace (nor crash a later stop with a double-stop)
                self._stop_trace()
                self.failed = True
                self.failed_reason = '%s: %s' % (type(exc).__name__,
                                                 str(exc)[:300])
                params, state_blob = None, None
            self.update_flag = False
            while not self.shutdown_flag:
                try:
                    self.update_queue.put((params, self.steps, state_blob),
                                          timeout=0.5)
                    break
                except queue.Full:
                    continue

    def shutdown(self):
        self.shutdown_flag = True
        self._stop_trace()   # idempotent: a no-op unless a trace is open
        self.batcher.stop()


class _EpochCadence:
    """Epoch trigger shared by every generation front-end: an epoch is due
    every ``update_episodes`` returned episodes past the warmup minimum
    (reference train.py:621-626). One definition so the fused, threaded and
    RPC-server loops cannot drift apart."""

    def __init__(self, args: Dict[str, Any]):
        self._next = args['minimum_episodes'] + args['update_episodes']
        self._step = args['update_episodes']

    def due(self, returned_episodes: int) -> bool:
        if returned_episodes >= self._next:
            self._next += self._step
            return True
        return False


class Learner:
    """Central conductor: owns the model, episode/eval accounting, epoch
    cadence, checkpoints, and the generation front-end."""

    def __init__(self, args: Dict[str, Any], net=None, remote: bool = False):
        train_args = args['train_args']
        env_args = args['env_args']
        train_args['env'] = env_args
        args = train_args

        from . import setup_compile_cache
        setup_compile_cache()

        self.args = args
        random.seed(args['seed'])

        # -- unified telemetry: one run id for the whole fleet (workers
        # receive it in the merged config and stamp their own registries),
        # a master collection switch, episode-lifecycle tracing, and the
        # optional Prometheus endpoint. The telemetry knob accepts a bool
        # (legacy switch) or a block with trace_dir / trace_sample_rate.
        tel = telemetry.config_block(args)
        if not tel['enabled']:
            telemetry.set_enabled(False)
        args.setdefault('run_id', telemetry.run_id())
        telemetry.set_run_id(args['run_id'])
        telemetry.set_process_label('learner')
        telemetry.configure_tracing(tel.get('trace_dir') or None,
                                    tel.get('trace_sample_rate'))
        telemetry.configure_recorder(tel.get('recorder_events'),
                                     tel.get('blackbox_dir'))
        if telemetry.enabled():
            # XLA compile-event counters (cache hits, compile durations)
            telemetry.install_jax_monitoring()
            # fatal errors leave a blackbox dump behind (sys.excepthook)
            telemetry.install_crash_dump()
        # compiled-performance plane: device-memory gauges, the retrace
        # sentinel (steady state marked after retrace_warmup_epochs), and
        # the dispatch/host_block utilization proxy
        telemetry.configure_perf_plane(tel.get('perf_plane'),
                                       tel.get('retrace'))
        self._retrace_warmup = int(tel.get('retrace_warmup_epochs', 1))
        # SLO alert engine: builtin catalog + telemetry.alerts overrides,
        # evaluated on the server loop / epoch writer / statusz scrapes
        # through one cadence-gated stream (None with alerting off)
        self._alerts = telemetry.AlertEngine.from_config(args)
        self._metrics_rotate_mb = float(tel.get('metrics_rotate_mb') or 0)
        self._last_fleet_telemetry: Optional[dict] = None
        self._exporter = None
        # epoch means of the policy-lag/sample-age histograms are computed
        # as deltas between epochs; marks hold the last-read (sum, count)
        self._lag_marks: Dict[str, tuple] = {}

        self.env = make_env(env_args)
        eval_modify_rate = (args['update_episodes'] ** 0.85) / args['update_episodes']
        self.eval_rate = max(args['eval_rate'], eval_modify_rate)
        self.shutdown_flag = False
        self.flags: set = set()

        # learner-side resilience (guard.py): preemption snapshot-and-exit,
        # episode ingest screening, checkpoint integrity/rollback plumbing
        guard_args = dict(args.get('guard') or {})
        self.preempt = guard_mod.PreemptionGuard(
            enabled=bool(guard_args.get('preempt_signals', True)))
        self._check_episodes = bool(guard_args.get('check_episodes', True))
        self._bad_episodes = 0
        self._chaos = guard_mod.parse_chaos()
        self._final_flushed = False
        self._fused_active = False
        self._last_ckpt_epoch = -1
        self._last_ckpt_steps = -1

        self.model_epoch = args['restart_epoch']
        module = net if net is not None else self.env.net()
        compute_dtype = args.get('compute_dtype')
        if compute_dtype and hasattr(module, 'dtype'):
            # bf16 activations on the MXU; params stay float32
            module = module.clone(dtype=jnp.dtype(compute_dtype))
        self.wrapper = ModelWrapper(module, seed=args['seed'])
        self.env.reset()
        self._example_obs = self.env.observation(self.env.players()[0])
        self.wrapper.ensure_params(self._example_obs)
        self._resume = False
        if self.model_epoch < 0:
            # auto-resume (restart_epoch: -1): the supervisor restart path
            # after a preemption exit — pick up the newest checkpoint that
            # passes integrity verification, or start fresh when none does
            self.model_epoch, discarded = guard_mod.newest_valid_epoch(
                self.args.get('model_dir', 'models'))
            args['restart_epoch'] = self.model_epoch
            if discarded:
                telemetry.counter('guard_ckpt_fallbacks_total').inc(
                    len(discarded))
            if self.model_epoch > 0:
                print('auto-resume: newest valid checkpoint is epoch %d'
                      % self.model_epoch)
        if self.model_epoch > 0:
            self._load_resume_params()
            self._resume = True
        elif args.get('init_params'):
            # warm start: params only — epoch counter, optimizer moments and
            # lr EMA start fresh (unlike restart_epoch, which resumes all)
            with open(args['init_params'], 'rb') as f:
                self.wrapper.load_params_bytes(f.read(), self._example_obs)
            print('warm-started params from %s' % args['init_params'])

        # generation accounting
        self.generation_results: Dict[int, tuple] = {}
        self.num_episodes = 0
        self.num_returned_episodes = 0
        # evaluation accounting
        self.results: Dict[int, tuple] = {}
        self.results_per_opponent: Dict[int, dict] = {}
        self.num_results = 0

        # Resolve the per-episode replay-window budget ONCE, from the env's
        # true episode length, so the device windower's per-episode cap and
        # the host ingest rate (both ~steps/forward_steps windows) agree —
        # the default of 64//forward_steps silently under-sampled long
        # episodes (a 200-ply goose yielded 4 windows instead of 12).
        if args.get('device_replay') and not args.get('replay_windows_per_episode'):
            from .environment import make_jax_env
            twin = make_jax_env(env_args)
            if twin is not None:
                max_steps = int(getattr(twin, 'MAX_STEPS',
                                        getattr(twin, 'MAX_PLIES', 64)))
                args['replay_windows_per_episode'] = max(
                    1, max_steps // args['forward_steps'])

        self.remote = remote
        self.use_batched_generation = (not remote
                                       and args.get('batched_generation', True))
        self.ledger: Optional[TaskLedger] = None   # built by server()
        self.fleet: Optional[FleetController] = None   # built by server()
        self.worker = None
        if not self.use_batched_generation:
            self.worker = WorkerServer(args) if remote else WorkerCluster(args)

        self.trainer = Trainer(args, self.wrapper)
        self.trainer.rollback_source = self._rollback_source
        # policy-lag accounting: the batcher stamps lag at window selection
        # against the CURRENT learner epoch (consumption, not ingest)
        self.trainer.batcher.epoch_fn = lambda: self.model_epoch
        # profile_epochs: wrap chosen epochs in a jax.profiler device trace
        # (start at the previous epoch's close, stop at the chosen epoch's
        # close). Disables the legacy one-shot auto-trace — the knob says
        # exactly which epochs the operator wants.
        from .config import parse_epoch_set
        self._profile_epochs = parse_epoch_set(args.get('profile_epochs'))
        if self._profile_epochs:
            if not self.trainer._profile_dir:
                self.trainer._profile_dir = os.path.join(
                    telemetry.trace_dir() or args.get('model_dir', 'models'),
                    'profile')
            self.trainer._profiled = True   # suppress the legacy auto-start
        if self._resume:
            state_path = self.trainer_state_path()
            if os.path.exists(state_path):
                from .parallel.partition import checkpoint_layout, describe_mesh
                from .utils.fs import read_layout_manifest, read_verified_bytes
                raw = read_verified_bytes(state_path)
                layout, lreason = read_layout_manifest(state_path)
                if lreason == 'unparsable':
                    # corrupt manifest = untrustworthy pair, same as a CRC
                    # failure: degrade to params-only resume
                    raw = None
                if raw is None:
                    _LOG.error('discarding corrupt trainer_state.ckpt '
                               '(checksum mismatch, truncation, or corrupt '
                               'layout manifest); the optimizer restarts '
                               'fresh from the model checkpoint')
                    telemetry.counter('guard_ckpt_fallbacks_total').inc()
                else:
                    # mesh-portable restore: the state is full host arrays,
                    # so a mesh-shape change is legal — log it explicitly
                    here = checkpoint_layout(self.trainer.mesh,
                                             self.trainer.partition_rules)
                    if layout is not None and (
                            layout.get('mesh') != here['mesh']
                            or layout.get('processes') != here['processes']):
                        print('mesh-portable restore: checkpoint written '
                              'under %s (%d process(es)), restoring onto '
                              '%s (%d process(es))'
                              % (describe_mesh(layout),
                                 int(layout.get('processes') or 1),
                                 describe_mesh(here), here['processes']))
                    try:
                        self.trainer.load_state_bytes(raw)
                        print('resumed trainer state (steps %d)'
                              % self.trainer.steps)
                    except Exception as exc:
                        _LOG.error('discarding undecodable trainer_state'
                                   '.ckpt (%s: %s); the optimizer restarts '
                                   'fresh', type(exc).__name__,
                                   str(exc)[:120])
                        telemetry.counter('guard_ckpt_fallbacks_total').inc()
        self._trainer_thread: Optional[threading.Thread] = None
        self._registry = None   # lazy ModelRegistry (serving.publish)

        # league training (league.py, docs/league.md): the pool, the
        # persistent rating book, and the per-epoch opponents-sampled
        # tally. Everything below is None with league.enabled false, so
        # task assignment/records/metrics stay byte-identical to the
        # pre-league behavior.
        lg = dict(args.get('league') or {})
        self._league: Optional[league_mod.LeaguePool] = None
        self._league_ratings: Optional[league_mod.RatingBook] = None
        self._league_journal = ''
        self._league_sampled: Dict[str, int] = {}
        if lg.get('enabled'):
            srv = args.get('serving') or {}
            line = str(lg.get('line') or srv.get('line', 'default'))
            self._league = league_mod.LeaguePool(lg, line)
            self._league_ratings = league_mod.make_rating_book(lg)
            self._league_journal = league_mod.journal_path(
                self._registry_root())
            if self._league_ratings.load(self._league_journal):
                print('league: reloaded ratings journal (%d entries, %d '
                      'promotions)' % (len(self._league_ratings.names()),
                                       self._league_ratings.promotions))
            try:
                self._league.refresh(self._ensure_registry())
            except Exception as exc:   # fresh run: no manifest yet
                _LOG.debug('league: initial pool refresh skipped (%s)', exc)
            if self.use_batched_generation:
                _LOG.warning('league.enabled only drives the worker-fleet '
                             "server() task assignment; the in-process "
                             'batched generator keeps mirror self-play')

        # durable training plane (spool.py EpisodeSpool + fault.LedgerJournal,
        # docs/large_scale_training.md "Zero-loss training plane"). Remote
        # only: the in-process front-ends lose nothing a checkpoint does not
        # already cover, and their records must stay byte-identical.
        # _load_durable_state publishes the resume token before the entry
        # listener opens; the spool creates its directory on first append.
        dur = dict(args.get('durability') or {})
        self._spool: Optional[EpisodeSpool] = None
        self._ledger_journal: Optional[LedgerJournal] = None
        self._restored_ledger: Optional[dict] = None
        self._durable_restored = False
        self._spool_horizon = 0          # consumption horizon at last ckpt
        self._run_generation = 0         # restart generation (resume token)
        self._token_path = os.path.join(args.get('model_dir', 'models'),
                                        'run_token.json')
        self._league_last_flush = time.monotonic()
        if remote and bool(dur.get('spool', True)):
            self._spool = EpisodeSpool(
                args.get('model_dir', 'models'),
                segment_mb=float(dur.get('segment_mb', 64)),
                keep_segments=int(dur.get('keep_segments', 2)))
        if remote and bool(dur.get('ledger_snapshot', True)):
            self._ledger_journal = LedgerJournal(
                args.get('model_dir', 'models'))
        # streaming ingest (streaming.py): one assembler merges chunked
        # uploads back into episodes. Constructed unconditionally (cheap,
        # inert while no chunk arrives) so spool recovery can replay chunk
        # records even if the restarted config flipped streaming off.
        from .streaming import ChunkAssembler
        self._assembler = ChunkAssembler(
            args, check_finite=self._check_episodes)
        self._recovered_closed_chunks: list = []
        self._load_durable_state()

        # the scrape endpoint binds only once everything it reads (trainer,
        # worker front-end) exists — a scrape can land any time after this
        export_port = int(args.get('telemetry_port') or 0)
        if export_port and telemetry.enabled():
            self._exporter = telemetry.TelemetryExporter(
                self._telemetry_snapshots, port=export_port,
                status=self._status_info).start()

        self._metrics_path = args.get('metrics_jsonl') or ''
        # optional wall-clock budget (absolute unix time): long quality runs
        # (scripts/run_north_star.py) stop at the next epoch boundary so the
        # final checkpoint lands inside the budget window
        self._deadline = float(os.environ.get('HANDYRL_TPU_DEADLINE', 0) or 0)

    def _past_epoch_budget(self) -> bool:
        """True when the epoch budget or the wall-clock deadline is spent."""
        if 0 <= self.args['epochs'] <= self.model_epoch:
            return True
        return self._deadline > 0 and time.time() >= self._deadline

    # -- durable training plane ------------------------------------------
    def _load_durable_state(self):
        """Restart recovery for the durable training plane: adopt the
        previous incarnation's resume token (same run_id, generation + 1),
        replay the persisted ledger book, restore the admission counters,
        cancel the tasks whose episodes already reached the spool, and
        feed every spooled episode past the newest checkpoint's
        consumption horizon back into the buffer — all before the fleet is
        served a single task."""
        if self._ledger_journal is None and self._spool is None:
            return
        token = None
        try:
            with open(self._token_path, 'r') as f:
                token = json.load(f)
        except (OSError, ValueError):
            token = None
        if isinstance(token, dict) and token.get('run_id'):
            # keep the dead incarnation's run_id: surviving gathers prove
            # membership against it in the resume-token handshake (and the
            # telemetry/trace stream stays one causal run)
            self.args['run_id'] = str(token['run_id'])
            telemetry.set_run_id(self.args['run_id'])
            self._run_generation = int(token.get('generation') or 0) + 1

        state = self._ledger_journal.load() \
            if self._ledger_journal is not None else None
        if state is not None:
            extra = state.get('extra') or {}
            # counter restore: at least the snapshot values, bounded below
            # by the sample_key watermark over the persisted book — a
            # fresh task must NEVER reuse a restored task's sample_key or
            # the purity contract (episode = f(seed, sample_key, params))
            # would mint two different episodes under one key
            g_max = e_max = -1
            for base in (list((state.get('tasks') or {}).values())
                         + list(state.get('reissue') or ())):
                if not isinstance(base, dict) \
                        or base.get('sample_key') is None:
                    continue
                if base.get('role') == 'g':
                    g_max = max(g_max, int(base['sample_key']))
                elif base.get('role') == 'e':
                    e_max = max(e_max, int(base['sample_key']))
            self.num_episodes = max(int(extra.get('num_episodes') or 0),
                                    g_max + 1)
            self.num_results = max(int(extra.get('num_results') or 0),
                                   e_max + 1)
            self.num_returned_episodes = int(
                extra.get('num_returned_episodes') or 0)
            self._spool_horizon = int(extra.get('spool_horizon') or 0)
            self._durable_restored = True
            print('durable plane: restored ledger book (%d outstanding, '
                  '%d pending re-issue, counters g=%d e=%d returned=%d)'
                  % (len(state.get('tasks') or {}),
                     len(state.get('reissue') or ()), self.num_episodes,
                     self.num_results, self.num_returned_episodes))

        if self._spool is not None:
            recovered = self._spool.recover(self._spool_horizon, conn_unpack)
            if recovered:
                # an episode that reached the spool must neither re-issue
                # nor double-count: drop its task_id from the restored
                # book before the ledger ever sees it (this closes the
                # only crash window — admitted but completion unflushed)
                tasks = (state or {}).get('tasks')
                # records below the restored returned-counter were already
                # counted by the dead incarnation; they only live in the
                # spool because the GC horizon holds back to the oldest
                # open streamed assembly — replaying them would double-count
                counted = self.num_returned_episodes
                episodes = []
                for rec in recovered:
                    episode = rec.get('episode')
                    if episode is None \
                            or int(rec.get('idx') or 0) < counted:
                        continue
                    episodes.append(episode)
                    tid = (episode.get('args') or {}).get('task_id')
                    if tasks is not None and tid is not None:
                        tasks.pop(tid, None)
                self.feed_episodes(episodes, recovered=True)
                # streamed chunk records replay through the assembler under
                # their original spool indices; an episode whose every
                # window was WAL'd reassembles right here — cancel its
                # restored task (tid, plus the sample_key scan for a pure
                # stream whose final attempt differed) and remember the key
                # so the ledger screens post-restart resends of it. A
                # still-open assembly keeps its restored book entry: the
                # re-issue regenerates the missing windows (the delivered
                # ones screen as duplicates in the restored chunk book).
                # The replay screen: a chunk replays iff its assembly is
                # still open in the restored book, closed by a POST-snapshot
                # delta (completion not yet in the restored counters), or
                # spooled past the counter — assemblies completed before the
                # snapshot are already counted and must stay dropped.
                from .streaming import chunk_key
                live_keys = set()
                for pair in (state or {}).get('chunks') or ():
                    try:
                        live_keys.add((str(pair[0][0]), int(pair[0][1])))
                    except Exception:
                        continue
                for k in (state or {}).get('chunks_closed') or ():
                    try:
                        live_keys.add((str(k[0]), int(k[1])))
                    except Exception:
                        continue
                chunk_recs = [
                    rec for rec in recovered
                    if rec.get('chunk') is not None
                    and (int(rec.get('idx') or 0) >= counted
                         or chunk_key(rec['chunk']) in live_keys)]
                if chunk_recs:
                    done = self.feed_chunks(
                        [rec['chunk'] for rec in chunk_recs],
                        recovered=True,
                        marks=[int(rec.get('idx') or 0)
                               for rec in chunk_recs])
                    for key, final_args in done:
                        self._recovered_closed_chunks.append(key)
                        if tasks is None:
                            continue
                        tid = (final_args or {}).get('task_id')
                        if tid is not None:
                            tasks.pop(tid, None)
                        if key and key[0] == 'k':
                            for t, base in list(tasks.items()):
                                if isinstance(base, dict) \
                                        and base.get('sample_key') == key[1] \
                                        and base.get('role') == 'g':
                                    tasks.pop(t, None)
                    print('durable plane: replayed %d spooled chunk(s) '
                          '(%d episode(s) reassembled, %d assembly(ies) '
                          'still open)'
                          % (len(chunk_recs), len(done),
                             self._assembler.open_count()))
                self._durable_restored = True
                print('durable plane: recovered %d spooled episode(s) '
                      'past horizon %d (zero admitted episodes lost)'
                      % (len(recovered), self._spool_horizon))
        self._restored_ledger = state
        if self._durable_restored:
            # the trainer resumes mid-stream: it must not re-wait a full
            # fresh minimum_episodes warmup on top of the restored buffer
            self.trainer.seen_episodes = self.num_returned_episodes

        # publish THIS incarnation's resume token now — before run() opens
        # the entry listener — so every gather (fresh or redialing) sees it
        # in the merged entry config. The NEXT restart adopts the run_id
        # and bumps the generation; reattaching gathers prove membership
        # against it (the RESUME_KIND branch in server()).
        os.makedirs(self.args.get('model_dir', 'models'), exist_ok=True)
        atomic_write_bytes(self._token_path, (json.dumps(
            {'run_id': str(self.args.get('run_id')),
             'generation': self._run_generation}) + '\n').encode('utf-8'))
        self.args['resume_token'] = {
            'run_id': str(self.args.get('run_id')),
            'generation': self._run_generation}

    def _sync_durable_state(self):
        """Epoch-sync the durable plane (rides every checkpoint write):
        republish the ledger snapshot — folding the delta journal — and
        GC spool segments behind the new consumption horizon."""
        # the consumption horizon holds back to the oldest OPEN streamed
        # assembly's first WAL mark: a restart must be able to replay every
        # window of a partially-delivered episode, even ones spooled before
        # episodes that already completed
        horizon = self.num_returned_episodes
        open_mark = self._assembler.min_open_mark()
        if open_mark is not None:
            horizon = min(horizon, int(open_mark))
        if self.ledger is not None and self._ledger_journal is not None:
            self.ledger.flush_journal()
            state = self.ledger.snapshot_state()
            state['extra'] = {
                'num_episodes': self.num_episodes,
                'num_results': self.num_results,
                'num_returned_episodes': self.num_returned_episodes,
                'spool_horizon': horizon,
            }
            self._ledger_journal.snapshot(state)
        if self._spool is not None:
            self._spool_horizon = horizon
            self._spool.gc(self._spool_horizon)

    # -- checkpoints ------------------------------------------------------
    def model_path(self, model_id: int) -> str:
        return os.path.join(self.args.get('model_dir', 'models'),
                            str(model_id) + '.ckpt')

    def latest_model_path(self) -> str:
        return os.path.join(self.args.get('model_dir', 'models'), 'latest.ckpt')

    def trainer_state_path(self) -> str:
        return os.path.join(self.args.get('model_dir', 'models'),
                            'trainer_state.ckpt')

    def update_model(self, params, steps: int,
                     state_blob: Optional[bytes] = None, bump: bool = True,
                     write_files: bool = True):
        """Advance the model epoch; persist snapshot + ckpt files unless
        ``write_files`` is False (checkpoint_interval's skip epochs, where
        params never leave the device)."""
        print('updated model(%d)' % steps)
        if bump:
            self.model_epoch += 1
            # chaos 'nanepoch': poison updates right after this epoch's
            # checkpoint lands, so a rollback target provably exists
            if self._chaos.get('nanepoch') == self.model_epoch:
                self.trainer.chaos_nan.arm(self.trainer.steps + 1)
        if not write_files:
            return
        self._last_ckpt_epoch = self.model_epoch
        self._last_ckpt_steps = steps
        # learner-side copy stays on HOST (numpy): it only feeds
        # snapshots/checkpoints; per-leaf device uploads each epoch
        # would pay a tunnel round trip per leaf
        self.wrapper.params = jax.tree_util.tree_map(np.asarray, params)
        os.makedirs(self.args.get('model_dir', 'models'), exist_ok=True)
        raw = self.wrapper.params_bytes()
        # atomic (temp + fsync + rename) plus a CRC32 sidecar manifest: a
        # crash mid-write must never leave a truncated latest.ckpt /
        # trainer_state.ckpt, and resume verifies the checksum so silent
        # on-disk corruption falls back instead of poisoning the restart.
        # A mesh-layout manifest rides along: checkpoints serialize full
        # host arrays, so they restore under ANY device/host count — the
        # manifest records what wrote them so the mesh change is logged,
        # and a corrupt manifest disqualifies the pair like a bad CRC.
        from .parallel.partition import checkpoint_layout
        from .utils.fs import write_layout_manifest
        layout = checkpoint_layout(self.trainer.mesh,
                                   self.trainer.partition_rules, steps=steps)
        for path in (self.model_path(self.model_epoch), self.latest_model_path()):
            checksummed_write_bytes(path, raw)
            write_layout_manifest(path, layout)
        if state_blob is not None:
            checksummed_write_bytes(self.trainer_state_path(), state_blob)
            write_layout_manifest(self.trainer_state_path(), layout)
        # publish BEFORE retention GC: a version the registry is about to
        # pin must be pinned by the time the GC pass reads the manifest
        self._publish_checkpoint(steps)
        self._gc_checkpoints()
        # durable plane rides the checkpoint cadence: the ledger snapshot
        # and the spool GC horizon must describe a state a restart can
        # actually resume from, i.e. one with a durable checkpoint
        self._sync_durable_state()

    def _registry_root(self) -> str:
        srv = self.args.get('serving') or {}
        return srv.get('registry_dir') or self.args.get('model_dir', 'models')

    def _ensure_registry(self):
        if self._registry is None:
            from .serving.registry import ModelRegistry
            self._registry = ModelRegistry(self._registry_root())
        return self._registry

    def _publish_checkpoint(self, steps: int):
        """``serving.publish``: register the just-written numbered
        checkpoint with the ModelRegistry as ``<line>@<epoch>`` (pinning it
        against ``keep_checkpoints`` GC); ``serving.auto_promote`` also
        makes it the line's champion in the same atomic manifest swap —
        unless the league owns promotion (league.enabled), in which case
        versions publish as candidates and the champion only flips through
        the rating gate (:meth:`_league_epoch_sync`). A registry failure is
        loud but never takes training down."""
        srv = self.args.get('serving') or {}
        if not srv.get('publish'):
            return
        if self._registry is None:
            from .serving.registry import ModelRegistry
            self._registry = ModelRegistry(self._registry_root())
        try:
            from . import models as model_zoo
            from .model import module_config
            promote = bool(srv.get('auto_promote', True))
            if getattr(self, '_league', None) is not None:
                # rating-gated promotion replaces recency auto_promote
                # (the registry still bootstraps the FIRST version as
                # champion — a line must never be headless)
                promote = False
            self._registry.publish(
                str(srv.get('line', 'default')),
                path=self.model_path(self.model_epoch),
                architecture=model_zoo.architecture_name(self.wrapper.module),
                config=module_config(self.wrapper.module) or None,
                steps=int(steps), version=self.model_epoch,
                promote=promote)
        except Exception as exc:
            _LOG.error('registry publish of epoch %d failed (%s: %s); '
                       'training continues unpublished', self.model_epoch,
                       type(exc).__name__, str(exc)[:200])
            telemetry.counter('registry_publish_failures_total').inc()
        sync = getattr(self, '_league_epoch_sync', None)
        if sync is not None:
            sync()

    def _league_epoch_sync(self):
        """League epoch boundary (after publish, before retention GC):
        refresh the member window from the registry manifest, run the
        rating-gated promotion, export the rating gauges, and journal the
        book atomically. Failures are loud but never take training down."""
        if getattr(self, '_league', None) is None \
                or self._league_ratings is None:
            return
        book = self._league_ratings
        try:
            reg = self._ensure_registry()
            self._league.refresh(reg)
            # a fresh member is a snapshot of the learner: seed it at the
            # learner's current rating instead of the cold initial_rating
            known = set(book.names())
            for m in self._league.members():
                if m not in known:
                    book.seed(m, book.rating(league_mod.LEARNER))
            if self._league.should_promote(book):
                incumbent = self._league.champion
                reg.promote(self._league.line, self.model_epoch)
                book.note_promotion()
                telemetry.counter('league_promotions_total').inc()
                self._league.refresh(reg)
                print('league: promoted %s@%d (learner %.1f vs incumbent '
                      '%s %.1f)' % (self._league.line, self.model_epoch,
                                    book.rating(league_mod.LEARNER),
                                    incumbent,
                                    book.rating(incumbent)
                                    if incumbent else float('nan')))
            for name in set(self._league.roster()) | set(book.names()):
                telemetry.gauge('league_rating', member=name).set(
                    round(book.rating(name), 2))
            book.save(self._league_journal)
        except Exception as exc:
            _LOG.error('league: epoch sync failed (%s: %s); training '
                       'continues', type(exc).__name__, str(exc)[:200])

    # -- checkpoint integrity / retention / rollback -----------------------
    def _load_resume_params(self):
        """Load the resume params for ``self.model_epoch``, falling back to
        the newest EARLIER checkpoint that both passes CRC verification and
        deserializes, instead of crashing on corrupt/truncated bytes."""
        from .utils.fs import verify_checkpoint
        model_dir = self.args.get('model_dir', 'models')
        candidates = [self.model_epoch] + [
            e for e in reversed(guard_mod.numbered_checkpoints(model_dir))
            if e < self.model_epoch]
        from .utils.fs import read_layout_manifest
        for epoch in candidates:
            path = self.model_path(epoch)
            ok, reason = verify_checkpoint(path)
            if not ok:
                _LOG.error('discarding checkpoint %s: %s', path, reason)
                telemetry.counter('guard_ckpt_fallbacks_total').inc()
                continue
            # a PRESENT but corrupt layout manifest disqualifies the pair
            # exactly like a failed CRC (missing = legacy, loadable)
            _layout, lreason = read_layout_manifest(path)
            if lreason == 'unparsable':
                _LOG.error('discarding checkpoint %s: corrupt layout '
                           'manifest', path)
                telemetry.counter('guard_ckpt_fallbacks_total').inc()
                continue
            try:
                with open(path, 'rb') as f:
                    self.wrapper.load_params_bytes(f.read(), self._example_obs)
            except Exception as exc:
                _LOG.error('discarding undecodable checkpoint %s (%s: %s)',
                           path, type(exc).__name__, str(exc)[:120])
                telemetry.counter('guard_ckpt_fallbacks_total').inc()
                continue
            if epoch != self.model_epoch:
                print('resume fell back to epoch %d (epoch %d checkpoint '
                      'invalid)' % (epoch, self.model_epoch))
                self.model_epoch = epoch
                self.args['restart_epoch'] = epoch
            return
        raise FileNotFoundError(
            'no loadable checkpoint at or below epoch %d in %s'
            % (self.model_epoch, model_dir))

    def _rollback_source(self):
        """(epoch, trainer_state bytes) of the newest valid checkpoint pair
        for the non-finite guard's in-place rollback; None before the first
        checkpoint lands (the guard then stays in skip mode)."""
        from .utils.fs import read_verified_bytes
        blob = read_verified_bytes(self.trainer_state_path())
        if blob is None:
            return None
        epoch, _discarded = guard_mod.newest_valid_epoch(
            self.args.get('model_dir', 'models'))
        if epoch <= 0:
            return None
        return epoch, blob

    def _apply_rollback(self, epoch: int):
        """The trainer restored its TrainState in place; rewind the
        model-pool epoch and the actor-facing host params to match, so
        subsequent checkpoints overwrite the poisoned trajectory."""
        try:
            with open(self.model_path(epoch), 'rb') as f:
                self.wrapper.load_params_bytes(f.read(), self._example_obs)
        except Exception as exc:
            _LOG.error('rollback: could not reload epoch %d params (%s: %s)',
                       epoch, type(exc).__name__, str(exc)[:120])
        prev = self.model_epoch
        self.model_epoch = min(self.model_epoch, epoch)
        print('guard: rolled back to epoch %d (from epoch %d)'
              % (self.model_epoch, prev))

    def _fused_guard_observe(self, metrics: Dict[str, float], fp):
        """Guard escalation for the fused loop (single-threaded: the
        rollback happens inline, including the model-pool rewind)."""
        tr = self.trainer
        bad = int(metrics.get('nonfinite') or 0)
        if bad:
            telemetry.counter('guard_nonfinite_total').inc(bad)
        cnt = int(metrics.get('data_count') or 0)
        loss_mean = (float(metrics['total']) / cnt
                     if cnt and 'total' in metrics else None)
        action = tr.guard.observe(bad, max(0, fp.sgd_steps - bad), loss_mean)
        if action == 'abort':
            raise RuntimeError(
                'guard: %d non-finite update(s) under nonfinite_policy='
                'abort' % bad)
        if action == 'skip':
            _LOG.warning('guard: skipped %d non-finite update(s) '
                         '(%d consecutive)', bad, tr.guard.consecutive)
        if action != 'rollback':
            return
        src = self._rollback_source()
        if src is None:
            _LOG.error('guard: rollback tripped but no valid checkpoint '
                       'exists yet; continuing with skipped updates')
            tr.guard.reset_streak()
            return
        epoch, blob = src
        tr.load_state_bytes(blob)   # place_state lays it back on the mesh
        tr.guard.reset_streak()
        tr.guard.rollbacks += 1
        telemetry.counter('guard_rollbacks_total').inc()
        _LOG.error('guard: non-finite training burst — rolled back to '
                   'checkpoint epoch %d (steps %d)', epoch, tr.steps)
        self._apply_rollback(epoch)

    def _poll_rollback(self):
        """Pick up a rollback the trainer thread performed since the last
        loop iteration (threaded/replay trainers; the fused loop rolls back
        inline)."""
        epoch = self.trainer.rollback_epoch
        if epoch is not None:
            self.trainer.rollback_epoch = None
            self._apply_rollback(epoch)

    def _gc_checkpoints(self):
        """``keep_checkpoints: N`` retention: drop numbered ckpts beyond
        the newest N (plus their sidecars). League-opponent checkpoint
        paths and registry-pinned versions (the serving tier's champion or
        any live candidate — serving/registry.py) are never deleted; the
        rollback target (the newest valid epoch) is always inside the kept
        window. An unreadable registry manifest SUSPENDS the GC pass: with
        the pin set unknown, deleting anything could pull a champion out
        from under a live service."""
        keep = int(self.args.get('keep_checkpoints') or 0)
        if keep <= 0:
            return
        from .utils.fs import layout_path, sidecar_path
        model_dir = self.args.get('model_dir', 'models')
        epochs = guard_mod.numbered_checkpoints(model_dir)
        if len(epochs) <= keep:
            return
        from .serving.registry import pinned_checkpoint_paths
        pinned = pinned_checkpoint_paths(self._registry_root())
        if pinned is None:
            return   # corrupt manifest: conservatively collect nothing
        if getattr(self, '_league', None) is not None:
            # league-pool members must outlive the retention window for as
            # long as PFSP can sample them (the member window can trail
            # keep_checkpoints); counted via guard_ckpt_gc_pinned_total
            # like any registry pin
            pinned = pinned | {os.path.abspath(p)
                               for p in self._league.member_paths()}
        protected = {os.path.abspath(o)
                     for o in (self.args.get('eval', {}).get('opponent') or [])
                     if isinstance(o, str) and os.path.exists(o)}
        for epoch in epochs[:-keep]:
            path = self.model_path(epoch)
            apath = os.path.abspath(path)
            if apath in pinned:
                telemetry.counter('guard_ckpt_gc_pinned_total').inc()
                continue   # registry-pinned: serving depends on these bytes
            if apath in protected:
                continue   # checkpoint league opponent
            for p in (path, sidecar_path(path), layout_path(path)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            telemetry.counter('guard_ckpt_gc_total').inc()

    def final_flush(self):
        """ONE code path for the fused-loop tail flush and the preemption
        snapshot: persist the current TrainState/params at most once, so a
        SIGTERM landing during the final epoch cannot write
        trainer_state.ckpt twice with different step counts."""
        if self._final_flushed:
            return
        self._final_flushed = True
        tr = self.trainer
        params = steps = blob = None
        if self._fused_active:
            if tr.state is not None:
                from .utils.fetch import fetch_tree
                host_state = fetch_tree(tr.state)
                params, steps = host_state.params, tr.steps
                blob = tr.state_bytes(host_state)
        elif (tr.started and tr.state is not None
              and self._trainer_thread is not None
              and self._trainer_thread.is_alive()):
            # threaded/server modes: the trainer owns the state — force an
            # epoch close and take the handover at the next batch boundary
            try:
                params, steps, blob = tr.update(timeout=60)
            except queue.Empty:
                _LOG.warning('flush: trainer did not reach a safe point in '
                             'time; keeping the last epoch checkpoint')
        if params is None:
            return
        if (self.model_epoch == self._last_ckpt_epoch
                and steps == self._last_ckpt_steps):
            return   # nothing advanced since the last write
        self.update_model(params, steps, blob, bump=False)

    def _write_preempt_record(self):
        """Final metrics_jsonl record tagged ``preempted`` + the exit-code
        contract line the supervisor greps for. Steps are the FLUSHED
        count (what resume will restore), not the live trainer counter —
        the JSONL step sequence stays monotonic across the restart."""
        telemetry.counter('guard_preemptions_total').inc()
        if getattr(self, '_league_ratings', None) is not None \
                and self._league_journal:
            # the ratings journal rides the preemption flush: the restart
            # reloads it bit-identically (atomic write, sorted keys)
            self._league_ratings.save(self._league_journal)
        steps = max(self._last_ckpt_steps, 0)
        self._write_metrics(steps, extra={
            'preempted': True, 'signal': int(self.preempt.signum or 0)})
        print('preempted: checkpoint flushed at epoch %d (steps %d); '
              'exiting %d for a supervisor restart'
              % (self.model_epoch, steps,
                 guard_mod.PREEMPT_EXIT_CODE), flush=True)

    # -- accounting -------------------------------------------------------
    def feed_episodes(self, episodes: List[Optional[dict]],
                      recovered: bool = False):
        """``recovered=True`` marks a restart replay from the episode
        spool: the episodes were already WAL'd and their ratings already
        journaled, so they skip the spool append and the league booking —
        everything else (guard screen, generation stats, the returned
        counter, the buffer) treats them exactly like a fresh upload."""
        if self._check_episodes:
            # ingest guard: one poisoned actor (NaN observations/rewards)
            # must not contaminate every future batch — drop and count
            # before anything enters the episode deque
            clean: List[Optional[dict]] = []
            for episode in episodes:
                if (episode is not None
                        and not guard_mod.episode_is_finite(episode)):
                    self._bad_episodes += 1
                    telemetry.counter('guard_bad_episodes_total').inc()
                    _LOG.warning('guard: dropped episode with non-finite '
                                 'data (%d total)', self._bad_episodes)
                    continue
                clean.append(episode)
            episodes = clean
        for episode in episodes:
            if episode is None:
                continue
            if self._spool is not None and not recovered:
                # WAL before ANY accounting: a SIGKILL past this line
                # replays the episode on restart; before it, the episode
                # never existed (its ledger task re-issues byte-identically)
                self._spool.append(
                    self.num_returned_episodes,
                    conn_pack({'idx': self.num_returned_episodes,
                               'episode': episode}))
            if episode.get('record_version'):
                # device-actor records that follow the device rng contract
                # instead of the host byte contract arrive stamped; the
                # counter keeps the divergence observable fleet-wide
                telemetry.counter(
                    'device_actor_stamped_episodes_total').inc()
            for p in episode['args']['player']:
                # attribute stats to the model that actually generated the
                # episode (the reference books everything under the current
                # epoch — its correct line is commented out at
                # train.py:461-462; with chunked generation spanning epoch
                # boundaries that skew would only grow)
                model_id = (episode['args'].get('model_id') or {}).get(p, -1)
                if model_id is None or model_id < 0:
                    model_id = self.model_epoch
                outcome = episode['outcome'][p]
                n, r, r2 = self.generation_results.get(model_id, (0, 0, 0))
                self.generation_results[model_id] = (n + 1, r + outcome,
                                                     r2 + outcome ** 2)
            if not recovered:
                self._league_observe_episode(episode)
            self.num_returned_episodes += 1
            if self.num_returned_episodes % 100 == 0:
                # complete line at debug level, not a bare dot stream that
                # splices mid-line with worker-process output
                _LOG.debug('returned %d episodes', self.num_returned_episodes)

        live = [e for e in episodes if e is not None]
        telemetry.counter('learner_episodes_returned_total').inc(len(live))
        # ingest stamp for the sample-age histogram: selection-time age is
        # measured against this learner-side clock (no cross-host skew)
        now = time.time()
        for e in live:
            e.setdefault('recv_time', now)
        self.trainer.episodes.extend(live)
        if self.trainer.ingest_queue is not None:
            # best-effort under backlog, but every drop is counted — the
            # metrics JSONL exposes how much generation never reached the ring
            for e in live:
                try:
                    self.trainer.ingest_queue.put_nowait(e)
                except queue.Full:
                    self.trainer.replay_stats['dropped_episodes'] += 1

        self._evict_episode_overflow()

    def _evict_episode_overflow(self):
        """Bound the host episode deque (memory-pressure-aware), shared by
        the whole-episode and streamed-chunk ingest paths."""
        mem_percent = psutil.virtual_memory().percent
        mem_ok = mem_percent <= 95
        maximum_episodes = (self.args['maximum_episodes'] if mem_ok else
                            int(len(self.trainer.episodes) * 95 / mem_percent))
        if self.trainer.replay is not None:
            # replay mode: training data lives in the HBM ring; the host
            # deque only gates startup and feeds metrics — don't hold a
            # second full copy of the buffer
            maximum_episodes = min(maximum_episodes,
                                   2 * self.args['minimum_episodes'])
        if not mem_ok and 'memory_over' not in self.flags:
            warnings.warn('memory usage %.1f%% with buffer size %d' %
                          (mem_percent, len(self.trainer.episodes)))
            self.flags.add('memory_over')
        while len(self.trainer.episodes) > maximum_episodes:
            self.trainer.episodes.popleft()

    def feed_chunks(self, chunks: List[Optional[dict]],
                    recovered: bool = False,
                    marks: Optional[list] = None) -> list:
        """Streamed-ingest twin of :meth:`feed_episodes` (streaming.py).

        Each (ledger-screened) chunk is WAL'd, folded into its assembly,
        and — the moment its contiguous prefix grows — training-visible as
        a partial buffer entry. A completed assembly closes its ledger
        task and runs the exact whole-episode accounting feed_episodes
        runs, on the byte-identical reassembled record. Returns the
        ``(key, final_args)`` pairs of the episodes completed here (spool
        recovery uses them to cancel restored book entries)."""
        from .streaming import chunk_key
        completed = []
        for j, chunk in enumerate(chunks):
            if chunk is None:
                continue
            mark = marks[j] if marks is not None \
                else self.num_returned_episodes
            if self._spool is not None and not recovered:
                # WAL before ANY accounting (same stance as feed_episodes):
                # recovery replays the chunk, the assembler dedupes it
                self._spool.append(
                    self.num_returned_episodes,
                    conn_pack({'idx': self.num_returned_episodes,
                               'chunk': chunk}))
            res = self._assembler.add(chunk, mark=mark)
            status = res.get('status')
            if status == 'dropped':
                continue
            entry = res.get('entry')
            if res.get('new') and entry is not None:
                entry.setdefault('recv_time', time.time())
                self.trainer.episodes.append(entry)
            if status != 'complete':
                continue
            key = chunk_key(chunk)
            final_args = res.get('final_args') or {}
            completed.append((key, final_args))
            if self.ledger is not None:
                self.ledger.complete_chunked(key, final_args.get('task_id'))
            record = res.get('record')
            if record is None:
                # a poisoned chunk froze the assembly: the task closed, the
                # record drops whole (mirrors the feed_episodes screen)
                self._bad_episodes += 1
                telemetry.counter('guard_bad_episodes_total').inc()
                _LOG.warning('guard: dropped streamed episode with '
                             'non-finite data (%d total)', self._bad_episodes)
                continue
            if record.get('record_version'):
                telemetry.counter(
                    'device_actor_stamped_episodes_total').inc()
            for p in record['args']['player']:
                model_id = (record['args'].get('model_id') or {}).get(p, -1)
                if model_id is None or model_id < 0:
                    model_id = self.model_epoch
                outcome = record['outcome'][p]
                n, r, r2 = self.generation_results.get(model_id, (0, 0, 0))
                self.generation_results[model_id] = (n + 1, r + outcome,
                                                     r2 + outcome ** 2)
            if not recovered:
                self._league_observe_episode(record)
            self.num_returned_episodes += 1
            telemetry.counter('learner_episodes_returned_total').inc()
            if self.num_returned_episodes % 100 == 0:
                _LOG.debug('returned %d episodes',
                           self.num_returned_episodes)
            if self.trainer.ingest_queue is not None and entry is not None:
                try:
                    self.trainer.ingest_queue.put_nowait(entry)
                except queue.Full:
                    self.trainer.replay_stats['dropped_episodes'] += 1
        self._evict_episode_overflow()
        return completed

    def feed_device_chunk(self, done, outcome,
                          model_id: Optional[int] = None) -> int:
        """Episode accounting for device-ingested rollout chunks: only the
        (done, outcome) arrays reach the host — trajectories stay in HBM
        (ops/device_windows.py). Mirrors feed_episodes' generation stats
        (every player's outcome counts, feed over args['player']).
        ``model_id`` is the epoch whose params generated the chunk, captured
        by the caller at dispatch time so stats survive epoch boundaries."""
        if model_id is None:
            model_id = self.model_epoch
        ks, envs = np.nonzero(done)
        num_players = outcome.shape[-1]
        for k, i in zip(ks, envs):
            for p in range(num_players):
                oc = float(outcome[k, i, p])
                n, r, r2 = self.generation_results.get(model_id, (0, 0, 0))
                self.generation_results[model_id] = (n + 1, r + oc,
                                                     r2 + oc ** 2)
            self.num_episodes += 1
            self.num_returned_episodes += 1
            if self.num_returned_episodes % 100 == 0:
                _LOG.debug('returned %d episodes', self.num_returned_episodes)
        telemetry.counter('learner_episodes_returned_total').inc(len(ks))
        return len(ks)

    def feed_results(self, results: List[Optional[dict]],
                     model_id: Optional[int] = None):
        """``model_id`` lets pipelined device evaluators attribute results
        to the epoch whose params were actually playing when the chunk was
        dispatched (they deliver results one dispatch late)."""
        if model_id is None:
            model_id = self.model_epoch
        for result in results:
            if result is None:
                continue
            for p in result['args']['player']:
                res = result['result'][p]
                n, r, r2 = self.results.get(model_id, (0, 0, 0))
                self.results[model_id] = (n + 1, r + res, r2 + res ** 2)
                opp_map = self.results_per_opponent.setdefault(model_id, {})
                opponent = result['opponent']
                n, r, r2 = opp_map.get(opponent, (0, 0, 0))
                opp_map[opponent] = (n + 1, r + res, r2 + res ** 2)
            self._league_observe_result(result)

    # -- league plumbing --------------------------------------------------
    def _league_gen_opponent(self, sample_key: int):
        """PFSP draw for the 'g' task stamped ``sample_key``: the
        ``(member, model_id)`` the opponent seats carry, or None for the
        self-play share / an empty pool. Deterministic per (seed,
        sample_key) — a ledger re-issue keeps the assignment anyway (the
        ledger replays the booked role_args verbatim, fault.py)."""
        if getattr(self, '_league', None) is None \
                or self._league_ratings is None:
            return None
        member = self._league.sample_opponent(
            int(self.args.get('seed') or 0), sample_key, self._league_ratings)
        if member is None:
            return None
        mid = self._league.member_model_id(member)
        if mid is None:
            return None
        return member, mid

    def _league_rating_opponent(self, counter: int):
        """Round-robin rating-match opponent for the 'e' slice, or None
        when this slot stays a configured-pool eval match."""
        if getattr(self, '_league', None) is None:
            return None
        rate = float(self._league.args.get('rating_match_rate', 0.25))
        if rate <= 0.0:
            return None
        # every ceil(1/rate)-th 'e' task becomes a rating match — a
        # deterministic stride, not a draw: coverage is the goal here
        stride = max(1, int(round(1.0 / rate)))
        if counter % stride != 0:
            return None
        return self._league.rating_opponent(counter // stride)

    def _league_model_snapshot(self, model_id) -> Optional[dict]:
        """'model' RPC fallback: resolve a league member version through
        the registry manifest (CRC-verified load) when the numbered
        checkpoint is gone from model_dir. None when the league is off or
        the registry cannot produce the version either."""
        if getattr(self, '_league', None) is None:
            return None
        try:
            snap = self._ensure_registry().load_snapshot(
                self._league.line, str(model_id))
            return {k: snap[k] for k in ('architecture', 'params', 'config')
                    if k in snap}
        except Exception as exc:
            _LOG.warning('league: registry could not resolve model %s '
                         '(%s: %s)', model_id, type(exc).__name__,
                         str(exc)[:120])
            return None

    def _league_observe_episode(self, episode: dict):
        """Book a league 'g' outcome: the learner's score vs the member
        the server seated (stamped league_opponent/league_seat)."""
        if getattr(self, '_league_ratings', None) is None:
            return
        args = episode.get('args') or {}
        member = args.get('league_opponent')
        if not member:
            return
        outcome = episode['outcome'].get(args.get('league_seat'))
        if outcome is None:
            return
        self._league_ratings.record(member, (float(outcome) + 1.0) / 2.0)
        self._league_sampled[member] = self._league_sampled.get(member, 0) + 1
        telemetry.counter('league_games_total').inc()
        self._league_flush_maybe()

    def _league_observe_result(self, result: dict):
        """Book a league rating match ('e' slice): the evaluated seat's
        result vs the member named by the task's opponent override."""
        if getattr(self, '_league_ratings', None) is None:
            return
        args = result.get('args') or {}
        if not args.get('league_rating_match'):
            return
        member = result.get('opponent')
        seats = args.get('player') or []
        if not member or not seats:
            return
        res = result['result'].get(seats[0])
        if res is None:
            return
        self._league_ratings.record(member, (float(res) + 1.0) / 2.0)
        telemetry.counter('league_games_total').inc()
        self._league_flush_maybe()

    def _league_flush_maybe(self):
        """Write the rating journal through shortly after an outcome lands
        (league.rating_flush_seconds min-interval): a hard-killed learner
        loses at most that window of ratings instead of everything since
        the last epoch sync. The journal write is already atomic
        (RatingBook.save -> atomic_write_bytes), so a kill mid-flush
        leaves the previous journal intact."""
        if getattr(self, '_league_ratings', None) is None \
                or not self._league_journal:
            return
        interval = float((self.args.get('league') or {})
                         .get('rating_flush_seconds', 5.0))
        if interval <= 0:
            return
        now = time.monotonic()
        if now - self._league_last_flush < interval:
            return
        self._league_last_flush = now
        self._league_ratings.save(self._league_journal)

    def _print_league_stats(self):
        if getattr(self, '_league', None) is None \
                or self._league_ratings is None:
            return
        book = self._league_ratings
        print('league: learner=%.1f games=%d members=%d champion=%s '
              'promotions=%d'
              % (book.rating(league_mod.LEARNER), book.games_since_promote,
                 len(self._league.members()), self._league.champion,
                 book.promotions))

    # -- telemetry plumbing ----------------------------------------------
    def _telemetry_snapshots(self) -> List[dict]:
        """Exporter collector: live local registry + the latest merged
        fleet snapshot (tagged source="fleet" to keep keys disjoint)."""
        telemetry.gauge('learner_epoch').set(self.model_epoch)
        telemetry.gauge('learner_buffer_episodes').set(
            len(self.trainer.episodes))
        telemetry.gauge('learner_sgd_steps_per_sec').set(
            self.trainer.last_steps_per_sec)
        snaps = [telemetry.snapshot()]
        fleet = self._last_fleet_telemetry
        if fleet and fleet.get('peers'):
            snaps.append(telemetry.relabel(fleet, source='fleet'))
        return snaps

    def _status_info(self) -> Dict[str, Any]:
        """/statusz payload: run progress, alert state, fleet host map.
        Scrape-driven alert evaluation shares the cadence gate with the
        server loop, so a scrape storm cannot distort rate windows."""
        info: Dict[str, Any] = {'progress': {
            'epoch': self.model_epoch,
            'steps': int(getattr(self.trainer, 'steps', 0)),
            'episodes': self.num_returned_episodes,
            'buffer': len(self.trainer.episodes)}}
        if self._alerts is not None:
            info['alerts'] = self._alerts.maybe_evaluate(
                self._telemetry_snapshots)
        if getattr(self, 'fleet', None) is not None:
            info['fleet_hosts'] = self.fleet.snapshot()
        if telemetry.perf_plane_enabled():
            info['perf'] = telemetry.perf_status()
        return info

    def _merge_fleet_telemetry(self) -> dict:
        """Aggregate the registry snapshots that rode in on the latest
        heartbeat per peer (gathers pre-merge their workers' snapshots)."""
        peers = self.worker.peer_info().values() if self.worker else ()
        merged = telemetry.merge_snapshots(
            [p.get('telemetry') for p in peers if isinstance(p, dict)])
        self._last_fleet_telemetry = merged
        return merged

    def _lag_snapshot(self) -> Dict[str, float]:
        """Epoch means of the policy-lag / sample-age histograms (delta
        since the previous epoch), mirrored onto plainly-named gauges so
        ``policy_lag`` and ``sample_age_seconds`` are scrapeable live."""
        out: Dict[str, float] = {}
        batcher = getattr(self.trainer, 'batcher', None)
        if batcher is None:
            return out
        for attr, key in (('_m_lag', 'policy_lag'),
                          ('_m_age', 'sample_age_seconds')):
            hist = getattr(batcher, attr, None)
            if hist is None:
                continue
            s, n = hist.sum, hist.count
            prev_s, prev_n = self._lag_marks.get(key, (0.0, 0))
            self._lag_marks[key] = (s, n)
            if n > prev_n:
                mean = (s - prev_s) / (n - prev_n)
                out[key] = round(mean, 4)
                telemetry.gauge(key + '_mean').set(mean)
        return out

    # -- device profiling (profile_epochs) --------------------------------
    def _maybe_profile(self):
        """Open/close the jax.profiler device trace around the epochs the
        ``profile_epochs`` knob names: epoch N's SGD work runs between the
        close of epoch N-1 and the close of epoch N, so the trace starts
        at the boundary BEFORE a chosen epoch and stops at its close
        (Trainer._start/_stop_trace are idempotent and exception-safe)."""
        if not self._profile_epochs:
            return
        tr = self.trainer
        if tr._trace_active:
            tr._stop_trace()
        if (self.model_epoch + 1) in self._profile_epochs:
            _LOG.info('profiling epoch %d (device trace -> %s)',
                      self.model_epoch + 1, tr._profile_dir)
            try:
                tr._start_trace()
            except Exception as exc:
                _LOG.warning('profiler start failed (%s: %s)',
                             type(exc).__name__, str(exc)[:120])

    # -- epoch boundary ---------------------------------------------------
    def update(self):
        print()
        print('epoch %d' % self.model_epoch)
        self._print_eval_stats()
        self._print_generation_stats()
        self._print_league_stats()

        with telemetry.span('epoch_update'):
            params, steps, state_blob = self.trainer.update()
        if params is None and self.trainer.failed:
            _LOG.error('training failed (see traceback above); shutting down')
            self.shutdown_flag = True
            return
        if params is None:
            params = self.wrapper.params
        self.update_model(params, steps, state_blob)
        self._write_metrics(steps)
        self._maybe_profile()
        self.flags = set()

    def _write_metrics(self, steps: int, extra: Optional[dict] = None):
        if not self._metrics_path:
            return
        rec = {'epoch': self.model_epoch, 'steps': steps,
               'episodes': self.num_returned_episodes, 'time': time.time(),
               'run_id': telemetry.run_id(),
               'sgd_steps_per_sec': round(self.trainer.last_steps_per_sec, 3),
               'buffer': len(self.trainer.episodes)}
        if extra:
            rec.update(extra)
        gen = self.generation_results.get(self.model_epoch - 1)
        if gen:
            n, r, _ = gen
            rec['generation_mean'] = r / (n + 1e-6)
        ev = self.results.get(self.model_epoch - 1)
        if ev:
            n, r, _ = ev
            rec['win_rate'] = (r / (n + 1e-6) + 1) / 2
        # per-opponent rows ride EVERY record (the console line still
        # collapses a 1-opponent pool to the reference format): with a
        # league pool the aggregate win rate hides exactly the per-member
        # signal the ratings are built from
        ev_opp = self.results_per_opponent.get(self.model_epoch - 1)
        if ev_opp:
            rec['eval_opponents'] = {
                name: {'games': n,
                       'win_rate': round((r / (n + 1e-6) + 1) / 2, 4)}
                for name, (n, r, _r2) in sorted(ev_opp.items())}
        if getattr(self, '_league', None) is not None \
                and self._league_ratings is not None:
            book = self._league_ratings
            names = sorted(set(book.names()) | set(self._league.roster()))
            rec['league'] = {
                'champion': self._league.champion,
                'members': self._league.members(),
                'ratings': {n: round(book.rating(n), 2) for n in names},
                'games': {n: book.games(n) for n in names},
                'games_since_promote': book.games_since_promote,
                'promotions': book.promotions,
                'opponents_sampled': dict(sorted(
                    self._league_sampled.items())),
            }
        # fast runs see only a handful of eval games per epoch (an epoch can
        # last ~2s); a trailing-window aggregate keeps the quality curve
        # readable from the JSONL alone
        recent = [self.results[e] for e in
                  range(max(0, self.model_epoch - 10), self.model_epoch)
                  if e in self.results]
        if recent:
            n = sum(t[0] for t in recent)
            r = sum(t[1] for t in recent)
            rec['win_rate_recent10'] = (r / (n + 1e-6) + 1) / 2
            rec['eval_games_recent10'] = n
        if self.trainer.replay is not None:
            stats = self.trainer.replay_stats
            rec['replay_dropped_episodes'] = stats['dropped_episodes']
            rec['replay_ring_occupancy'] = round(
                self.trainer.ring_occupancy(), 4)
            rec['replay_sample_reuse'] = round(
                stats['samples_drawn'] / max(1, stats['windows_ingested']), 3)
        # learning dynamics (ops/train_step.py diag metrics, per epoch):
        # rho/c clip fractions, importance-ratio moments, entropy, grad
        # norm — the off-policy health the streaming-ingest and staleness-
        # weighting work will be judged against (docs/observability.md)
        rec.update(self.trainer.last_dynamics)
        # policy-lag accounting: epoch means of the lag/age histograms the
        # batcher observes at window selection (consumption time)
        rec.update(self._lag_snapshot())
        # guard health: cumulative skipped non-finite updates, in-place
        # rollbacks, and dropped poisoned episodes (guard.py)
        rec['guard_nonfinite'] = self.trainer.guard.total_bad
        rec['guard_rollbacks'] = self.trainer.guard.rollbacks
        rec['guard_bad_episodes'] = self._bad_episodes
        # compiled-performance plane: per-epoch device-memory sample (the
        # hbm_pressure alert input — only the learner publishes the ratio
        # gauge, a ratio must not sum across fleet snapshots), steady-state
        # marking once warm-up is over, and the chaos retrace probe
        # (HANDYRL_TPU_CHAOS=retraceepoch=N) for e2e sentinel drills
        if telemetry.perf_plane_enabled():
            mem_rows = telemetry.sample_device_memory()
            telemetry.gauge('device_mem_utilization').set(
                round(telemetry.device_memory_utilization(mem_rows), 6))
            if (not telemetry.steady_state_active()
                    and self.model_epoch >= self._retrace_warmup):
                telemetry.mark_steady_state(
                    'epoch %d (retrace_warmup_epochs=%d)'
                    % (self.model_epoch, self._retrace_warmup))
            chaos_at = self._chaos.get('retraceepoch')
            if chaos_at is not None and self.model_epoch == int(chaos_at):
                self._chaos_retrace_probe()
        if getattr(self, 'ledger', None) is not None:
            rec.update({'fleet_' + k: v
                        for k, v in self._fleet_snapshot().items()
                        if k != 'disconnects'})
        # unified telemetry: the learner's own registry plus the merged
        # per-peer snapshots that rode in on heartbeat frames (worker-mode
        # runs), histograms reduced to count/sum/p50/p95/p99
        telemetry.gauge('learner_epoch').set(self.model_epoch)
        telemetry.gauge('learner_buffer_episodes').set(
            len(self.trainer.episodes))
        rec['telemetry'] = telemetry.summarize(telemetry.snapshot())
        if self.worker is not None:
            rec['fleet_telemetry'] = telemetry.summarize(
                self._merge_fleet_telemetry())
        # SLO alert state rides every record: active names, cumulative
        # fired counts, and the last evaluated value per rule
        if self._alerts is not None:
            rec['alerts'] = self._alerts.maybe_evaluate(
                self._telemetry_snapshots)
        # size-based rotation (telemetry.metrics_rotate_mb): long runs must
        # not grow the JSONL unboundedly — atomic rename to `.1` keeps one
        # previous generation around for postmortems
        if self._metrics_rotate_mb > 0 and rotate_file(
                self._metrics_path, self._metrics_rotate_mb):
            telemetry.counter('metrics_rotations_total').inc()
        # append-safe single-write line + fsync: a killed learner can never
        # leave a torn half-line that breaks downstream JSONL parsing
        append_jsonl(self._metrics_path, rec)
        telemetry.trace_flush()   # epoch boundary: land buffered spans

    def _chaos_retrace_probe(self):
        """Chaos hook: compile a deliberately fresh jitted program after
        steady state so an e2e drill can watch the retrace sentinel fire
        (retrace_storm alert, flight-recorder event, abort policy)."""
        _LOG.warning('chaos: compiling a fresh program at epoch %d to '
                     'trigger the retrace sentinel', self.model_epoch)

        def chaos_retrace_probe(x):
            return x + 1.0
        # device_put (not jnp.zeros) so the only fresh compile the sentinel
        # sees — and names — is chaos_retrace_probe itself
        jax.jit(chaos_retrace_probe)(jax.device_put(
            np.zeros((self.model_epoch % 7 + 1,), np.float32)))

    def _run_eval_share(self, evaluator, tracker: Dict[str, int]):
        """Advance online evaluation until its share of episodes reaches
        eval_rate. The host evaluator advances all its matches ONE ply per
        call while chunked generators deliver episodes in bursts, so it gets
        several plies per loop iteration or it never finishes a match; the
        device evaluator finishes whole batches per call and exits after one
        step once the share is met. ``tracker`` carries the previous
        dispatch's epoch for pipelined evaluators (their results arrive one
        dispatch late)."""
        pipelined = getattr(evaluator, 'pipelined', False)
        for _ in range(16):
            if self.num_results >= self.eval_rate * self.num_episodes:
                break
            cur = self.model_epoch
            results = evaluator.step()
            self.num_results += len(results)
            self.feed_results(
                results,
                model_id=tracker.get('prev', cur) if pipelined else cur)
            tracker['prev'] = cur

    # -- generation front-end A: in-process batched self-play -------------
    def _run_batched(self):
        """TPU-first local mode: vectorized self-play + interleaved eval in
        this process; no worker processes at all."""
        args = self.args
        actor = ModelWrapper(self.wrapper.module)
        # actor params live ON DEVICE, refreshed once per epoch — binding
        # the learner's numpy copy would re-upload the full parameter set
        # on every rollout/eval dispatch (ruinous through a WAN tunnel)
        actor.params = put_tree(self.wrapper.params)
        env_args = args['env']

        def make_env_fn(i):
            e = make_env({**env_args, 'id': i})
            return e

        env_mod = None
        chunk_steps = int(args.get('device_chunk_steps') or 16)
        if args.get('device_generation'):
            from .environment import make_jax_env
            env_mod = make_jax_env(env_args)
            if env_mod is None:
                _LOG.warning('no pure-JAX twin for %s; falling back to '
                             'host envs', env_args['env'])

        # device-ingest layout (when the env/config allows assembling
        # training windows on device, ops/device_windows.py). On a
        # multi-device mesh only the fused pipeline runs device ingest
        # (shard_map over 'data': per-shard envs + ring, gradient psum);
        # the generation_envs/batch_size must divide the device count.
        n_dev = len(self.trainer.mesh.devices.flat) \
            if self.trainer.mesh is not None else 1
        eval_envs = int(args.get('eval_envs')
                        or max(4, args.get('generation_envs', 64) // 8))
        # the shard_map'd fused pipeline is pure data parallelism: it
        # requires a 1-wide 'model' axis and replicate-everything partition
        # rules (tensor-parallel configs train through the jit paths, whose
        # in/out shardings come from the rule engine)
        from .parallel.partition import pure_data_parallel
        mesh_fused_ok = (
            self.trainer.mesh is None
            or (args.get('fused_pipeline', True)
                and args.get('generation_envs', 64) % n_dev == 0
                and args['batch_size'] % n_dev == 0
                and int(self.trainer.mesh.shape.get('model', 1)) == 1
                and pure_data_parallel(self.trainer.partition_rules)))
        if self.trainer.mesh is not None and mesh_fused_ok \
                and eval_envs % n_dev != 0:
            # eval_envs is only a throughput knob — round it up to the mesh
            # rather than silently disqualifying the sharded trainer
            from .parallel.mesh import pad_to_multiple
            eval_envs = pad_to_multiple(eval_envs, n_dev)
        ingest_mode = None
        if (env_mod is not None and args.get('device_replay')
                and args.get('device_ingest', True)
                and mesh_fused_ok):
            simultaneous = bool(getattr(env_mod, 'SIMULTANEOUS', False))
            if simultaneous and not args['turn_based_training']:
                ingest_mode = 'solo'
            elif not simultaneous and args['turn_based_training']:
                # observation=True is admitted too: every env records only
                # the acting seat per ply (``observers()`` defaults empty,
                # reference environment.py:84), so the compact 'turn'
                # window layout computes training math identical to the
                # wide (B,T,P) observation layout for per-sample models
                # (gradient-level proof: tests/test_turn_layout_parity.py);
                # with batch-statistics norms the compact layout's
                # statistics exclude the wide layout's zeroed non-acting
                # seat rows (window-tail pad rows still enter, as in the
                # reference's train-mode BatchNorm). The device loss runs
                # with observation=False to match the layout.
                ingest_mode = 'turn'

        # the loss config the DEVICE pipelines train with: identical to
        # the host trainer's except when 'turn' ingest serves an
        # observation=True config (see the gate comment above)
        tr = self.trainer
        tr.device_cfg = tr.cfg
        if ingest_mode == 'turn' and args['observation']:
            tr.device_cfg = tr.cfg._replace(observation=False)
            if tr.replay is not None:
                # the threaded replay trainer samples windower rows in the
                # compact layout too — rebuild its fused K-step program
                # with the matching cfg (nothing traced yet at this point)
                tr.replay_update = tr.build_replay_update(tr.device_cfg)

        opponents = args.get('eval', {}).get('opponent', []) or ['random']

        def device_eval_ok():
            """'random', checkpoint (feedforward OR recurrent — the
            evaluator plumbs an opponent hidden tree through the rollout
            scan), and (where the env twin vectorizes its agent as
            ``greedy_action``) 'rulebase' opponents run on device; other
            rulebases use the host evaluator."""
            if env_mod is None or not args.get('device_eval', True):
                return False
            if len(opponents) > eval_envs:   # every opponent needs an env
                return False
            for o in opponents:
                if o == 'random':
                    continue
                if o == 'rulebase' and hasattr(env_mod, 'greedy_action'):
                    continue   # vectorized rulebase runs on device
                if isinstance(o, str) and os.path.exists(o):
                    continue   # checkpoint league opponent
                return False
            return True

        if device_eval_ok():
            # eval matches ride the accelerator too: the host evaluator's
            # one-dispatch-per-ply cost dominates chunked device generation
            from .device_generation import DeviceEvaluator
            # shard eval envs only when the sharded fused trainer runs (its
            # replicated actor params are what the eval program binds)
            eval_mesh = (self.trainer.mesh
                         if (self.trainer.mesh is not None
                             and ingest_mode is not None) else None)
            evaluator = DeviceEvaluator(env_mod, actor, args,
                                        n_envs=eval_envs,
                                        chunk_steps=chunk_steps,
                                        mesh=eval_mesh,
                                        opponents=opponents)
        else:
            evaluator = BatchedEvaluator(make_env_fn, actor, args,
                                         n_envs=eval_envs)

        def build_windower(mode):
            from .ops.device_windows import DeviceWindower
            max_steps = int(getattr(env_mod, 'MAX_STEPS',
                                    getattr(env_mod, 'MAX_PLIES', 256)))
            windows_cap = (args.get('replay_windows_per_episode')
                           or max(1, 64 // args['forward_steps']))
            return DeviceWindower(
                mode=mode, fs=args['forward_steps'],
                bi=args['burn_in_steps'], max_steps=max_steps,
                windows_cap=windows_cap,
                # on a mesh each shard owns ring_capacity/n_dev rows; the
                # global ring keeps the configured total budget
                capacity=max(1, self.trainer.replay.capacity // n_dev),
                num_players=env_mod.NUM_PLAYERS, gamma=args['gamma'],
                has_reward=hasattr(env_mod, 'rewards'))

        if ingest_mode is not None and args.get('fused_pipeline', True):
            # the fully-fused loop: rollout + ingest + K SGD steps per
            # dispatch, driven single-threaded (ops/fused_pipeline.py)
            return self._run_fused(env_mod, actor, evaluator,
                                   build_windower(ingest_mode), ingest_mode)

        gen = None
        if env_mod is not None:
            from .device_generation import DeviceGenerator
            gen = DeviceGenerator(env_mod, actor, args,
                                  n_envs=args.get('generation_envs', 64),
                                  chunk_steps=chunk_steps)
            gen.step = gen.step_chunk   # same streaming surface
        if gen is None:
            gen = BatchedGenerator(make_env_fn, actor, args,
                                   n_envs=args.get('generation_envs', 64))

        # device ingest: trajectories never leave the accelerator — rollout
        # records flow straight into the windower's HBM ring; the host does
        # episode accounting from the (done, outcome) arrays only
        device_ingest = False
        if ingest_mode is not None:
            self.trainer.windower = build_windower(ingest_mode)
            device_ingest = True
            print('device ingest: windows assembled on device '
                  '(%s mode)' % ingest_mode)

        cadence = _EpochCadence(args)
        actor_epoch = self.model_epoch
        # pipelined generators return the PREVIOUS dispatch's chunk: stamp
        # episodes with the epoch captured when that chunk was dispatched
        chunk_epoch = self.model_epoch
        eval_tracker: Dict[str, int] = {}

        def stamp_and_feed(episodes, epoch):
            for ep in episodes:
                self.num_episodes += 1
                # in-process generators leave model_id unset (-1): stamp
                # the epoch whose params played the episode
                mid = ep['args'].setdefault('model_id', {})
                for p, v in list(mid.items()):
                    if v is None or v < 0:
                        mid[p] = epoch
            self.feed_episodes(episodes)

        while not self.shutdown_flag:
            if self._deadline and time.time() >= self._deadline:
                break                      # wall-clock budget spent mid-epoch
            if self.preempt.requested():
                _LOG.warning('preemption signal received; snapshotting '
                             'and exiting')
                break
            self._poll_rollback()
            if actor_epoch != self.model_epoch:   # follow latest epoch
                actor.params = put_tree(self.wrapper.params)
                actor_epoch = self.model_epoch
            dispatch_epoch = self.model_epoch
            if device_ingest:
                records, done, outcome = gen.step_chunk_records()
                self.feed_device_chunk(done, outcome, chunk_epoch)
                self.trainer.seen_episodes = self.num_returned_episodes
                # BLOCKING hand-off: the windower's per-env histories track
                # a contiguous ply stream, so dropping a chunk would splice
                # different episodes together — backpressure generation
                # instead (the trainer drains chunks even while it waits
                # for minimum_episodes)
                while not self.shutdown_flag and not self.preempt.requested():
                    try:
                        self.trainer.chunk_queue.put(records, timeout=1.0)
                        break
                    except queue.Full:
                        continue
            else:
                # pipelined generators return the PREVIOUS dispatch's
                # episodes (stamp with that dispatch's epoch); host-path
                # generators return episodes finished under current params
                stamp_and_feed(gen.step(),
                               chunk_epoch if getattr(gen, 'pipelined', False)
                               else dispatch_epoch)
            chunk_epoch = dispatch_epoch

            self._run_eval_share(evaluator, eval_tracker)

            if cadence.due(self.num_returned_episodes):
                self.update()
                if self._past_epoch_budget():
                    self.shutdown_flag = True

        # account the one speculative chunk still in the pipeline
        if hasattr(gen, 'drain_records') and device_ingest:
            tail = gen.drain_records()
            if tail is not None:
                _records, done, outcome = tail
                self.feed_device_chunk(done, outcome, chunk_epoch)
        elif hasattr(gen, 'drain_episodes'):
            stamp_and_feed(gen.drain_episodes(), chunk_epoch)
        if hasattr(evaluator, 'drain'):
            self.feed_results(evaluator.drain(),
                              model_id=eval_tracker.get('prev'))

    # -- generation front-end A': the fully-fused device loop --------------
    def _run_fused(self, env_mod, actor, evaluator, windower, mode):
        """Single-threaded steady state: ONE program dispatch per loop
        iteration runs rollout chunk + window ingest + K SGD steps
        (ops/fused_pipeline.py). The trainer thread stays parked — there is
        no queue competition on the device stream, and the only per-chunk
        host traffic is the previous chunk's (done, outcome) fetch.

        Sample reuse is explicit here: ``sgd_steps_per_chunk`` pins the
        replay ratio instead of letting the trainer thread spin as fast as
        dispatch latency allows."""
        args = self.args
        tr = self.trainer
        self._fused_active = True   # final_flush reads tr.state directly
        n_dev = len(tr.mesh.devices.flat) if tr.mesh is not None else 1
        print('fused device pipeline: rollout+ingest+train in one dispatch '
              '(%s mode%s)' % (mode, ', sharded over %d devices' % n_dev
                               if tr.mesh is not None else ''))
        from .ops.fused_pipeline import FusedPipeline
        if args.get('max_sample_reuse'):
            print('note: max_sample_reuse applies to the threaded replay '
                  'trainer; the fused pipeline pins reuse via '
                  'sgd_steps_per_chunk instead')
        sgd_steps = int(args.get('sgd_steps_per_chunk') or 16)   # doc: config.py
        tr.windower = windower   # ring occupancy reporting
        fp = FusedPipeline(
            env_mod, actor, tr.device_cfg, windower, args,
            n_envs=args.get('generation_envs', 64),
            chunk_steps=int(args.get('device_chunk_steps') or 16),
            sgd_steps=sgd_steps, batch_size=args['batch_size'],
            default_lr=tr.default_lr, seed=args.get('seed', 0),
            mesh=tr.mesh)

        cadence = _EpochCadence(args)
        actor_epoch = self.model_epoch
        pending_metrics: List[Any] = []
        epoch_steps = 0
        epoch_t0 = time.time()
        eval_tracker: Dict[str, int] = {}
        timing = os.environ.get('HANDYRL_TPU_TIMING') == '1'
        tacc = {'dispatch': 0.0, 'fetch': 0.0, 'eval': 0.0, 'epoch': 0.0,
                'iters': 0}
        # feed_device_chunk is one fetch behind dispatch; chunk -> epoch
        # attribution therefore uses the epoch captured at dispatch time
        epoch_of_dispatch = deque()
        # fused dispatch/fetch latency joins the same 'dispatch' /
        # 'host_block' stage histograms the threaded trainer's StageTimer
        # mirror feeds; epoch deltas feed the device-utilization proxy
        m_dispatch = telemetry.histogram('stage_seconds', stage='dispatch')
        m_block = telemetry.histogram('stage_seconds', stage='host_block')
        tlast = {'dispatch': 0.0, 'fetch': 0.0}

        def account(prev):
            if prev is None:
                return
            self.feed_device_chunk(prev['done'], prev['outcome'],
                                   epoch_of_dispatch.popleft())
            if prev['metrics'] is not None:
                pending_metrics.append(prev['metrics'])
                # guard: the 'nonfinite' skip count is already a host
                # float on the packed fetch — escalation costs no sync
                self._fused_guard_observe(prev['metrics'], fp)

        # actor/eval params refresh DEVICE-to-device from the train state:
        # no host round trip, and correct even on epochs where
        # checkpoint_interval skipped the host snapshot. A real copy (not an
        # alias) is required — the next fused dispatch donates tr.state.
        if tr.mesh is not None:
            # pin the replicated layout up front so dispatches never
            # re-broadcast device-0 arrays across the mesh
            from .parallel.mesh import replicated_sharding
            repl = replicated_sharding(tr.mesh)
            actor.params = jax.device_put(actor.params, repl)
            if tr.state is not None:
                tr.state = jax.device_put(tr.state, repl)
            copy_params = jax.jit(
                lambda p: jax.tree_util.tree_map(jnp.copy, p),
                out_shardings=repl)
        else:
            copy_params = jax.jit(
                lambda p: jax.tree_util.tree_map(jnp.copy, p))

        while not self.shutdown_flag:
            if self._deadline and time.time() >= self._deadline:
                break                      # wall-clock budget spent mid-epoch
            if self.preempt.requested():
                _LOG.warning('preemption signal received; snapshotting '
                             'and exiting')
                break
            if actor_epoch != self.model_epoch:
                actor.params = (copy_params(tr.state.params)
                                if tr.state is not None
                                else put_tree(self.wrapper.params))
                actor_epoch = self.model_epoch
            epoch_of_dispatch.append(self.model_epoch)
            # on a mesh, also hold warmup until EVERY shard's ring slice
            # has at least one window (a shard with local size 0 would feed
            # all-zero batches into the psum'd gradient); ring_min_host is
            # one fetch behind, which only extends warmup by one chunk
            warm = (self.num_returned_episodes < args['minimum_episodes']
                    or (tr.mesh is not None and fp.dispatches > 0
                        and fp.ring_min_host < 1))
            t0 = time.time()
            if warm:
                account(fp.warm_step(actor.params))
                dt_fetch = time.time() - t0
                tacc['fetch'] += dt_fetch
                m_block.observe(dt_fetch)
            else:
                ema = tr.data_cnt_ema
                if tr.chaos_nan.due(tr.steps, fp.sgd_steps):
                    _LOG.warning('chaos: injecting non-finite update at '
                                 'step %d', tr.steps)
                    ema = float('nan')   # poisons the on-device lr schedule
                tr.state, prev = fp.train_step(actor.params, tr.state, ema)
                t1 = time.time()
                tacc['dispatch'] += t1 - t0
                m_dispatch.observe(t1 - t0)
                tr.steps += fp.sgd_steps
                epoch_steps += fp.sgd_steps
                account(prev)
                dt_fetch = time.time() - t1
                tacc['fetch'] += dt_fetch
                m_block.observe(dt_fetch)
            tacc['iters'] += 1

            t2 = time.time()
            self._run_eval_share(evaluator, eval_tracker)
            tacc['eval'] += time.time() - t2

            if cadence.due(self.num_returned_episodes):
                t3 = time.time()
                self._fused_epoch(pending_metrics, epoch_steps,
                                  time.time() - epoch_t0, fp, evaluator)
                tacc['epoch'] += time.time() - t3
                # device-utilization proxy from this epoch's dispatch/fetch
                # deltas: the fused loop's 'host_block' is the packed fetch
                util = telemetry.utilization_from_stages(
                    {'dispatch': tacc['dispatch'] - tlast['dispatch'],
                     'host_block': tacc['fetch'] - tlast['fetch']})
                telemetry.set_utilization_proxy(util)
                tlast.update(dispatch=tacc['dispatch'], fetch=tacc['fetch'])
                if timing:
                    line = {k: round(v, 2) for k, v in tacc.items()}
                    if util is not None:
                        line['util'] = round(util, 4)
                    print('timing: %s' % json.dumps(line))
                pending_metrics.clear()   # account() closes over this list
                epoch_steps = 0
                epoch_t0 = time.time()
                if self._past_epoch_budget():
                    self.shutdown_flag = True
        account(fp.drain())
        if hasattr(evaluator, 'drain'):
            self.feed_results(evaluator.drain(),
                              model_id=eval_tracker.get('prev'))
        # checkpoint_interval may have skipped the last epoch's file write,
        # and a preemption lands mid-epoch: one shared idempotent flush
        # covers both (it also writes the preempt snapshot, so a SIGTERM
        # during the final epoch cannot write trainer_state twice)
        self.final_flush()

    def _fused_epoch(self, pending_metrics, epoch_steps, epoch_wall,
                     fp, evaluator):
        """Epoch boundary for the fused loop: drain metric futures, print
        the reference-format lines, update the lr EMA, checkpoint."""
        tr = self.trainer
        print()
        print('epoch %d' % self.model_epoch)
        self._print_eval_stats()
        self._print_generation_stats()

        data_cnt = 0
        loss_sum: Dict[str, float] = {}
        diag_sum: Dict[str, float] = {}
        for metrics in pending_metrics:   # host floats — no device fetch
            for k, v in metrics.items():
                if k == 'data_count':
                    data_cnt += int(v)
                elif k == 'nonfinite':
                    continue   # guard counter, observed per chunk
                elif k.startswith('diag_'):
                    diag_sum[k] = diag_sum.get(k, 0.0) + float(v)
                else:
                    loss_sum[k] = loss_sum.get(k, 0.0) + float(v)
        if epoch_steps > 0:
            print('loss = %s' % ' '.join(
                [k + ':' + '%.3f' % (l / max(data_cnt, 1))
                 for k, l in sorted(loss_sum.items())]))
            tr.data_cnt_ema = (tr.data_cnt_ema * 0.8
                               + data_cnt / (1e-2 + epoch_steps) * 0.2)
            tr.last_steps_per_sec = epoch_steps / max(epoch_wall, 1e-9)
            tr._diag_sum = diag_sum
            tr.last_dynamics = tr._epoch_dynamics(loss_sum, data_cnt,
                                                  epoch_steps)
        if tr.replay is not None:
            tr.replay_stats['samples_drawn'] += (
                epoch_steps * self.args['batch_size'])
            # ring size + true cumulative ingest count ride the per-chunk
            # packed fetch — no device sync (ring size saturates at
            # capacity once the ring wraps; the ingest counter does not)
            tr._ring_size_host = fp.ring_size_host
            tr.replay_stats['windows_ingested'] = max(
                tr.replay_stats['windows_ingested'],
                fp.windows_ingested_host)

        # Fetching + serializing the full train state dominates short
        # epochs on a tunneled device (~40% of a 100k-episode geese run):
        # with checkpoint_interval > 1, intermediate epochs skip the host
        # round trip entirely — the actor/eval params refresh device-to-
        # device in the fused loop, so nothing here needs host bytes.
        interval = int(self.args.get('checkpoint_interval') or 1)
        final = 0 <= self.args['epochs'] <= self.model_epoch + 1
        if interval <= 1 or (self.model_epoch + 1) % interval == 0 or final:
            # ONE packed transfer for params + optimizer state (per-leaf
            # np.asarray costs a tunnel round trip per leaf)
            from .utils.fetch import fetch_tree
            host_state = fetch_tree(tr.state)
            self.update_model(host_state.params, tr.steps,
                              tr.state_bytes(host_state))
        else:
            self.update_model(None, tr.steps, write_files=False)
        rec_extra = {'dispatches_gen': fp.dispatches,
                     'dispatches_eval': getattr(evaluator, 'dispatches', 0)}
        self._write_metrics(tr.steps, rec_extra)
        self._maybe_profile()
        self.flags = set()

    def _print_eval_stats(self):
        if self.model_epoch not in self.results:
            print('win rate = Nan (0)')
            return

        def output_wp(name, results):
            n, r, r2 = results
            mean = r / (n + 1e-6)
            name_tag = ' (%s)' % name if name != '' else ''
            print('win rate%s = %.3f (%.1f / %d)'
                  % (name_tag, (mean + 1) / 2, (r + n) / 2, n))

        keys = self.results_per_opponent[self.model_epoch]
        if (len(self.args.get('eval', {}).get('opponent', [])) <= 1
                and len(keys) <= 1):
            output_wp('', self.results[self.model_epoch])
        else:
            output_wp('total', self.results[self.model_epoch])
            for key in sorted(keys):
                output_wp(key, keys[key])

    def _print_generation_stats(self):
        if self.model_epoch not in self.generation_results:
            print('generation stats = Nan (0)')
            return
        n, r, r2 = self.generation_results[self.model_epoch]
        mean = r / (n + 1e-6)
        std = (r2 / (n + 1e-6) - mean ** 2) ** 0.5
        print('generation stats = %.3f +- %.3f' % (mean, std))

    # -- generation front-end B: RPC server over workers ------------------
    def server(self):
        """4-RPC conductor: args / episode / result / model
        (reference train.py:541-627; 'model' answers with an architecture
        name + msgpack params snapshot, never pickled code).

        Every assigned task is booked in a :class:`TaskLedger` with a
        deadline; tasks stranded on a detached endpoint (the Hub's
        heartbeat/liveness machinery journals those) or past their deadline
        are re-issued ahead of fresh assignments, WITHOUT re-incrementing
        ``num_episodes``/``num_results`` — so episode accounting converges
        and budgeted runs cannot hang waiting for episodes a dead host will
        never deliver. Duplicate uploads (a gather resending an un-acked
        RPC after reconnect) are dropped by the same book.

        On top of the ledger sits ELASTIC FLEET CONTROL
        (:class:`~.fault.FleetController`): every peer endpoint maps to a
        host key (socket peers by address — gathers on one machine share
        one health record across reconnects; pipe peers individually), and
        each host carries a health state (healthy / degraded / draining /
        quarantined) fed by ledger strandings and by the engine-failover /
        engine-restart counters riding heartbeat telemetry. Flapping hosts
        stop receiving fresh tasks — they get 'idle' placeholders while
        their booked work drains — sit out a quarantine, and are
        re-admitted. State transitions are exported as per-host
        ``fleet_host_state`` gauges, a transitions counter, the per-epoch
        ``fleet:`` line, and ``fleet_host_states`` in metrics_jsonl."""
        _LOG.info('started server')
        cadence = _EpochCadence(self.args)
        ft = self.args.get('fault_tolerance') or {}
        ledger = self.ledger = TaskLedger(
            deadline=float(ft.get('task_deadline', 300.0)))
        if self._restored_ledger is not None:
            # previous incarnation's in-flight book: restored tasks
            # re-issue with their original sample_keys ahead of fresh work
            ledger.restore_state(self._restored_ledger)
            self._restored_ledger = None
        if self._recovered_closed_chunks:
            # streamed assemblies spool recovery reassembled and counted:
            # close their keys so a reattached gather's resend replays
            # screen as duplicates instead of re-building the episode
            ledger.seed_closed_chunks(self._recovered_closed_chunks)
            self._recovered_closed_chunks = []
        if self._ledger_journal is not None:
            ledger.journal = self._ledger_journal
        if self._durable_restored:
            # restored counters already crossed earlier epoch thresholds —
            # the dead incarnation consumed them (its checkpoints exist);
            # drain the cadence so they are not re-fired as empty epochs
            while cadence.due(self.num_returned_episodes):
                pass
        fleet = self.fleet = FleetController(
            degrade_after=int(ft.get('host_degrade_after', 1)),
            quarantine_after=int(ft.get('host_quarantine_after', 3)),
            health_window=float(ft.get('host_health_window', 120.0)),
            quarantine_period=float(ft.get('host_quarantine_period', 60.0)))
        host_of: Dict[Any, str] = {}       # endpoint -> host key
        fault_seen: Dict[Any, float] = {}  # endpoint -> fault counter mark
        m_withheld = telemetry.counter('fleet_tasks_withheld_total')

        def host_key(ep) -> str:
            """Stable host identity for an endpoint: socket peers key by
            address (a respawned/reconnected gather from the same machine
            keeps its health history), pipe peers individually."""
            key = host_of.get(ep)
            if key is None:
                try:
                    sock = getattr(ep, 'sock', None)
                    # a closed FramedConnection still has the attribute
                    # with sock=None — that's a dead socket peer, not a pipe
                    if sock is None and hasattr(ep, 'sock'):
                        raise OSError('socket already closed')
                    key = ('host-%s' % sock.getpeername()[0]
                           if sock is not None
                           else 'local-%d' % ep.fileno())
                except (OSError, AttributeError):
                    key = 'host-unknown'
                host_of[ep] = key
                if fleet.observe(key):
                    telemetry.gauge('fleet_host_state', host=key).set(
                        telemetry.HOST_STATE_CODES[fleet.state(key)])
            return key

        def pump_fleet_health():
            """Feed the controller and mirror its transitions to metrics:
            strandings from the ledger, soft faults (engine restarts and
            worker failovers) from heartbeat telemetry deltas, then the
            time/drain-driven transitions."""
            for ep, _reason, _t in ledger.drain_stranding_events():
                host = host_of.get(ep)
                if host is not None:
                    fleet.record_stranding(host)
            for ep, info in self.worker.peer_info().items():
                if not isinstance(info, dict) or ep not in host_of:
                    continue
                counters = (info.get('telemetry') or {}).get('counters') or {}
                cur = sum(v for k, v in counters.items()
                          if k.startswith(('engine_restarts_total',
                                           'worker_engine_failovers_total')))
                prev = fault_seen.get(ep, 0)
                if cur > prev:   # < prev = the peer process restarted
                    fleet.record_soft_fault(host_of[ep], cur - prev)
                fault_seen[ep] = cur
            outstanding: Dict[str, int] = {}
            for ep, n in ledger.outstanding_by_endpoint().items():
                host = host_of.get(ep)
                if host is not None:
                    outstanding[host] = outstanding.get(host, 0) + n
            fleet.tick(outstanding)
            for host, prev, state, _t in fleet.drain_transitions():
                _LOG.warning('fleet: host %s %s -> %s', host, prev, state)
                telemetry.gauge('fleet_host_state', host=host).set(
                    telemetry.HOST_STATE_CODES[state])
                telemetry.counter('fleet_host_transitions_total',
                                  **{'from': prev, 'to': state}).inc()
            if self._alerts is not None:
                # the cadence gate makes this an ~interval-spaced stream
                # even though the loop spins every recv timeout
                self._alerts.maybe_evaluate(self._telemetry_snapshots)

        while self.worker.connection_count() > 0 or not self.shutdown_flag:
            if self.preempt.requested():
                # preemption: don't wait for the fleet to wind down — the
                # snapshot happens in run()'s flush, gathers redial the
                # restarted learner on their own (PR 2 supervision)
                _LOG.warning('preemption signal received; snapshotting '
                             'and exiting')
                self.shutdown_flag = True
                break
            self._poll_rollback()
            # fleet supervision runs even when no RPC arrives: stranded
            # tasks must re-enter the queue or the epoch cadence starves
            detached = []
            for ep, reason, _t in self.worker.drain_detach_events():
                lost = ledger.fail_endpoint(ep)
                detached.append(ep)
                if lost:
                    _LOG.warning('re-issuing %d task(s) from detached '
                                 'peer (%s)', lost, reason)
            ledger.reap()
            pump_fleet_health()
            for ep in detached:       # after the stranding drain mapped them
                host_of.pop(ep, None)
                fault_seen.pop(ep, None)
            try:
                conn, (req, data) = self.worker.recv(timeout=0.3)
            except queue.Empty:
                continue

            multi_req = isinstance(data, list)
            if not multi_req:
                data = [data]
            send_data = []

            if req == 'args':
                if self.shutdown_flag:
                    send_data = [None] * len(data)
                elif not fleet.admits(host_key(conn)):
                    # drain-before-detach: a draining/quarantined host gets
                    # placeholder tasks — unbooked and uncounted — so its
                    # workers stay warm for re-admission while its in-
                    # flight work either lands or strands on the ledger
                    fleet.stats['withheld'] += len(data)
                    m_withheld.inc(len(data))
                    send_data = [{'role': 'idle', 'wait': 1.0}
                                 for _ in data]
                else:
                    for _ in data:
                        role_args = ledger.next_reissue()
                        if role_args is None:
                            role_args = {'model_id': {}}
                            if self.num_results < self.eval_rate * self.num_episodes:
                                role_args['role'] = 'e'
                            else:
                                role_args['role'] = 'g'

                            if role_args['role'] == 'g':
                                players = self.env.players()
                                role_args['player'] = players
                                for p in players:
                                    role_args['model_id'][p] = self.model_epoch
                                # league (league.py): the PFSP share seats
                                # a pool member on every non-learner seat;
                                # the learner seat rotates so first-mover
                                # advantage cancels over the stream. The
                                # stamped league_opponent/league_seat ride
                                # the ledger's booked role_args, so a
                                # re-issue keeps the exact assignment.
                                drawn = self._league_gen_opponent(
                                    self.num_episodes)
                                if drawn is not None:
                                    member, mid = drawn
                                    seat = players[
                                        self.num_episodes % len(players)]
                                    for p in players:
                                        if p != seat:
                                            role_args['model_id'][p] = mid
                                    role_args['league_opponent'] = member
                                    role_args['league_seat'] = seat
                                # the action-sampling key: with it, the
                                # episode is a pure function of (seed,
                                # sample_key, params) — identical on the
                                # per-worker and engine inference paths,
                                # on whichever worker the task (or its
                                # ledger re-issue) lands
                                role_args['sample_key'] = self.num_episodes
                                self.num_episodes += 1
                            else:
                                players = self.env.players()
                                role_args['player'] = [
                                    players[self.num_results % len(players)]]
                                for p in players:
                                    role_args['model_id'][p] = (
                                        self.model_epoch if p in role_args['player']
                                        else -1)
                                # league rating matches: a deterministic
                                # slice of 'e' tasks pins its opponent to a
                                # round-robin roster member (the worker's
                                # Evaluator honors the stamped override);
                                # registry members ride as model_id seats,
                                # anchors resolve worker-side by name
                                member = self._league_rating_opponent(
                                    self.num_results)
                                if member is not None:
                                    role_args['opponent'] = member
                                    role_args['league_rating_match'] = True
                                    mid = self._league.member_model_id(member)
                                    if mid is not None and mid > 0:
                                        for p in players:
                                            if p not in role_args['player']:
                                                role_args['model_id'][p] = mid
                                role_args['sample_key'] = self.num_results
                                self.num_results += 1
                        ledger.assign(conn, role_args)
                        send_data.append(role_args)

            elif req == 'episode':
                self.feed_episodes(ledger.admit(data))
                # completions flush AFTER the spool append above: an
                # admitted-but-unflushed kill window recovers from the
                # spool (whose task_ids cancel the restored book entries)
                ledger.flush_journal()
                send_data = [None] * len(data)

            elif req == 'chunk':
                # streamed in-flight windows (streaming.py): screened per
                # (assembly, chunk index), WAL'd, merged — same flush-after-
                # spool ordering as whole episodes, extended to partials
                self.feed_chunks(ledger.admit_chunks(data))
                ledger.flush_journal()
                send_data = [None] * len(data)

            elif req == 'result':
                self.feed_results(ledger.admit(data))
                ledger.flush_journal()
                send_data = [None] * len(data)

            elif req == RESUME_KIND:
                # resume-token handshake: a surviving gather redialed a
                # restarted learner. run_id match => reattach in place
                # (its resend buffer replays as ordinary duplicate-screened
                # uploads); mismatch => the gather cold-respawns, exactly
                # today's behavior for a genuinely different run
                for tok in data:
                    tok = tok if isinstance(tok, dict) else {}
                    ok = (str(tok.get('run_id'))
                          == str(self.args.get('run_id')))
                    if ok and int(tok.get('generation', -1)) \
                            != self._run_generation:
                        telemetry.counter('gather_reattach_total').inc()
                        _LOG.info(
                            'gather %s reattached across a learner restart '
                            '(generation %s -> %d)', tok.get('gather'),
                            tok.get('generation'), self._run_generation)
                    send_data.append(
                        {'ok': ok, 'run_id': str(self.args.get('run_id')),
                         'generation': self._run_generation})

            elif req == 'model':
                for model_id in data:
                    snap = None
                    if model_id == self.model_epoch or model_id <= 0:
                        snap = self.wrapper.snapshot()
                    else:
                        try:
                            from .model import module_config
                            from . import models as model_zoo
                            with open(self.model_path(model_id), 'rb') as f:
                                snap = {'architecture': model_zoo
                                        .architecture_name(self.wrapper.module),
                                        'params': f.read()}
                            # non-default module config (e.g. GeisterNet
                            # norm_kind='batch') must ride along or the
                            # worker rebuilds the registry default, whose
                            # param tree rejects these bytes
                            config = module_config(self.wrapper.module)
                            if config:
                                snap['config'] = config
                        except OSError:
                            # league members can outlive model_dir (GC'd
                            # numbered ckpt, registry-owned bytes): resolve
                            # the version through the registry manifest
                            # before falling back to the live snapshot
                            snap = (self._league_model_snapshot(model_id)
                                    or self.wrapper.snapshot())
                    send_data.append(snap)

            if not multi_req and len(send_data) == 1:
                send_data = send_data[0]
            self.worker.send(conn, send_data)

            if cadence.due(self.num_returned_episodes):
                # abandon streamed assemblies no attempt can ever finish
                # (e.g. a dead device-actor stream, whose re-issue keys a
                # new task_id) so they stop pinning the spool GC horizon
                for key in self._assembler.reap(2 * ledger.deadline):
                    ledger.abandon_chunks(key)
                self.update()
                self._print_fleet_stats()
                if self._past_epoch_budget():
                    self.shutdown_flag = True
        _LOG.info('finished server')

    def _fleet_snapshot(self) -> Dict[str, Any]:
        """Aggregate fleet health: server-side ledger + hub counters plus
        the per-gather stats that ride in on heartbeat payloads."""
        led = self.ledger.stats
        hub = self.worker.hub_stats()
        peers = self.worker.peer_info().values()
        snap = {
            'live': self.worker.connection_count(),
            'outstanding': self.ledger.outstanding(),
            'pending_reissue': self.ledger.pending_reissue(),
            'reissued': led['reissued'],
            'expired': led['expired'],
            'duplicates_dropped': led['duplicates'],
            'detached': hub.get('detached', 0),
            'reconnects': sum(int((p or {}).get('reconnects', 0))
                              for p in peers),
            'dropped_uploads': sum(int((p or {}).get('dropped_uploads', 0))
                                   for p in peers),
        }
        reasons = {k[len('disconnect_'):]: v for k, v in hub.items()
                   if k.startswith('disconnect_')}
        if reasons:
            snap['disconnects'] = reasons
        if getattr(self, 'fleet', None) is not None:
            counts = self.fleet.counts()
            snap['hosts'] = sum(counts.values())
            snap['hosts_degraded'] = counts['degraded']
            snap['hosts_draining'] = counts['draining']
            snap['hosts_quarantined'] = counts['quarantined']
            snap['withheld'] = self.fleet.stats['withheld']
            snap['readmitted'] = self.fleet.stats['readmitted']
            # full per-host map: metrics_jsonl only (popped from the
            # printed line, which carries the counts above)
            snap['host_states'] = self.fleet.snapshot()
        return snap

    def _print_fleet_stats(self):
        if getattr(self, 'ledger', None) is None:
            return
        snap = self._fleet_snapshot()
        # learner-side guard health rides the same per-epoch line
        snap['guard_nonfinite'] = self.trainer.guard.total_bad
        snap['guard_rollbacks'] = self.trainer.guard.rollbacks
        snap['guard_bad_episodes'] = self._bad_episodes
        snap.pop('host_states', None)
        reasons = snap.pop('disconnects', {})
        line = ' '.join('%s=%s' % kv for kv in snap.items())
        if reasons:
            line += ' (%s)' % ', '.join(
                '%s=%d' % kv for kv in sorted(reasons.items()))
        print('fleet: ' + line)

    def shutdown(self):
        """Stop the trainer loop and join its thread so no daemon thread is
        left inside XLA at interpreter exit (which aborts the process). The
        join must outlast one full update step — slow recurrent models can
        take seconds per step on CPU, and an unjoined thread inside XLA
        compute at teardown aborts with 'exception not rethrown'."""
        self.shutdown_flag = True
        # the steady-state flag is process-global: an in-process learner
        # (tests, notebooks) must not leave the retrace sentinel armed for
        # whatever jits next in this process
        telemetry.clear_steady_state()
        if self._spool is not None:
            self._spool.close()
        if self._ledger_journal is not None:
            self._ledger_journal.close()
        self.trainer.shutdown()
        if self._trainer_thread is not None:
            self._trainer_thread.join(timeout=300)
            if self._trainer_thread.is_alive():
                _LOG.warning('trainer thread still running at shutdown')
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None
        # collate this run's trace JSONL into the Chrome/Perfetto JSON (a
        # no-op with tracing off); the JSONL remains the source of truth
        try:
            out = telemetry.finalize_trace()
            if out:
                _LOG.info('episode trace collated to %s', out)
        except Exception as exc:
            _LOG.warning('trace finalize failed (%s: %s)',
                         type(exc).__name__, str(exc)[:120])
        self.preempt.uninstall()

    def run(self):
        # SIGTERM/SIGINT → cooperative snapshot-and-exit (safe points only);
        # chaos 'preempt=<s>' arms a self-SIGTERM for the e2e tests
        self.preempt.install()
        guard_mod.arm_chaos_preempt(self._chaos)
        self._trainer_thread = threading.Thread(target=self.trainer.run,
                                                name='trainer', daemon=True)
        self._trainer_thread.start()
        self._maybe_profile()   # profile_epochs may name the first epoch
        try:
            if self.use_batched_generation:
                self._run_batched()
            else:
                self.worker.run()
                self.server()
        finally:
            if self.preempt.fired:
                # flush the full checkpoint BEFORE tearing children down:
                # the supervisor restart must find TrainState + trainer
                # accounting exactly as of the last safe point
                try:
                    self.final_flush()
                    self._write_preempt_record()
                except Exception:
                    import traceback
                    traceback.print_exc()
            self.shutdown()


def _init_multihost(args):
    """Activate jax.distributed when configured (train_args['distributed']
    dict or JAX_COORDINATOR_ADDRESS-style env vars); no-op on single host.

    Must run before any other JAX use so jax.devices() sees the global
    device set; parallel/mesh.py then spans hosts transparently (gradient
    all-reduce on ICI within a slice, DCN across slices)."""
    from .parallel import multihost
    dist = (args.get('train_args') or {}).get('distributed') or {}
    active = multihost.initialize(
        coordinator_address=dist.get('coordinator_address'),
        num_processes=dist.get('num_processes'),
        process_id=dist.get('process_id'))
    if active:
        import jax
        print('multi-host: process %d of %d, %d global devices'
              % (jax.process_index(), jax.process_count(),
                 jax.device_count()))
    return active


def train_main(args):
    _init_multihost(args)
    prepare_env(args['env_args'])
    learner = Learner(args=args)
    learner.run()
    if learner.preempt.fired:
        # supervisor contract: EX_TEMPFAIL asks for a restart into the
        # resume path (restart_epoch: -1 auto-resolves the snapshot)
        raise SystemExit(guard_mod.PREEMPT_EXIT_CODE)
    _exit_if_train_failed(learner)


def _exit_if_train_failed(learner):
    """A dead optimizer (train-thread exception, e.g. a RetraceError under
    HANDYRL_TPU_RETRACE=abort) shuts the run down gracefully — but the
    PROCESS must still exit nonzero or CI reads the failure as a pass."""
    if getattr(learner.trainer, 'failed', False):
        raise SystemExit('training failed: %s'
                         % (learner.trainer.failed_reason or 'see traceback'))


def train_server_main(args):
    _init_multihost(args)
    learner = Learner(args=args, remote=True)
    learner.run()
    if learner.preempt.fired:
        raise SystemExit(guard_mod.PREEMPT_EXIT_CODE)
    _exit_if_train_failed(learner)
