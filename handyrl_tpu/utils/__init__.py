from .tree import map_structure, stack_structure, batch_structure, unbatch_structure, softmax
