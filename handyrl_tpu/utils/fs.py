"""Crash-safe file writes for checkpoints.

``latest.ckpt`` / ``trainer_state.ckpt`` are exactly the files a resumed
run loads, so an in-place ``open(path, 'wb')`` is the worst possible place
to die: a crash mid-write leaves a truncated file that poisons the next
start. Writes go to a temp file in the SAME directory (os.replace must not
cross filesystems), are fsynced, then atomically renamed over the target —
a reader sees either the old bytes or the new bytes, never a prefix.
"""

from __future__ import annotations

import json
import os
import struct
import tempfile
import time
import zlib


def atomic_write_bytes(path: str, data: bytes):
    """Write ``data`` to ``path`` atomically (temp file + fsync + rename)."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + '.tmp.',
                               dir=directory)
    try:
        with os.fdopen(fd, 'wb') as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # crash/interrupt before publish: the target is untouched; don't
        # litter the checkpoint dir with partial temp files
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def sidecar_path(path: str) -> str:
    """The checksum manifest that rides next to a checkpoint file."""
    return path + '.crc'


def checksummed_write_bytes(path: str, data: bytes):
    """Atomic write plus a CRC32 sidecar manifest (``<path>.crc``).

    The manifest is a one-line JSON dict: ``{"algo": "crc32", "crc32": N,
    "size": N, "time": T}``. The data file lands BEFORE the manifest: a
    crash between the two publishes leaves a stale manifest that FAILS
    verification, so resume conservatively falls back to an older epoch —
    it never trusts a half-published pair.
    """
    atomic_write_bytes(path, data)
    manifest = {'algo': 'crc32', 'crc32': zlib.crc32(data) & 0xffffffff,
                'size': len(data), 'time': time.time()}
    atomic_write_bytes(sidecar_path(path),
                       (json.dumps(manifest) + '\n').encode('utf-8'))


def _verify(path: str):
    """(ok, reason, data-or-None). A missing sidecar reads as ok with
    reason 'unverified' — checkpoints written before the manifest era (or
    by external tools) stay loadable."""
    try:
        with open(path, 'rb') as f:
            data = f.read()
    except OSError as exc:
        return False, 'unreadable (%s)' % exc, None
    try:
        with open(sidecar_path(path), 'r') as f:
            manifest = json.load(f)
    except OSError:
        return True, 'unverified', data
    except ValueError:
        return False, 'manifest unparsable', None
    if int(manifest.get('size', -1)) != len(data):
        return False, 'size mismatch (truncated write?)', None
    if int(manifest.get('crc32', -1)) != (zlib.crc32(data) & 0xffffffff):
        return False, 'crc32 mismatch (corrupt bytes)', None
    return True, 'ok', data


def verify_checkpoint(path: str):
    """(ok, reason) for ``path`` against its CRC32 sidecar manifest."""
    ok, reason, _data = _verify(path)
    return ok, reason


def read_verified_bytes(path: str):
    """The file's bytes, or None when it is missing, truncated, or fails
    the sidecar checksum (legacy files without a sidecar pass)."""
    ok, _reason, data = _verify(path)
    return data if ok else None


def layout_path(path: str) -> str:
    """The mesh-layout manifest that rides next to a checkpoint file
    (on top of the CRC sidecar): one JSON dict describing the mesh shape,
    device/process counts, and partition rules the checkpoint was written
    under (parallel/partition.py checkpoint_layout)."""
    return path + '.layout'


def write_layout_manifest(path: str, layout: dict):
    """Atomically publish ``path``'s layout manifest. Written AFTER the
    data + CRC pair so a crash can only leave a stale manifest — which
    reads as unparsable-or-missing, never as a wrong-but-plausible one."""
    atomic_write_bytes(layout_path(path),
                       (json.dumps(layout) + '\n').encode('utf-8'))


def read_layout_manifest(path: str):
    """(layout-dict-or-None, reason) for ``path``'s mesh-layout manifest.

    reason is 'ok', 'missing' (legacy checkpoint — loadable, layout
    unknown), or 'unparsable' (a PRESENT but corrupt manifest: the
    checkpoint pair cannot be trusted; resume falls back through the
    newest-valid path exactly like a CRC failure).
    """
    try:
        with open(layout_path(path), 'r') as f:
            layout = json.load(f)
    except OSError:
        return None, 'missing'
    except ValueError:
        return None, 'unparsable'
    if not isinstance(layout, dict) or 'format' not in layout:
        return None, 'unparsable'
    return layout, 'ok'


def rotate_file(path: str, max_mb: float) -> bool:
    """Size-gated single-generation rotation: when ``path`` exceeds
    ``max_mb`` megabytes it is atomically renamed to ``<path>.1``
    (replacing any previous generation) and True is returned — the next
    append recreates the live file. os.replace on the same filesystem is
    atomic, so a concurrent reader sees the old file or the rotated one,
    never a truncation in progress. Missing file / non-positive cap is a
    no-op."""
    if not path or max_mb <= 0:
        return False
    try:
        size = os.path.getsize(path)
    except OSError:
        return False
    if size < max_mb * 1024 * 1024:
        return False
    os.replace(path, path + '.1')
    return True


def append_jsonl(path: str, record: dict, fsync: bool = True):
    """Append ``record`` to a JSONL file append-safely.

    The whole encoded line (payload + newline) goes down in ONE
    ``os.write`` on an ``O_APPEND`` descriptor and (by default) is fsynced
    before the descriptor closes — so a learner killed mid-epoch leaves
    either the complete line or no line, never a torn half-line that
    breaks every downstream JSONL parse of the metrics file.
    ``fsync=False`` keeps the single-write torn-line guarantee against a
    process SIGKILL but skips the disk barrier — right for hot-path
    journals (the ledger delta journal) whose machine-crash story is
    already covered by the epoch snapshot."""
    line = (json.dumps(record) + '\n').encode('utf-8')
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line)
        if fsync:
            os.fsync(fd)
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# CRC-framed binary records (the episode-spool WAL vocabulary)
#
# One record = MAGIC(4) + length(4, big-endian) + crc32(4, big-endian) +
# payload. The frame is deliberately chunk-shaped: the same framing serves
# any future streaming-ingest journal (a trajectory chunk is just a
# payload). Appends go down in ONE os.write on an O_APPEND descriptor, so
# a SIGKILL leaves at worst one torn record at the tail — which
# read_framed_records detects (bad magic / short header / short payload /
# crc mismatch) and reports so recovery can truncate it cleanly.

RECORD_MAGIC = b'HRLW'
_RECORD_HEADER = struct.Struct('>II')   # payload length, crc32


def frame_record(payload: bytes) -> bytes:
    """One self-verifying framed record for ``payload``."""
    return (RECORD_MAGIC
            + _RECORD_HEADER.pack(len(payload),
                                  zlib.crc32(payload) & 0xffffffff)
            + payload)


def open_append(path: str) -> int:
    """An O_APPEND descriptor for a record file (create if missing)."""
    return os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)


def append_framed_record(fd: int, payload: bytes) -> int:
    """Append one framed record in a single write; returns bytes written."""
    frame = frame_record(payload)
    os.write(fd, frame)
    return len(frame)


def read_framed_records(path: str):
    """Decode a framed-record file tolerantly: ``(records, valid_bytes,
    torn)`` where ``records`` is the list of verified payloads,
    ``valid_bytes`` is the offset of the first byte past the last GOOD
    record, and ``torn`` is True when trailing bytes past that offset
    failed framing/CRC (a SIGKILL mid-append) — the caller truncates the
    file to ``valid_bytes`` to restore a clean tail."""
    try:
        with open(path, 'rb') as f:
            data = f.read()
    except OSError:
        return [], 0, False
    records, offset, frame_len = [], 0, len(RECORD_MAGIC) + _RECORD_HEADER.size
    while offset + frame_len <= len(data):
        if data[offset:offset + len(RECORD_MAGIC)] != RECORD_MAGIC:
            break
        size, crc = _RECORD_HEADER.unpack_from(data, offset + len(RECORD_MAGIC))
        start = offset + frame_len
        payload = data[start:start + size]
        if len(payload) < size or (zlib.crc32(payload) & 0xffffffff) != crc:
            break
        records.append(payload)
        offset = start + size
    return records, offset, offset < len(data)
