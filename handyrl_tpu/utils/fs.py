"""Crash-safe file writes for checkpoints.

``latest.ckpt`` / ``trainer_state.ckpt`` are exactly the files a resumed
run loads, so an in-place ``open(path, 'wb')`` is the worst possible place
to die: a crash mid-write leaves a truncated file that poisons the next
start. Writes go to a temp file in the SAME directory (os.replace must not
cross filesystems), are fsynced, then atomically renamed over the target —
a reader sees either the old bytes or the new bytes, never a prefix.
"""

from __future__ import annotations

import json
import os
import tempfile


def atomic_write_bytes(path: str, data: bytes):
    """Write ``data`` to ``path`` atomically (temp file + fsync + rename)."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + '.tmp.',
                               dir=directory)
    try:
        with os.fdopen(fd, 'wb') as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # crash/interrupt before publish: the target is untouched; don't
        # litter the checkpoint dir with partial temp files
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def append_jsonl(path: str, record: dict):
    """Append ``record`` to a JSONL file append-safely.

    The whole encoded line (payload + newline) goes down in ONE
    ``os.write`` on an ``O_APPEND`` descriptor and is fsynced before the
    descriptor closes — so a learner killed mid-epoch leaves either the
    complete line or no line, never a torn half-line that breaks every
    downstream JSONL parse of the metrics file."""
    line = (json.dumps(record) + '\n').encode('utf-8')
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line)
        os.fsync(fd)
    finally:
        os.close(fd)
