"""Per-stage wall-clock accounting for the host ingest path.

The distributed learner path crosses several hand-off points (episode
selection -> bz2 decode -> batch assembly -> batcher IPC -> host-to-device
staging -> async dispatch of the compiled update -> blocking on device
results), and a regression in any one of
them hides inside an aggregate episodes/sec number. ``StageTimer``
accumulates wall seconds and event counts per named stage from any thread
(batcher threads and the trainer thread share one instance), and the
``HANDYRL_TPU_TIMING=1`` hook prints one compact JSON line per epoch with
the breakdown — the same stage names ``BENCH_MODE=ingest`` (bench.py)
reports, so a bench row and a live-run epoch line are directly comparable.

Canonical stage names for the ingest path (telemetry.INGEST_STAGES is the
one authoritative tuple):
  select / decode / assemble / ipc / h2d / dispatch / host_block

``dispatch`` is the host time to issue the compiled update (async — the
call returns as soon as XLA accepts the work); ``host_block`` is the time
the host then spends blocked on device results (block_until_ready / metric
fetch). Their ratio is the device-utilization proxy the compiled-
performance plane exports (docs/observability.md).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict

from .. import telemetry


class StageTimer:
    """Thread-safe accumulator of per-stage wall time.

    ``add`` is cheap (one lock acquisition); the timed sections themselves
    run unlocked, so batcher threads never serialize on the timer.

    ``registry`` (a telemetry.MetricRegistry) mirrors every ``add`` into
    the ``stage_seconds{stage=...}`` span-histogram family, so the same
    measurements that feed the per-epoch timing line and the ingest bench
    also feed the fleet-wide telemetry/exporter view — and, when episode
    tracing is active (``HANDYRL_TPU_TRACE``), each registry-mirrored add
    also lands as a rate-sampled batch-level span in the trace file (one
    vocabulary for bench rows, timing lines, histograms and traces).
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._acc: Dict[str, float] = {}
        self._n: Dict[str, int] = {}
        self._registry = registry

    def add(self, stage: str, seconds: float, count: int = 1):
        with self._lock:
            self._acc[stage] = self._acc.get(stage, 0.0) + seconds
            self._n[stage] = self._n.get(stage, 0) + count
        if self._registry is not None:
            self._registry.observe_stage(stage, seconds, count)
            telemetry.trace_stage(stage, seconds, count)

    @contextmanager
    def section(self, stage: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(stage, time.perf_counter() - t0)

    def snapshot(self, reset: bool = False) -> Dict[str, Dict[str, float]]:
        """{stage: {'s': total_seconds, 'n': events}} at this instant."""
        with self._lock:
            out = {k: {'s': round(self._acc[k], 4), 'n': self._n.get(k, 0)}
                   for k in self._acc}
            if reset:
                self._acc.clear()
                self._n.clear()
        return out

    def seconds(self, stage: str) -> float:
        with self._lock:
            return self._acc.get(stage, 0.0)


def null_section(_stage):
    """A no-op replacement for ``StageTimer.section`` when timing is off."""
    return _NULL


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()
