"""Packed device->host transfers.

On a tunneled TPU every DISTINCT array fetch pays one host round trip
(~100-140 ms measured through the axon WAN tunnel) regardless of size,
while bandwidth is cheap (a 4 MB array arrives in ~one round trip). Naive
``np.asarray`` per pytree leaf therefore costs leaves x RTT — seconds for a
parameter tree at every epoch boundary. ``fetch_tree`` flattens the tree
into ONE device buffer per dtype (a tiny jitted concat, dispatched async)
and pays one round trip per dtype group instead.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry

_PACKERS: Dict[Tuple, Any] = {}
_SPLITTERS: Dict[Tuple, Any] = {}


def _packer(sig: Tuple) -> Any:
    """One cached jitted concat per (dtype, shapes) signature."""
    fn = _PACKERS.get(sig)
    if fn is None:
        fn = jax.jit(lambda ls: jnp.concatenate([l.reshape(-1) for l in ls]))
        _PACKERS[sig] = fn
    return fn


def _splitter(sig: Tuple) -> Any:
    """One cached jitted split+reshape per (dtype, shapes) signature."""
    fn = _SPLITTERS.get(sig)
    if fn is None:
        _, shapes = sig

        def split(flat):
            out, pos = [], 0
            for shape in shapes:
                n = 1
                for s in shape:
                    n *= s
                out.append(jax.lax.dynamic_slice(flat, (pos,), (n,))
                           .reshape(shape))
                pos += n
            return out

        fn = jax.jit(split)
        _SPLITTERS[sig] = fn
    return fn


def fetch_tree(tree: Any) -> Any:
    """Device pytree -> host numpy pytree in one round trip per dtype.

    Leaves already on host (numpy / python scalars) pass through untouched.
    Structure, shapes, and dtypes are preserved exactly.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    device_ix: Dict[Any, List[int]] = {}
    out: List[Any] = [None] * len(leaves)
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array):
            device_ix.setdefault(jnp.asarray(leaf).dtype, []).append(i)
        else:
            out[i] = leaf
    for dtype, idxs in device_ix.items():
        group = [leaves[i] for i in idxs]
        if len(group) == 1:
            flat_host = np.asarray(group[0]).reshape(-1)
        else:
            sig = (str(dtype), tuple(g.shape for g in group))
            # per-signature cached jit: a FRESH signature compiles once by
            # design, so the scope is declared to the retrace sentinel
            with telemetry.expected_compile('fetch_tree packer'):
                flat_host = np.asarray(_packer(sig)(group))
        pos = 0
        for i, g in zip(idxs, group):
            n = int(np.prod(g.shape)) if g.shape else 1
            out[i] = flat_host[pos:pos + n].reshape(g.shape)
            pos += n
    return jax.tree_util.tree_unflatten(treedef, out)


def put_tree(tree: Any) -> Any:
    """Host numpy pytree -> device pytree in one upload per dtype.

    The mirror of ``fetch_tree``: leaves are concatenated on the HOST, sent
    as one buffer, and split back by a tiny cached jitted program — instead
    of one `device_put` round trip per leaf (actor-params refresh happens
    every epoch)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: Dict[Any, List[int]] = {}
    out: List[Any] = [None] * len(leaves)
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        leaves[i] = arr
        groups.setdefault(arr.dtype, []).append(i)
    for dtype, idxs in groups.items():
        group = [leaves[i] for i in idxs]
        if len(group) == 1:
            out[idxs[0]] = jax.device_put(group[0])
            continue
        shapes = tuple(tuple(g.shape) for g in group)
        flat = np.concatenate([g.reshape(-1) for g in group])
        with telemetry.expected_compile('put_tree splitter'):
            parts = _splitter((str(dtype), shapes))(jax.device_put(flat))
        for i, part in zip(idxs, parts):
            out[i] = part
    return jax.tree_util.tree_unflatten(treedef, out)
