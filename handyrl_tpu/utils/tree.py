"""Small host-side pytree helpers.

The reference hand-rolls a recursive container library (`/root/reference/
handyrl/util.py`). Here the device side uses ``jax.tree_util`` directly; these
helpers cover the host-side cases where ``None`` is a meaningful leaf (a
player who did not observe a step) and jax's registry would prune it.
"""

from __future__ import annotations

import numpy as np


def map_structure(fn, x):
    """Recursively apply ``fn`` to every non-container leaf, keeping None-leaves
    visible to ``fn`` (unlike jax.tree_util, which drops them)."""
    if isinstance(x, (list, tuple)):
        return type(x)(map_structure(fn, v) for v in x)
    if isinstance(x, dict):
        return {k: map_structure(fn, v) for k, v in x.items()}
    return fn(x)


def stack_structure(items, axis=0):
    """Stack a list of identically-shaped structures leaf-wise into arrays."""
    head = items[0]
    if isinstance(head, (list, tuple)):
        return type(head)(stack_structure([it[i] for it in items], axis)
                          for i in range(len(head)))
    if isinstance(head, dict):
        return {k: stack_structure([it[k] for it in items], axis) for k in head}
    return np.stack([np.asarray(it) for it in items], axis=axis)


def batch_structure(x):
    """Add a leading batch dim of 1 to every leaf (None passes through)."""
    return map_structure(lambda v: None if v is None else np.asarray(v)[None], x)


def unbatch_structure(x):
    """Drop the leading batch dim from every leaf (None passes through)."""
    return map_structure(lambda v: None if v is None else np.asarray(v)[0], x)


def softmax(x: np.ndarray) -> np.ndarray:
    """Numerically stable softmax over the last axis (host numpy)."""
    e = np.exp(x - np.max(x, axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)
