"""Match gateway: sessionful gameplay over the stateless replica fleet.

The fleet (fleet.py) serves pure per-ply inference; a real product runs
*matches*. The :class:`MatchGateway` is the session tier on top of
:class:`~.fleet.RoutedClient`: a client opens a session naming an
environment and a ``line@selector``, the gateway instantiates the env
host-side (any :class:`~..environment.BaseEnvironment`), steps every
opponent seat through the fleet, and caches recurrent hidden state
keyed by session — so each client ply is one round trip and consecutive
plies of a session coalesce into the same engine batch (session
affinity via the :class:`~..fault.SessionLedger`).

Robustness model — the PR 12 zero-loss story extended from requests to
sessions. Every session keeps a compact **journal**: env name + the
audited seed that built it, the model spec *pinned* to a concrete
``line@version`` at open (so a champion flip mid-match never forks the
opponent), the full action history, and a digest of the cached hidden
state. Because fleet inference is pure in ``(model@version, obs,
hidden, legal, seed)``, the journal is a complete reconstruction
recipe:

* **drain → handoff.** A draining replica's sessions are re-pinned to a
  survivor with ZERO replayed plies — the hidden cache lives in the
  gateway and rides the next request (``gateway_handoffs_total``).
* **SIGKILL → reconstruct.** The monitor rebuilds each stranded
  session from its journal: a fresh env from ``(env, seed)``, every
  journaled opponent ply replayed through a survivor with its original
  audited seed. Replayed actions must equal the journaled ones and the
  rebuilt hidden digest must equal the journal's — byte-identical, and
  the rebuilt state is *adopted*, so play continues on proven state
  (``gateway_reconstructs_total`` / ``gateway_replayed_plies_total``;
  a divergence books ``gateway_reconstruct_mismatch_total`` and drops
  the session — loudly, never silently).

Match outcomes feed the league :class:`~..league.RatingBook`: external
players are provisional members (seeded at the learner's rating, high
sigma, never promotion-eligible), the served model is its rated
``line@version`` entry. Admission control sheds *opens*, never plies.
Opponent inference seeds ride the audited
:func:`~..generation.sample_seed` machinery under namespace
``GATEWAY_SEED_NAMESPACE`` so replay is a pure function of the journal.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from .. import telemetry
from ..connection import FramedConnection, Hub
from ..connection import TRACE_KEY, open_socket_connection
from ..environment import make_env
from ..fault import HOST_DEGRADED, HOST_HEALTHY, SessionLedger
from ..generation import sample_seed
from ..guard import PREEMPT_EXIT_CODE, PreemptionGuard
from ..league import journal_path, make_rating_book
from .client import (SERVE_KIND, ServiceClient, ServiceError,
                     ServiceUnavailable, is_serve, parse_endpoint)
from .fleet import RoutedClient
from .service import ring_percentile_ms

_LOG = telemetry.get_logger('serving')

# Episode-key namespace for gateway opponent-inference draws (0 =
# generation, 1 = worker-local, 2 = evaluator, 3 = league — see
# generation.py / league.py). Draw 0 derives the per-session env seed;
# opponent plies consume draws 1, 2, ... in strict session order, so a
# journal replay re-consumes the identical sequence.
GATEWAY_SEED_NAMESPACE = 4

_ROUTABLE = (HOST_HEALTHY, HOST_DEGRADED)


def _feed(h, node) -> None:
    if node is None:
        h.update(b'N')
    elif isinstance(node, dict):
        h.update(b'D')
        for k in sorted(node, key=str):
            h.update(str(k).encode('utf-8'))
            _feed(h, node[k])
    elif isinstance(node, (list, tuple)):
        h.update(b'L%d' % len(node))
        for v in node:
            _feed(h, v)
    elif isinstance(node, np.ndarray):
        h.update(b'A')
        h.update(str(node.dtype).encode('ascii'))
        h.update(str(node.shape).encode('ascii'))
        h.update(np.ascontiguousarray(node).tobytes())
    elif isinstance(node, (bytes, bytearray)):
        h.update(b'B')
        h.update(bytes(node))
    else:
        h.update(b'S')
        h.update(repr(node).encode('utf-8'))


def state_digest(state) -> str:
    """Deterministic digest of a (possibly nested) hidden-state pytree —
    the byte-identity witness the session journal carries."""
    h = hashlib.sha1()
    _feed(h, state)
    return h.hexdigest()


def session_env_seed(base_seed: int, counter: int) -> int:
    """Per-session env construction seed: draw 0 of the session's audited
    sequence, folded to one int (HungryGeese-style envs seed their own
    ``random.Random(args['id'])`` from it)."""
    seq = sample_seed(int(base_seed),
                      (GATEWAY_SEED_NAMESPACE, int(counter)), 0)
    return int(np.random.default_rng(seq).integers(0, 2 ** 31 - 1))


class MatchSession:
    """One open match: the host-side env, the per-seat hidden cache, and
    the journal that makes both reconstructible."""

    def __init__(self, sid: str, counter: int, env_name: str,
                 env_args: Dict[str, Any], env, model: str, seat: int,
                 base_seed: int, client: str, clock=time.time, trace=None):
        self.sid = sid
        self.counter = int(counter)
        self.env = env
        self.model = str(model)          # pinned line@version (or raw spec)
        self.seat = int(seat)
        self.client = str(client)
        self.base_seed = int(base_seed)
        self.opened_at = clock()
        self.last_active = self.opened_at
        self.lock = threading.Lock()
        self.hiddens: Dict[int, Any] = {}   # opponent seat -> cached hidden
        self.draws = 1                       # draw 0 built the env seed
        # the session's trace context: the id minted (or adopted) at open;
        # reconstruct/handoff link spans carry it so a failover reads as
        # one causal chain from the original open
        self.trace = trace
        self.lat_ring: deque = deque(maxlen=64)   # per-session ply seconds
        self.done = False
        self.outcome: Optional[Dict[int, float]] = None
        self.journal: Dict[str, Any] = {
            'sid': sid, 'counter': self.counter, 'env': str(env_name),
            'env_args': dict(env_args), 'model': self.model,
            'seat': self.seat, 'client': self.client,
            'base_seed': self.base_seed,
            'actions': [],                   # one {player: action} per step
            'hidden_digest': state_digest({}),
        }

    def plies(self) -> int:
        return len(self.journal['actions'])

    def summary(self, replica=None, clock=time.time) -> Dict[str, Any]:
        return {'sid': self.sid, 'env': self.journal['env'],
                'model': self.model, 'seat': self.seat,
                'client': self.client, 'plies': self.plies(),
                'version': self.model.rpartition('@')[2] or None,
                'ply_p99_ms': (ring_percentile_ms(list(self.lat_ring), 0.99)
                               if self.lat_ring else None),
                'age_s': round(clock() - self.opened_at, 3),
                'replica': replica, 'done': self.done}


class MatchGateway:
    """The session tier: listener + Hub + worker pool over the fleet.

    ``args`` is a train_args-style dict; knobs ride
    ``serving.gateway.*`` (see config.py). Fast admin ops (``status`` /
    ``sessions``) answer inline on the dispatch thread; ``open`` /
    ``play`` / ``close`` run on the worker pool, each worker owning its
    own :class:`RoutedClient` (the one-submitter-per-instance
    contract). A monitor thread watches the fleet table: draining
    replicas hand their sessions off, vanished replicas trigger
    journal reconstruction.
    """

    def __init__(self, args: Dict[str, Any]):
        srv = dict(args.get('serving') or {})
        gw = dict(srv.get('gateway') or {})
        flt = dict(srv.get('fleet') or {})
        self.port = int(gw.get('port', 0) or 0)
        self.workers_n = max(1, int(gw.get('workers', 4)))
        self.max_sessions = max(1, int(gw.get('max_sessions', 64)))
        self.ply_timeout = max(0.1, float(gw.get('ply_timeout', 15.0)))
        self.monitor_interval = max(0.05, float(gw.get('monitor_interval',
                                                       0.5)))
        self.session_timeout = max(1.0, float(gw.get('session_timeout',
                                                     600.0)))
        self.default_model = str(gw.get('model') or 'default@champion')
        self.resolver_endpoint = str(gw.get('resolver')
                                     or flt.get('resolver') or '')
        if not self.resolver_endpoint:
            raise ValueError('the match gateway needs a fleet resolver '
                             '(serving.gateway.resolver)')
        self.base_seed = int(args.get('seed', 0) or 0)
        root = srv.get('registry_dir') or args.get('model_dir', 'models')
        self.ratings = make_rating_book(args.get('league') or {})
        self._ratings_path = journal_path(str(root))
        self.ratings.load(self._ratings_path)
        self._ratings_lock = threading.Lock()

        self.ledger = SessionLedger()
        self._sessions: Dict[str, MatchSession] = {}
        self._lock = threading.Lock()
        self._counter = 0
        self._queue: "queue.Queue" = queue.Queue()
        self._tl = threading.local()
        self._lat_ring: deque = deque(maxlen=512)   # guarded-by: _lock
        self._stop = False
        self._sock: Optional[socket.socket] = None
        self.hub: Optional[Hub] = None
        self._threads: List[threading.Thread] = []
        self.metrics_port = int(gw.get('metrics_port') or 0)
        self._exporter = None

        self._m_opened = telemetry.counter('gateway_sessions_opened_total')
        self._m_closed = telemetry.counter('gateway_sessions_closed_total')
        self._m_drops = telemetry.counter('gateway_session_drops_total')
        self._m_shed = telemetry.counter('gateway_shed_total')
        self._m_plies = telemetry.counter('gateway_plies_total')
        self._m_outcomes = telemetry.counter('gateway_outcomes_total')
        self._m_handoffs = telemetry.counter('gateway_handoffs_total')
        self._m_reconstructs = telemetry.counter(
            'gateway_reconstructs_total')
        self._m_replayed = telemetry.counter('gateway_replayed_plies_total')
        self._m_mismatch = telemetry.counter(
            'gateway_reconstruct_mismatch_total')
        self._m_open_g = telemetry.gauge('gateway_sessions_open')
        self._m_age_g = telemetry.gauge('gateway_session_age_seconds')
        self._m_p99_g = telemetry.gauge('gateway_ply_p99_ms')
        self._m_ply_h = telemetry.REGISTRY.histogram('gateway_ply_seconds')
        self._alerts = telemetry.AlertEngine.from_config(args)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> 'MatchGateway':
        self._sock = open_socket_connection(self.port)
        self._sock.listen(self.max_sessions + 8)
        self._sock.settimeout(0.5)
        self.port = self._sock.getsockname()[1]
        self.hub = Hub()
        if self.metrics_port and telemetry.enabled():
            self._exporter = telemetry.TelemetryExporter(
                lambda: [telemetry.snapshot()], port=self.metrics_port,
                status=self._status_info,
            ).start()
            self.metrics_port = self._exporter.port
        loops = [(self._accept_loop, 'gateway-accept'),
                 (self._dispatch_loop, 'gateway-dispatch'),
                 (self._monitor_loop, 'gateway-monitor')]
        loops += [(self._worker_loop, 'gateway-worker-%d' % i)
                  for i in range(self.workers_n)]
        for target, name in loops:
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        _LOG.info('match gateway listening on port %d (resolver %s, '
                  '%d worker(s), max %d sessions)', self.port,
                  self.resolver_endpoint, self.workers_n,
                  self.max_sessions)
        return self

    def stop(self, drain: bool = True):
        if drain:
            # sessions are reconstructible from their journals by design;
            # a gateway drain just stops admitting and lets in-flight ops
            # finish (they complete in worker time, bounded by ply_timeout)
            deadline = time.monotonic() + min(self.ply_timeout, 30.0)
            while not self._queue.empty() and time.monotonic() < deadline:
                time.sleep(0.02)
        self._stop = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        time.sleep(0.25)     # let Hub writers flush final replies
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None

    # -- the per-thread fleet router ---------------------------------------

    def _router(self) -> RoutedClient:
        r = getattr(self._tl, 'router', None)
        if r is None:
            host, port = parse_endpoint(self.resolver_endpoint)
            r = RoutedClient(host, port, timeout=self.ply_timeout,
                             name='gateway',
                             refresh_interval=self.monitor_interval)
            self._tl.router = r
        return r

    # -- accept / dispatch -------------------------------------------------

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.hub.attach(FramedConnection(conn), liveness=0)

    def _dispatch_loop(self):
        while not self._stop:
            try:
                ep, msg = self.hub.recv(timeout=0.3)
            except queue.Empty:
                continue
            try:
                if not is_serve(msg) or not isinstance(msg[1], dict):
                    self.hub.send(ep, (SERVE_KIND,
                                       {'error': 'unknown frame kind'}))
                    continue
                body = msg[1]
                op = body.get('op')
                if op == 'status':
                    self.hub.send(ep, (SERVE_KIND, self.stats()))
                elif op == 'sessions':
                    self.hub.send(ep, (SERVE_KIND,
                                       {'sessions': self.session_table()}))
                elif op == 'trace':
                    # runtime tracing toggle (bench A/B legs flip the
                    # SAME warmed gateway on and off between legs)
                    telemetry.configure_tracing(
                        str(body.get('dir') or ''), body.get('rate'),
                        force=True)
                    self.hub.send(ep, (SERVE_KIND,
                                       {'ok': True,
                                        'dir': telemetry.trace_dir(),
                                        'rate':
                                            telemetry.trace_sample_rate()}))
                elif op in ('open', 'play', 'close'):
                    self._queue.put((ep, body))
                else:
                    self.hub.send(ep, (SERVE_KIND,
                                       {'error': 'unknown gateway op %r'
                                                 % (op,)}))
            except Exception as exc:   # noqa: BLE001 — the loop must live
                _LOG.error('gateway: dispatch error (%s: %s)',
                           type(exc).__name__, str(exc)[:200])

    def _worker_loop(self):
        while not self._stop:
            try:
                ep, body = self._queue.get(timeout=0.3)
            except queue.Empty:
                continue
            op = body.get('op')
            try:
                if op == 'open':
                    reply = self._op_open(body)
                elif op == 'play':
                    reply = self._op_play(body)
                else:
                    reply = self._op_close(body)
            except (ServiceError, ServiceUnavailable, TimeoutError) as exc:
                reply = {'error': '%s: %s' % (type(exc).__name__, exc)}
            except Exception as exc:   # noqa: BLE001 — answer, never drop
                _LOG.error('gateway: %s failed (%s: %s)', op,
                           type(exc).__name__, str(exc)[:200])
                reply = {'error': '%s: %s' % (type(exc).__name__, exc)}
            try:
                self.hub.send(ep, (SERVE_KIND, reply))
            except Exception:   # noqa: BLE001 — client gone mid-reply
                pass

    # -- session ops -------------------------------------------------------

    def _op_open(self, body: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            if len(self._sessions) >= self.max_sessions:
                self._m_shed.inc()
                return {'error': 'gateway full (%d sessions)'
                                 % self.max_sessions, 'shed': True}
            self._counter += 1
            counter = self._counter
        env_name = str(body.get('env') or '')
        model = str(body.get('model') or self.default_model)
        seat = int(body.get('seat', 0))
        client = str(body.get('client') or 'anon')[:64]
        base_seed = int(body['seed']) if body.get('seed') is not None \
            else self.base_seed
        env_args = {'env': env_name,
                    'id': session_env_seed(base_seed, counter)}
        try:
            env = make_env(dict(env_args))
            env.reset()
        except Exception as exc:   # noqa: BLE001 — bad env name/args
            return {'error': 'cannot build env %r: %s' % (env_name, exc)}
        if seat not in env.players():
            return {'error': 'seat %d not in players %s'
                             % (seat, env.players())}
        router = self._router()
        pinned = router._pin_spec(model)
        sid = 's%06d' % counter
        # session trace context: adopt the client's id, else mint at this
        # edge; every ply/seat/reconstruct span of the session links to it
        tid = body.get(TRACE_KEY) or (telemetry.mint_trace_id()
                                      if telemetry.trace_enabled() else None)
        t0 = time.time()
        session = MatchSession(sid, counter, env_name, env_args, env,
                               pinned, seat, base_seed, client, trace=tid)
        with self._lock:
            self._sessions[sid] = session
        with session.lock:
            self._advance(session, None, router, trace=tid)
            if router.last_replica is not None:
                self.ledger.book(sid, router.last_replica)
            reply = self._state_reply(session)
        if tid:
            telemetry.trace_event('gateway_open', ts=t0,
                                  dur=time.time() - t0, trace_id=tid,
                                  sid=sid, model=pinned, client=client)
        self._m_opened.inc()
        self._set_gauges()
        reply.update({'sid': sid, 'seat': seat, 'model': pinned})
        if session.done:
            self._finish(session)
        return reply

    def _op_play(self, body: Dict[str, Any]) -> Dict[str, Any]:
        sid = str(body.get('sid') or '')
        with self._lock:
            session = self._sessions.get(sid)
        if session is None:
            return {'error': 'unknown session %r' % sid}
        router = self._router()
        # per-ply trace context: the client's id if it sent one, else a
        # fresh mint; args carry the session's open-time id as the link
        tid = body.get(TRACE_KEY) or (telemetry.mint_trace_id()
                                      if telemetry.trace_enabled() else None)
        t0 = time.monotonic()
        t0_wall = time.time()
        with session.lock:
            if session.done:
                return dict(self._state_reply(session), sid=sid)
            action: Optional[int] = None
            if session.seat in (int(p) for p in session.env.turns()):
                if body.get('action') is None:
                    return {'error': 'it is your turn in session %s — '
                                     'an action is required' % sid}
                action = int(body['action'])
                if action not in [int(a)
                                  for a in session.env.legal_actions(
                                      session.seat)]:
                    return {'error': 'illegal action %d in session %s'
                                     % (action, sid)}
            elif body.get('action') is not None:
                return {'error': 'not your turn in session %s' % sid}
            # action None here = a spectate poll (the client's seat is out
            # of the match but the game runs on): advance to terminal
            before = session.plies()
            self._advance(session, action, router, trace=tid)
            played = session.journal['actions'][before:]
            if router.last_replica is not None:
                self.ledger.move(sid, router.last_replica)
            session.last_active = time.time()
            session.lat_ring.append(time.monotonic() - t0)
            reply = self._state_reply(session)
        dt = time.monotonic() - t0
        with self._lock:
            self._lat_ring.append(dt)
        self._m_plies.inc()
        self._m_ply_h.observe(dt)
        if tid:
            telemetry.trace_event('gateway_ply', ts=t0_wall, dur=dt,
                                  trace_id=tid, sid=sid,
                                  session_trace=session.trace)
        reply.update({'sid': sid,
                      'actions': [{int(p): int(a) for p, a in step.items()}
                                  for step in played]})
        if session.done:
            self._finish(session)
        self._set_gauges()
        return reply

    def _op_close(self, body: Dict[str, Any]) -> Dict[str, Any]:
        sid = str(body.get('sid') or '')
        with self._lock:
            session = self._sessions.pop(sid, None)
        if session is None:
            return {'error': 'unknown session %r' % sid}
        self.ledger.release(sid)
        self._m_closed.inc()
        self._set_gauges()
        return {'sid': sid, 'closed': True, 'done': session.done}

    def _state_reply(self, session: MatchSession) -> Dict[str, Any]:
        env = session.env
        out: Dict[str, Any] = {'done': bool(env.terminal())}
        if out['done']:
            session.done = True
            session.outcome = {int(p): float(s)
                               for p, s in env.outcome().items()}
            out['outcome'] = session.outcome
        else:
            out['obs'] = env.observation(session.seat)
            out['legal'] = [int(a)
                            for a in env.legal_actions(session.seat)] \
                if session.seat in env.turns() else []
            out['to_move'] = session.seat in env.turns()
        return out

    # -- the opponent-stepping core ----------------------------------------

    def _advance(self, session: MatchSession, action: Optional[int],
                 router: RoutedClient,
                 replica: Optional[str] = None, trace=None) -> None:
        """Step the env until it is the client's turn with no pending
        action, or terminal. Every step's action dict lands in the
        journal; opponent seats act (and observers watch) through the
        fleet in sorted-seat order, so a journal replay consumes the
        identical audited-seed sequence."""
        env = session.env
        while not env.terminal():
            acting = sorted(int(p) for p in env.turns())
            watching = sorted(int(p) for p in env.observers())
            if session.seat in acting and action is None:
                break
            moves: Dict[int, int] = {}
            for p in acting:
                if p == session.seat:
                    moves[p] = int(action)
                    action = None
                else:
                    moves[p] = self._opponent_act(session, p, router,
                                                  replica, trace)
            for p in watching:
                if p != session.seat:
                    self._opponent_watch(session, p, router, replica,
                                         trace)
            env.step(moves)
            session.journal['actions'].append(
                {int(p): int(a) for p, a in moves.items()})
        session.journal['hidden_digest'] = state_digest(session.hiddens)

    def _seed_seq(self, session: MatchSession) -> List[int]:
        seq = sample_seed(session.base_seed,
                          (GATEWAY_SEED_NAMESPACE, session.counter),
                          session.draws)
        session.draws += 1
        return seq

    def _opponent_act(self, session: MatchSession, p: int,
                      router: RoutedClient,
                      replica: Optional[str] = None, trace=None) -> int:
        env = session.env
        t0 = time.time()
        reply = router.request(
            session.model, env.observation(p),
            hidden=session.hiddens.get(p),
            legal=[int(a) for a in env.legal_actions(p)],
            seed=self._seed_seq(session),
            timeout=self.ply_timeout,
            replica=replica if replica is not None
            else self.ledger.replica_of(session.sid),
            trace=trace)
        if trace:
            telemetry.trace_event('gateway_seat', ts=t0,
                                  dur=time.time() - t0, trace_id=trace,
                                  sid=session.sid, seat=p)
        session.hiddens[p] = reply.get('hidden')
        return int(reply['action'])

    def _opponent_watch(self, session: MatchSession, p: int,
                        router: RoutedClient,
                        replica: Optional[str] = None, trace=None) -> None:
        env = session.env
        t0 = time.time()
        reply = router.request(
            session.model, env.observation(p),
            hidden=session.hiddens.get(p),
            timeout=self.ply_timeout,
            replica=replica if replica is not None
            else self.ledger.replica_of(session.sid),
            trace=trace)
        if trace:
            telemetry.trace_event('gateway_seat', ts=t0,
                                  dur=time.time() - t0, trace_id=trace,
                                  sid=session.sid, seat=p, watch=True)
        session.hiddens[p] = (reply.get('outputs') or {}).get('hidden')

    # -- outcome booking ---------------------------------------------------

    def _finish(self, session: MatchSession):
        """Book the finished match into the RatingBook (the external
        player is a provisional member; the served model is its rated
        ``line@version`` entry) and retire the session."""
        with self._lock:
            live = self._sessions.pop(session.sid, None)
        self.ledger.release(session.sid)
        if live is None:      # already closed/dropped concurrently
            return
        score = (session.outcome or {}).get(session.seat, 0.0)
        score = min(max(0.5 * (1.0 + float(score)), 0.0), 1.0)
        player = 'gateway:%s' % session.client
        with self._ratings_lock:
            self.ratings.seed_provisional(player)
            self.ratings.record_between(player, session.model, score)
            try:
                self.ratings.save(self._ratings_path)
            except OSError as exc:
                _LOG.warning('gateway: rating journal write failed: %s',
                             exc)
        self._m_outcomes.inc()
        self._m_closed.inc()
        self._set_gauges()

    def _drop(self, session: MatchSession, reason: str):
        with self._lock:
            self._sessions.pop(session.sid, None)
        self.ledger.release(session.sid)
        self._m_drops.inc()
        telemetry.record_event('session_drop', session.sid, reason=reason)
        _LOG.error('gateway: dropped session %s (%s)', session.sid, reason)
        self._set_gauges()

    # -- journal reconstruction --------------------------------------------

    def _reconstruct(self, session: MatchSession,
                     router: RoutedClient) -> bool:
        """Rebuild a stranded session from its journal through a
        survivor: fresh env from ``(env, seed)``, every opponent ply
        replayed with its original audited seed. The replayed actions
        and the rebuilt hidden digest must match the journal — then the
        rebuilt state is adopted, proving the journal alone carries the
        match. False (and a drop) on divergence."""
        j = session.journal
        # link span: the replay-through-a-survivor carries the session's
        # ORIGINAL open-time trace id, so the SIGKILL reads as one chain
        tid = session.trace
        t0 = time.time()
        env = make_env(dict(j['env_args']))
        env.reset()
        hiddens: Dict[int, Any] = {}
        draws = 1
        replayed = 0
        for step in list(j['actions']):
            step = {int(p): int(a) for p, a in step.items()}
            acting = sorted(int(p) for p in env.turns())
            watching = sorted(int(p) for p in env.observers())
            for p in acting:
                if p == j['seat']:
                    continue
                seq = sample_seed(j['base_seed'],
                                  (GATEWAY_SEED_NAMESPACE, j['counter']),
                                  draws)
                draws += 1
                reply = router.request(
                    j['model'], env.observation(p),
                    hidden=hiddens.get(p),
                    legal=[int(a) for a in env.legal_actions(p)],
                    seed=seq, timeout=self.ply_timeout, trace=tid)
                hiddens[p] = reply.get('hidden')
                replayed += 1
                if int(reply['action']) != step.get(p):
                    self._m_mismatch.inc()
                    if tid:
                        telemetry.trace_event(
                            'gateway_reconstruct', ts=t0,
                            dur=time.time() - t0, trace_id=tid,
                            link='reconstruct', sid=session.sid,
                            replayed=replayed, ok=False)
                    self._drop(session, 'reconstruct action mismatch at '
                                        'ply %d seat %d' % (replayed, p))
                    return False
            for p in watching:
                if p != j['seat']:
                    reply = router.request(j['model'], env.observation(p),
                                           hidden=hiddens.get(p),
                                           timeout=self.ply_timeout,
                                           trace=tid)
                    hiddens[p] = (reply.get('outputs') or {}).get('hidden')
            env.step(step)
        if state_digest(hiddens) != j['hidden_digest']:
            self._m_mismatch.inc()
            if tid:
                telemetry.trace_event('gateway_reconstruct', ts=t0,
                                      dur=time.time() - t0, trace_id=tid,
                                      link='reconstruct', sid=session.sid,
                                      replayed=replayed, ok=False)
            self._drop(session, 'reconstruct hidden-digest mismatch')
            return False
        session.env = env
        session.hiddens = hiddens
        session.draws = draws
        self._m_reconstructs.inc()
        self._m_replayed.inc(replayed)
        if tid:
            telemetry.trace_event('gateway_reconstruct', ts=t0,
                                  dur=time.time() - t0, trace_id=tid,
                                  link='reconstruct', sid=session.sid,
                                  replayed=replayed, ok=True)
        if router.last_replica is not None:
            self.ledger.move(session.sid, router.last_replica)
        _LOG.warning('gateway: reconstructed session %s (%d plies '
                     'replayed, digest verified)', session.sid, replayed)
        return True

    # -- fleet monitoring: handoff and reconstruction ----------------------

    def _monitor_loop(self):
        router: Optional[RoutedClient] = None
        known: Dict[str, Dict[str, Any]] = {}
        while not self._stop:
            time.sleep(self.monitor_interval)
            try:
                if router is None:
                    host, port = parse_endpoint(self.resolver_endpoint)
                    router = RoutedClient(host, port,
                                          timeout=self.ply_timeout,
                                          name='gateway-monitor',
                                          refresh_interval=
                                          self.monitor_interval)
                table = {str(r['replica']): r for r in router.replicas()}
            except (ServiceUnavailable, TimeoutError, ServiceError):
                continue
            survivors = [n for n, r in sorted(table.items())
                         if r.get('state') in _ROUTABLE
                         and not r.get('draining')]
            # drain → handoff: zero replayed plies, the hidden cache is
            # ours and simply rides the next request to the survivor
            for name, rec in table.items():
                if rec.get('draining') and rec.get('state') in _ROUTABLE:
                    self._handoff(name, survivors, reason='drain')
            # SIGKILL → reconstruct: the replica vanished from the table
            # (externally managed) or was stranded out of the routable
            # states (a managed corpse walks healthy → quarantined and is
            # respawned under its old name — its in-flight plies died)
            dead = list(set(known) - set(table))
            dead += [name for name, rec in table.items()
                     if rec.get('state') not in _ROUTABLE]
            for name in dead:
                sids = self.ledger.fail_replica(name, reason='killed')
                for sid in sids:
                    with self._lock:
                        session = self._sessions.get(sid)
                    if session is None:
                        continue
                    with session.lock:
                        if not session.done:
                            self._reconstruct(session, router)
            known = table
            self._reap()
            self._set_gauges()
            if self._alerts is not None:
                self._alerts.maybe_evaluate(
                    lambda: [telemetry.snapshot()])

    def _handoff(self, replica: str, survivors: List[str], reason: str):
        sids = self.ledger.sessions_on(replica)
        if not sids:
            return
        pool = [s for s in survivors if s != replica]
        if not pool:
            return      # nowhere to go yet; next tick retries
        for i, sid in enumerate(sids):
            target = pool[i % len(pool)]
            self.ledger.move(sid, target)
            self._m_handoffs.inc()
            with self._lock:
                session = self._sessions.get(sid)
            if session is not None and session.trace:
                # link span under the session's original open-time id
                telemetry.trace_event('gateway_handoff',
                                      trace_id=session.trace,
                                      link='handoff', sid=sid,
                                      from_replica=replica,
                                      to_replica=target, reason=reason)
        _LOG.warning('gateway: handed %d session(s) off %s (%s)',
                     len(sids), replica, reason)

    def _reap(self):
        now = time.time()
        with self._lock:
            idle = [s for s in self._sessions.values()
                    if now - s.last_active > self.session_timeout]
        for session in idle:
            self._drop(session, 'session_timeout')

    # -- observability -----------------------------------------------------

    def _set_gauges(self):
        now = time.time()
        with self._lock:
            n = len(self._sessions)
            oldest = max((now - s.opened_at
                          for s in self._sessions.values()), default=0.0)
            lats = list(self._lat_ring)
        self._m_open_g.set(float(n))
        self._m_age_g.set(float(oldest))
        self._m_p99_g.set(ring_percentile_ms(lats, 0.99))

    def session_table(self) -> List[Dict[str, Any]]:
        with self._lock:
            sessions = list(self._sessions.values())
        return [s.summary(replica=self.ledger.replica_of(s.sid))
                for s in sessions]

    def _status_info(self) -> Dict[str, Any]:
        """/statusz payload for the gateway metrics port: the live
        session table (main.py --status renders it), session/ply
        progress, and the gateway's alert state."""
        with self._lock:
            lats = list(self._lat_ring)
        info: Dict[str, Any] = {
            'sessions': self.session_table(),
            'progress': {'opened': int(self._m_opened.value),
                         'plies': int(self._m_plies.value),
                         'outcomes': int(self._m_outcomes.value),
                         'handoffs': int(self._m_handoffs.value),
                         'reconstructs': int(self._m_reconstructs.value),
                         'dropped': int(self._m_drops.value)},
            'slo': {'ply_p50_ms': ring_percentile_ms(lats, 0.50),
                    'ply_p99_ms': ring_percentile_ms(lats, 0.99)},
        }
        if self._alerts is not None:
            info['alerts'] = self._alerts.maybe_evaluate(
                lambda: [telemetry.snapshot()])
        return info

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._sessions)
            lats = list(self._lat_ring)
        return {'gateway': True, 'port': self.port,
                'resolver': self.resolver_endpoint,
                'sessions': n, 'max_sessions': self.max_sessions,
                'opened': int(self._m_opened.value),
                'closed': int(self._m_closed.value),
                'dropped': int(self._m_drops.value),
                'shed': int(self._m_shed.value),
                'plies': int(self._m_plies.value),
                'outcomes': int(self._m_outcomes.value),
                'handoffs': int(self._m_handoffs.value),
                'reconstructs': int(self._m_reconstructs.value),
                'replayed_plies': int(self._m_replayed.value),
                'mismatches': int(self._m_mismatch.value),
                'ply_p50_ms': ring_percentile_ms(lats, 0.50),
                'ply_p99_ms': ring_percentile_ms(lats, 0.99),
                'ledger': dict(self.ledger.stats),
                'ratings': self.ratings.names()}


class GatewayClient:
    """Client for the match gateway: the whole session protocol over one
    :class:`ServiceClient` admin channel (``open``/``play``/``close``
    round trips; one in flight at a time per client, matching the
    one-submitter contract)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 name: str = ''):
        self.name = str(name)
        self._client = ServiceClient(host, int(port), timeout=timeout,
                                     name=name)

    def _call(self, body: Dict[str, Any],
              timeout: Optional[float] = None) -> Dict[str, Any]:
        if (body.get('op') in ('open', 'play') and TRACE_KEY not in body
                and telemetry.trace_enabled()):
            # mint at the true request edge so the chain starts with the
            # client; the gateway adopts the id instead of minting its own
            body = dict(body, **{TRACE_KEY: telemetry.mint_trace_id()})
        reply = self._client.call_admin(body, timeout)
        if reply.get('error'):
            raise ServiceError(str(reply['error']))
        return reply

    def open(self, env: str, model: Optional[str] = None, seat: int = 0,
             seed: Optional[int] = None,
             timeout: Optional[float] = None) -> Dict[str, Any]:
        body: Dict[str, Any] = {'op': 'open', 'env': str(env),
                                'seat': int(seat), 'client': self.name}
        if model is not None:
            body['model'] = str(model)
        if seed is not None:
            body['seed'] = int(seed)
        return self._call(body, timeout)

    def play(self, sid: str, action: Optional[int] = None,
             timeout: Optional[float] = None) -> Dict[str, Any]:
        """Submit a ply (``action=None`` is a spectate poll: the seat is
        out of the match but the game runs on)."""
        body: Dict[str, Any] = {'op': 'play', 'sid': str(sid),
                                'client': self.name}
        if action is not None:
            body['action'] = int(action)
        return self._call(body, timeout)

    def close_session(self, sid: str) -> Dict[str, Any]:
        return self._call({'op': 'close', 'sid': str(sid)})

    def sessions(self) -> List[Dict[str, Any]]:
        return self._call({'op': 'sessions'}).get('sessions', [])

    def status(self) -> Dict[str, Any]:
        return self._call({'op': 'status'})

    def close(self):
        self._client.close()


def gateway_main(args, argv=None):
    """``main.py --gateway``: one MatchGateway over a running fleet
    resolver until SIGTERM/SIGINT, then drain and exit 75 (the
    supervisor restart contract). Prints one JSON ``gateway_ready``
    line once the listener is bound."""
    sargs = dict(args['train_args'])
    sargs['env'] = dict(args.get('env_args') or {})
    telemetry.adopt_config(sargs)
    telemetry.set_process_label('gateway')
    telemetry.install_crash_dump()
    guard = PreemptionGuard().install()
    gateway = MatchGateway(sargs).start()
    print(json.dumps({'gateway_ready': {
        'port': gateway.port, 'pid': os.getpid(),
        'resolver': gateway.resolver_endpoint,
        'max_sessions': gateway.max_sessions}}), flush=True)
    try:
        while not guard.requested():
            time.sleep(0.2)
        _LOG.warning('gateway: preemption signal received; draining')
    finally:
        gateway.stop(drain=True)
        guard.uninstall()
    if guard.fired:
        raise SystemExit(PREEMPT_EXIT_CODE)
