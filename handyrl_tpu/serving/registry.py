"""Versioned model registry: named lines, pinned champions, atomic flips.

The registry is the serving tier's source of truth for *which params a name
refers to*. It is deliberately dumb storage with strong ordering rules:

* **State is one JSON manifest** (``<root>/registry.json``) published with
  the atomic temp+fsync+rename writer (utils/fs.py), so a reader — another
  process, a service restart, a crash-recovering learner — sees either the
  old serving set or the new one, never a prefix. Mutations take a
  cross-process file lock plus a per-instance thread lock and re-read the
  manifest under it, so two racing promotes serialize instead of one
  silently reverting the other.

* **Data lands before the manifest references it.** ``publish`` writes the
  checkpoint bytes + CRC32 sidecar first and only then flips the manifest;
  a crash between the two leaves an orphan file, never a manifest entry
  pointing at unverifiable bytes. ``load_snapshot`` re-verifies the CRC on
  every read — a torn or bit-flipped serving set is an error, not a
  silently wrong model.

* **Promote/rollback are single manifest swaps.** Each line records its
  ``champion`` and the ``previous`` champion; ``promote`` advances the
  pair atomically and ``rollback`` swaps them back, restoring the prior
  champion bit-identically (the version's bytes never move).

* **Pinned versions survive retention GC.** Every version still referenced
  by a line's manifest is *live* (the champion or a rolling candidate);
  :func:`pinned_checkpoint_paths` feeds the learner's ``keep_checkpoints``
  GC exclusion so a registry-pinned ``models/<epoch>.ckpt`` is never
  collected out from under the serving tier.

Versions are either *referenced* (``publish(path=...)`` — the learner
pinning its own numbered checkpoints, which already carry CRC sidecars) or
*owned* (``publish(snapshot=...)`` — bytes copied under
``<root>/<line>/<version>.ckpt``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional, Set, Tuple

from .. import telemetry
from ..fault import Backoff
from ..utils import fs

_LOG = telemetry.get_logger('registry')

MANIFEST_NAME = 'registry.json'
MANIFEST_FORMAT = 1

# default for the serving.lock_timeout knob: how long a mutation waits for
# the cross-process manifest lock before failing loudly instead of hanging
DEFAULT_LOCK_TIMEOUT = 10.0

_m_publishes = telemetry.counter('registry_publishes_total')
_m_promotes = telemetry.counter('registry_promotes_total')
_m_rollbacks = telemetry.counter('registry_rollbacks_total')
_m_lock_timeouts = telemetry.counter('registry_lock_timeouts_total')


class RegistryError(RuntimeError):
    """A resolve/load against the registry cannot be satisfied."""


class RegistryLockTimeout(RegistryError):
    """The cross-process manifest lock could not be acquired within
    ``serving.lock_timeout`` — a peer process is wedged while holding it.
    Raised instead of blocking the caller (e.g. the learner's publish
    hook) forever."""


def parse_spec(spec: str) -> Tuple[str, str]:
    """``'line@selector'`` -> (line, selector); a bare line means its
    champion. Selectors: ``champion``, ``previous``, ``latest``, or an
    exact version identifier."""
    spec = str(spec).strip()
    line, sep, selector = spec.partition('@')
    if not line:
        raise RegistryError('model spec %r names no line' % spec)
    return line, (selector if sep else 'champion') or 'champion'


def _empty_manifest() -> Dict[str, Any]:
    return {'format': MANIFEST_FORMAT, 'lines': {}}


class ModelRegistry:
    """Versioned model lines over one atomic JSON manifest."""

    def __init__(self, root: str,
                 lock_timeout: float = DEFAULT_LOCK_TIMEOUT):
        self.root = os.path.abspath(root)
        self.lock_timeout = float(lock_timeout)
        self._tlock = threading.RLock()
        # (st_mtime_ns, st_size) of the manifest the cache was parsed from;
        # both maps shared by resolve/mutate callers on any thread
        self._cache_stamp: Optional[Tuple[int, int]] = None  # guarded-by: _tlock
        self._cache: Dict[str, Any] = _empty_manifest()      # guarded-by: _tlock

    # -- paths -------------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    def _version_path(self, line: str, version: str) -> str:
        return os.path.join(self.root, line, '%s.ckpt' % version)

    def _abs(self, path: str) -> str:
        return path if os.path.isabs(path) else os.path.join(self.root, path)

    # -- manifest IO -------------------------------------------------------

    def _read(self) -> Dict[str, Any]:
        """Parse the manifest (stat-cached; the atomic writer guarantees a
        whole file). A missing manifest is an empty registry; an unparsable
        one raises — serving from a corrupt manifest would be guessing."""
        with self._tlock:
            try:
                st = os.stat(self.manifest_path)
                stamp = (st.st_mtime_ns, st.st_size)
            except OSError:
                self._cache_stamp = None
                self._cache = _empty_manifest()
                return self._cache
            if stamp == self._cache_stamp:
                return self._cache
            try:
                with open(self.manifest_path, 'r') as f:
                    manifest = json.load(f)
            except ValueError as exc:
                raise RegistryError('registry manifest %s is unparsable '
                                    '(%s)' % (self.manifest_path, exc))
            if not isinstance(manifest, dict) or 'lines' not in manifest:
                raise RegistryError('registry manifest %s has no lines '
                                    'table' % self.manifest_path)
            self._cache_stamp = stamp
            self._cache = manifest
            return manifest

    def _flock(self, lock_fd: int):
        """Acquire the cross-process manifest lock, non-blockingly with
        jittered retries bounded by ``lock_timeout``: a peer that wedged
        while holding the lock must surface as a loud
        :class:`RegistryLockTimeout`, not hang the caller forever."""
        try:
            import fcntl
        except ImportError:           # non-POSIX: thread lock only
            return
        backoff = Backoff(initial=0.02, maximum=0.5, jitter=0.5)
        deadline = time.monotonic() + self.lock_timeout
        while True:
            try:
                fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                return
            except OSError:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    _m_lock_timeouts.inc()
                    raise RegistryLockTimeout(
                        'could not acquire the registry manifest lock under '
                        '%s within %.1fs — a peer process is wedged while '
                        'holding it' % (self.root, self.lock_timeout))
                time.sleep(min(backoff.next_delay(), remaining))

    def _mutate(self, fn) -> Any:
        """Serialized read-modify-write of the manifest: thread lock +
        cross-process ``flock`` on a sidecar lock file, fresh re-read under
        the lock, then ONE atomic publish. Two racing promotes therefore
        serialize; a reader at any instant sees a complete manifest."""
        with self._tlock:
            os.makedirs(self.root, exist_ok=True)
            lock_fd = os.open(os.path.join(self.root, '.registry.lock'),
                              os.O_CREAT | os.O_RDWR, 0o644)
            try:
                self._flock(lock_fd)
                self._cache_stamp = None          # force a fresh read
                manifest = self._read()
                out = fn(manifest)
                fs.atomic_write_bytes(
                    self.manifest_path,
                    (json.dumps(manifest, sort_keys=True) + '\n')
                    .encode('utf-8'))
                self._cache_stamp = None
                return out
            finally:
                os.close(lock_fd)     # releases the flock

    # -- publishing --------------------------------------------------------

    def publish(self, line: str, *, snapshot: Optional[Dict[str, Any]] = None,
                path: Optional[str] = None, architecture: Optional[str] = None,
                config: Optional[Dict[str, Any]] = None, steps: int = 0,
                version: Optional[Any] = None, promote: bool = False) -> str:
        """Register one model version on ``line``; returns its version id.

        Exactly one of ``snapshot`` (an engine-style dict whose bytes are
        copied under the registry root with a CRC sidecar) or ``path`` (a
        reference to an existing CRC-sidecar'd checkpoint, e.g. the
        learner's ``models/<epoch>.ckpt``) must be given. The data file is
        fully on disk before the manifest mentions it. ``promote=True``
        additionally flips the line's champion in the SAME manifest swap.
        """
        if (snapshot is None) == (path is None):
            raise RegistryError('publish takes exactly one of snapshot= '
                                'or path=')
        if snapshot is not None:
            architecture = snapshot['architecture']
            config = snapshot.get('config') or config

        def apply(manifest: Dict[str, Any]) -> str:
            entry = manifest['lines'].setdefault(
                line, {'champion': None, 'previous': None, 'next_seq': 1,
                       'versions': {}})
            seq = int(entry.get('next_seq', 1))
            vid = str(version) if version is not None else str(seq)
            if vid in entry['versions']:
                raise RegistryError('version %s@%s already published'
                                    % (line, vid))
            if snapshot is not None:
                dest = self._version_path(line, vid)
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                fs.checksummed_write_bytes(dest, snapshot['params'])
                rel = os.path.relpath(dest, self.root)
            else:
                rel = os.path.abspath(path)
                if architecture is None:
                    raise RegistryError('publish(path=...) requires '
                                        'architecture=')
            meta: Dict[str, Any] = {'path': rel, 'architecture': architecture,
                                    'steps': int(steps), 'seq': seq,
                                    'time': time.time()}  # graftlint: allow[GL001] publish timestamps are operator metadata in the manifest, not episode-record data
            if config:
                meta['config'] = dict(config)
            entry['versions'][vid] = meta
            entry['next_seq'] = seq + 1
            if promote or entry['champion'] is None:
                entry['previous'] = entry['champion']
                entry['champion'] = vid
            return vid

        vid = self._mutate(apply)
        _m_publishes.inc()
        _LOG.info('registry: published %s@%s (steps %d%s)', line, vid,
                  int(steps), ', promoted' if promote else '')
        return vid

    def promote(self, line: str, version: Any) -> str:
        """Make ``version`` the line's champion — one atomic manifest swap.
        The displaced champion becomes ``previous`` (the rollback target).
        Promoting the current champion is a no-op."""
        vid = str(version)

        def apply(manifest: Dict[str, Any]) -> str:
            entry = manifest['lines'].get(line)
            if entry is None or vid not in entry['versions']:
                raise RegistryError('cannot promote unknown version %s@%s'
                                    % (line, vid))
            if entry['champion'] != vid:
                entry['previous'] = entry['champion']
                entry['champion'] = vid
            return vid

        out = self._mutate(apply)
        _m_promotes.inc()
        _LOG.info('registry: promoted %s@%s to champion', line, vid)
        return out

    def rollback(self, line: str) -> str:
        """Restore the line's previous champion (bit-identically: the
        version's bytes never moved). Returns the restored version id."""
        def apply(manifest: Dict[str, Any]) -> str:
            entry = manifest['lines'].get(line)
            if entry is None:
                raise RegistryError('unknown line %r' % line)
            prev = entry.get('previous')
            if prev is None or prev not in entry['versions']:
                raise RegistryError('line %r has no previous champion to '
                                    'roll back to' % line)
            entry['champion'], entry['previous'] = prev, entry['champion']
            return prev

        out = self._mutate(apply)
        _m_rollbacks.inc()
        _LOG.warning('registry: rolled line %r back to champion %s',
                     line, out)
        return out

    def retire(self, line: str, version: Any):
        """Drop a candidate from the manifest (unpinning it for GC). The
        champion and the rollback target cannot be retired."""
        vid = str(version)

        def apply(manifest: Dict[str, Any]):
            entry = manifest['lines'].get(line)
            if entry is None or vid not in entry['versions']:
                raise RegistryError('cannot retire unknown version %s@%s'
                                    % (line, vid))
            if vid in (entry.get('champion'), entry.get('previous')):
                raise RegistryError('%s@%s is the champion or its rollback '
                                    'target; promote past it first'
                                    % (line, vid))
            del entry['versions'][vid]

        self._mutate(apply)

    # -- resolution --------------------------------------------------------

    def resolve(self, line: str, selector: str = 'champion'
                ) -> Tuple[str, Dict[str, Any]]:
        """(version id, meta) for one ``line@selector``. Raises
        :class:`RegistryError` when the line/selector names nothing."""
        manifest = self._read()
        entry = manifest['lines'].get(line)
        if entry is None:
            raise RegistryError('unknown model line %r' % line)
        selector = str(selector)
        if selector in ('champion', 'previous'):
            vid = entry.get(selector)
            if vid is None:
                raise RegistryError('line %r has no %s' % (line, selector))
        elif selector == 'latest':
            versions = entry['versions']
            if not versions:
                raise RegistryError('line %r has no versions' % line)
            vid = max(versions, key=lambda v: int(versions[v].get('seq', 0)))
        else:
            vid = selector
        meta = entry['versions'].get(vid)
        if meta is None:
            raise RegistryError('unknown version %s@%s' % (line, vid))
        return vid, dict(meta, path=self._abs(meta['path']))

    def load_snapshot(self, line: str, selector: str = 'champion'
                      ) -> Dict[str, Any]:
        """Engine-style snapshot for ``line@selector`` with the version id
        riding along — bytes re-verified against the CRC sidecar on every
        load, so a torn/corrupt serving set raises instead of serving."""
        vid, meta = self.resolve(line, selector)
        data = fs.read_verified_bytes(meta['path'])
        if data is None:
            raise RegistryError(
                'version %s@%s is unverifiable (%s missing, truncated, or '
                'failing its CRC sidecar)' % (line, vid, meta['path']))
        snap = {'architecture': meta['architecture'], 'params': data,
                'version': vid, 'line': line}
        if meta.get('config'):
            snap['config'] = dict(meta['config'])
        return snap

    # -- introspection -----------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        """Manifest summary: per line, the champion/previous pair and every
        live version's metadata (path made absolute)."""
        manifest = self._read()
        out: Dict[str, Any] = {}
        for line, entry in manifest['lines'].items():
            out[line] = {
                'champion': entry.get('champion'),
                'previous': entry.get('previous'),
                'versions': {vid: dict(meta, path=self._abs(meta['path']))
                             for vid, meta in entry['versions'].items()},
            }
        return out

    def pinned_paths(self) -> Set[str]:
        """Absolute checkpoint paths of every live version (champion or
        rolling candidate) across all lines — the retention-GC exclusion
        set."""
        manifest = self._read()
        return {self._abs(meta['path'])
                for entry in manifest['lines'].values()
                for meta in entry['versions'].values()}


def pinned_checkpoint_paths(root: str) -> Optional[Set[str]]:
    """GC-side helper: the registry's pinned paths (empty set when no
    manifest exists under ``root``), or None when a manifest is PRESENT
    but unusable. Never raises — but the None is deliberate: with an
    unreadable manifest the pin set is unknown, so the caller must skip
    retention GC entirely rather than delete a possibly-pinned champion."""
    try:
        return ModelRegistry(root).pinned_paths()
    except RegistryError as exc:
        _LOG.error('registry manifest under %s unusable for GC pinning '
                   '(%s); retention GC is suspended until it is repaired',
                   root, exc)
        return None
