"""Serving fleet: a resolver/router tier over N InferenceService replicas.

PR 10 made inference a product tier, but a single process: one SIGKILL
took it down. This module horizontally replicates it with zero-loss
failover, composing pieces the repo already owns:

* :class:`ServiceResolver` — the control plane. Replicas register and
  heartbeat liveness + a live SLO snapshot (p99, shed, inflight); the
  resolver runs the :class:`~..fault.FleetController` state machine
  (healthy → degraded → draining → quarantined) over them, supervises the
  replica subprocesses it spawned (respawning crashed ones under their old
  name, which re-admits them — the healthy→quarantined→healthy round
  trip), and optionally autoscales: a sustained SLO breach admits a
  standby replica, sustained idleness drains one through the PR 10
  SIGTERM graceful-drain contract (every accepted request answered,
  exit 75).

* :class:`RoutedClient` — the data plane. Same surface as
  :class:`~.client.ServiceClient` but resolves replicas through the
  resolver and carries one :class:`ReplicaBreaker` per replica: a request
  that dials a dead or draining replica opens that breaker and is
  transparently replayed against a healthy one. Requests are pure in
  ``(model@version, obs, seed)`` (the PR 5 contract), so the replayed
  reply is byte-identical — a replica SIGKILL mid-burst is invisible to
  callers. Half-open probes re-admit recovered replicas.

* **Rolling promotes** — ``{'op': 'promote'}`` walks the fleet replica by
  replica, having each one materialize + compile the candidate version
  (the ``warm`` admin op) before the registry champion flips, so the swap
  never blips client p99.

Topology (see docs/serving.md "Serving fleet")::

    clients (RoutedClient / EngineClient / serve:// eval specs)
        │ fleet table + per-replica breakers        control plane
        ▼                                           ┌──────────────┐
    replica r0  replica r1  …  replica rN  ◀──────▶ │ServiceResolver│
    (InferenceService, one registry)  register +    └──────────────┘
                                      heartbeat SLO   │ autoscaler,
                                                      ▼ supervision
                                                  spawn / SIGTERM
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import telemetry
from ..connection import (FramedConnection, Hub, INFER_KIND, TRACE_KEY,
                          open_socket_connection, is_infer)
from ..fault import (Backoff, FleetController, HOST_DEGRADED, HOST_HEALTHY)
from ..guard import PREEMPT_EXIT_CODE, PreemptionGuard
from .client import (SERVE_KIND, ServiceClient, ServiceError,
                     ServiceUnavailable, is_serve, parse_endpoint)
from .registry import ModelRegistry, RegistryError, parse_spec

_LOG = telemetry.get_logger('fleet')

# replica states a router will dispatch to
_ROUTABLE = (HOST_HEALTHY, HOST_DEGRADED)


class ReplicaBreaker:
    """Per-replica circuit breaker: ``closed`` admits requests; a failure
    opens it (probe delay doubling per consecutive failure); once the
    delay elapses ONE half-open probe is admitted — success closes the
    breaker and resets the backoff, failure re-opens it with a longer
    delay. Same shape as the worker EngineClient's breaker, but per
    replica instead of per engine."""

    def __init__(self, initial: float = 0.5, maximum: float = 8.0,
                 clock=time.monotonic, rng=None):
        self._backoff = Backoff(initial=initial, maximum=maximum, rng=rng)
        self._clock = clock
        self.state = 'closed'
        self._probe_at = 0.0
        self._probing = False

    def admits(self) -> bool:
        """May a request be routed here right now? True while closed, and
        for exactly one in-flight probe once the reprobe delay elapsed."""
        if self.state == 'closed':
            return True
        return not self._probing and self._clock() >= self._probe_at

    def begin_probe(self):
        """Mark the half-open probe in flight (call when routing a request
        to an open breaker that ``admits()``)."""
        if self.state != 'closed':
            self._probing = True

    def record_success(self):
        self.state = 'closed'
        self._probing = False
        self._backoff.reset()

    def record_failure(self) -> bool:
        """Open (or re-open) the breaker; True when this call newly opened
        a closed breaker."""
        opened = self.state == 'closed'
        self.state = 'open'
        self._probing = False
        self._probe_at = self._clock() + self._backoff.next_delay()
        return opened


class AutoscalerPolicy:
    """Pure SLO-driven admit/drain policy over heartbeat snapshots.

    ``decide(replicas)`` consumes the resolver's fleet table (state +
    p99_ms/shed/inflight per replica) and returns ``'admit'`` (a sustained
    SLO breach and room below ``max_replicas``), ``'drain'`` (a sustained
    fully-idle fleet above ``min_replicas``), or None. Breach = any
    routable replica over ``slo_p99_ms`` (when set) or shedding since the
    last look. Both conditions must persist (``breach_window`` /
    ``idle_window``) so one slow batch or one quiet second does not thrash
    the fleet. Deterministic and clock-injectable: unit-testable from
    synthetic snapshots."""

    def __init__(self, slo_p99_ms: float = 0.0, breach_window: float = 10.0,
                 idle_window: float = 60.0, min_replicas: int = 1,
                 max_replicas: int = 4, clock=time.monotonic):
        self.slo_p99_ms = float(slo_p99_ms)
        self.breach_window = float(breach_window)
        self.idle_window = float(idle_window)
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self._clock = clock
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_shed: Dict[str, int] = {}

    def decide(self, replicas: List[Dict[str, Any]]) -> Optional[str]:
        now = self._clock()
        routable = [r for r in replicas if r.get('state') in _ROUTABLE]
        shedding = False
        for r in routable:
            name = str(r.get('replica'))
            shed = int(r.get('shed', 0))
            if shed > self._last_shed.get(name, 0):
                shedding = True
            self._last_shed[name] = shed
        if not routable:
            self._breach_since = self._idle_since = None
            return None
        breach = shedding or (
            self.slo_p99_ms > 0.0
            and any(float(r.get('p99_ms', 0.0)) > self.slo_p99_ms
                    for r in routable))
        idle = not breach and all(int(r.get('inflight', 0)) == 0
                                  for r in routable)
        if breach:
            self._idle_since = None
            if self._breach_since is None:
                self._breach_since = now
            if (now - self._breach_since >= self.breach_window
                    and len(routable) < self.max_replicas):
                self._breach_since = None
                return 'admit'
        elif idle:
            self._breach_since = None
            if self._idle_since is None:
                self._idle_since = now
            if (now - self._idle_since >= self.idle_window
                    and len(routable) > self.min_replicas):
                self._idle_since = None
                return 'drain'
        else:
            self._breach_since = self._idle_since = None
        return None


class ServiceResolver:
    """The fleet's control plane: a TCP server speaking the SERVE_KIND
    admin protocol (register / heartbeat / fleet / status / promote /
    drain) over the same Hub machinery as the service, plus a tick thread
    running heartbeat-liveness accounting, the FleetController state
    machine, managed-replica supervision, and the autoscaler.

    ``spawner(name) -> subprocess.Popen`` (set by :func:`resolver_main`,
    or a test) makes a replica *managed*: the resolver respawns it when it
    crashes and SIGTERMs it to drain. Externally-run replicas just
    register and heartbeat; a drain directive rides their heartbeat reply.
    """

    def __init__(self, args: Dict[str, Any],
                 spawner: Optional[Callable[[str], Any]] = None,
                 clock=time.monotonic):
        srv = dict(args.get('serving') or {})
        flt = dict(srv.get('fleet') or {})
        self.host = str(srv.get('host') or '')
        self.port = int(flt.get('port', 0))
        self.metrics_port = int(flt.get('metrics_port') or 0)
        self.default_line = str(srv.get('line', 'default'))
        self.registry_root = str(srv.get('registry_dir')
                                 or args.get('model_dir', 'models'))
        self.lock_timeout = float(srv.get('lock_timeout', 10.0))
        self.heartbeat_timeout = float(flt.get('heartbeat_timeout', 10.0))
        self.autoscale = bool(flt.get('autoscale', False))
        self.max_replicas = max(1, int(flt.get('max_replicas', 4)))
        self.spawner = spawner
        self._clock = clock
        self.policy = AutoscalerPolicy(
            slo_p99_ms=float(flt.get('slo_p99_ms', 0.0)),
            breach_window=float(flt.get('breach_window', 10.0)),
            idle_window=float(flt.get('idle_window', 60.0)),
            min_replicas=int(flt.get('min_replicas', 1)),
            max_replicas=self.max_replicas, clock=clock)

        self._lock = threading.Lock()
        # replica name -> {endpoint, pid, slo, last_beat, drain_wanted}
        self._replicas: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._procs: Dict[str, Any] = {}       # managed  # guarded-by: _lock
        self._respawn_at: Dict[str, float] = {}          # guarded-by: _lock
        self._respawn_backoff: Dict[str, Backoff] = {}   # guarded-by: _lock
        self._next_replica = 0                           # guarded-by: _lock
        # the state machine is driven from both the dispatch thread
        # (register/heartbeat) and the tick thread
        self.controller = FleetController(            # guarded-by: _lock
            degrade_after=1, quarantine_after=1,
            health_window=max(30.0, self.heartbeat_timeout * 6),
            quarantine_period=float(flt.get('quarantine_period', 30.0)),
            clock=clock)

        self._stop = False
        self._sock = None
        self.hub: Optional[Hub] = None
        self._threads: list = []

        self._m_state = lambda replica: telemetry.gauge(
            'fleet_replica_state', replica=replica)
        self._m_transitions = lambda frm, to: telemetry.counter(
            'fleet_replica_transitions_total', **{'from': frm, 'to': to})
        self._m_replicas = telemetry.gauge('fleet_replicas')
        self._m_heartbeats = telemetry.counter('fleet_heartbeats_total')
        self._m_hb_misses = telemetry.counter('fleet_heartbeat_misses_total')
        self._m_admits = telemetry.counter('fleet_autoscale_admits_total')
        self._m_drains = telemetry.counter('fleet_autoscale_drains_total')
        self._m_respawns = telemetry.counter('fleet_respawns_total')
        self._m_promotes = telemetry.counter('fleet_rolling_promotes_total')

        # resolver-side SLO alert engine (heartbeat misses, quarantine
        # flap, shed burn over the merged heartbeat counters), driven from
        # the tick loop and /statusz scrapes
        self._alerts = telemetry.AlertEngine.from_config(args)
        self._exporter = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> 'ServiceResolver':
        self._sock = open_socket_connection(self.port)
        self._sock.listen(64)
        self._sock.settimeout(0.5)
        self.port = self._sock.getsockname()[1]
        self.hub = Hub()
        for target, name in ((self._accept_loop, 'fleet-accept'),
                             (self._dispatch_loop, 'fleet-dispatch'),
                             (self._tick_loop, 'fleet-tick')):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self.metrics_port and telemetry.enabled():
            self._exporter = telemetry.TelemetryExporter(
                lambda: [telemetry.snapshot()], port=self.metrics_port,
                status=self._status_info).start()
            self.metrics_port = self._exporter.port
        _LOG.info('fleet: resolver listening on port %d (registry %s)',
                  self.port, self.registry_root)
        return self

    def stop(self, drain: bool = True):
        """SIGTERM every managed replica (graceful drain, exit 75), wait
        them out, then tear the resolver down."""
        with self._lock:
            procs = dict(self._procs)
        if drain:
            for name, proc in procs.items():
                if proc.poll() is None:
                    _LOG.info('fleet: draining managed replica %r (SIGTERM '
                              'pid %d)', name, proc.pid)
                    try:
                        proc.send_signal(signal.SIGTERM)
                    except OSError:
                        pass
            for name, proc in procs.items():
                try:
                    proc.wait(timeout=60)
                except Exception:
                    _LOG.error('fleet: replica %r did not exit; killing',
                               name)
                    try:
                        proc.kill()
                        proc.wait(timeout=10)
                    except Exception:
                        pass
        else:
            for proc in procs.values():
                try:
                    proc.kill()
                    proc.wait(timeout=10)
                except Exception:
                    pass
        self._stop = True
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        time.sleep(0.25)      # let hub writers flush final replies

    # -- accept / dispatch -------------------------------------------------

    def _accept_loop(self):
        import socket as _socket
        while not self._stop:
            try:
                conn, _addr = self._sock.accept()
            except _socket.timeout:
                continue
            except OSError:
                return
            self.hub.attach(FramedConnection(conn), liveness=0)

    def _dispatch_loop(self):
        import queue as _q
        while not self._stop:
            try:
                ep, msg = self.hub.recv(timeout=0.3)
            except _q.Empty:
                continue
            try:
                if is_serve(msg):
                    self._admin(ep, msg[1] if isinstance(msg[1], dict)
                                else {})
                elif is_infer(msg):
                    body = msg[1] if isinstance(msg[1], dict) else {}
                    # control plane only: inference frames are answered
                    # with an error so a misdirected client fails fast
                    self.hub.send(ep, (INFER_KIND, {
                        'rid': body.get('rid'), 'engine_fault': True,
                        'error': 'resolver is control-plane only; route '
                                 'requests through a RoutedClient or dial '
                                 'a replica endpoint'}))
                else:
                    self.hub.send(ep, (SERVE_KIND,
                                       {'error': 'unknown frame kind'}))
            except Exception as exc:   # noqa: BLE001 — the loop must live
                _LOG.error('fleet: dispatch error (%s: %s)',
                           type(exc).__name__, str(exc)[:200])

    def _admin(self, ep, body: Dict[str, Any]):
        op = body.get('op')
        if op == 'register':
            self._register(ep, body)
        elif op == 'heartbeat':
            self._heartbeat(ep, body)
        elif op == 'fleet':
            self.hub.send(ep, (SERVE_KIND, {'fleet': True,
                                            'replicas': self.fleet_table()}))
        elif op == 'status':
            self.hub.send(ep, (SERVE_KIND, self.stats()))
        elif op == 'promote':
            self._promote_async(ep, str(body.get('model')))
        elif op == 'drain':
            name = str(body.get('replica') or '')
            if self._request_drain(name):
                self.hub.send(ep, (SERVE_KIND, {'ok': True,
                                                'replica': name}))
            else:
                self.hub.send(ep, (SERVE_KIND,
                                   {'error': 'unknown replica %r' % name}))
        else:
            self.hub.send(ep, (SERVE_KIND,
                               {'error': 'unknown admin op %r' % (op,)}))

    # -- registration / heartbeats -----------------------------------------

    def _name_replica(self) -> str:
        with self._lock:
            name = 'r%d' % self._next_replica
            self._next_replica += 1
            return name

    def _register(self, ep, body: Dict[str, Any]):
        endpoint = str(body.get('endpoint') or '')
        if not endpoint:
            self.hub.send(ep, (SERVE_KIND,
                               {'error': 'register carries no endpoint'}))
            return
        name = str(body.get('replica') or '') or self._name_replica()
        now = self._clock()
        with self._lock:
            rec = self._replicas.get(name)
            known = rec is not None
            if rec is None:
                rec = self._replicas[name] = {'slo': {},
                                              'drain_wanted': False}
            rec['endpoint'] = endpoint
            rec['pid'] = int(body.get('pid') or 0)
            rec['last_beat'] = now
            self.controller.observe(name)
            recovered = known and self.controller.state(name) != HOST_HEALTHY
            if recovered:
                # the replica proved itself alive by re-registering (a
                # respawn): re-admit now, don't wait out the quarantine
                self.controller.readmit(name)
        _LOG.info('fleet: replica %r registered at %s (pid %d)%s',
                  name, endpoint, int(body.get('pid') or 0),
                  ' — re-admitted' if recovered else '')
        self._journal()
        self.hub.send(ep, (SERVE_KIND, {'ok': True, 'replica': name}))

    def _heartbeat(self, ep, body: Dict[str, Any]):
        name = str(body.get('replica') or '')
        slo = dict(body.get('slo') or {})
        with self._lock:
            rec = self._replicas.get(name)
            if rec is None:
                known = False
            else:
                known = True
                rec['last_beat'] = self._clock()
                prev_shed = int((rec.get('slo') or {}).get('shed', 0))
                rec['slo'] = slo
                if int(slo.get('shed', 0)) > prev_shed:
                    # shedding load: struggling but alive — a soft fault
                    self.controller.record_soft_fault(name)
                drain = bool(rec['drain_wanted'])
        if not known:
            self.hub.send(ep, (SERVE_KIND,
                               {'error': 'unknown replica %r — register '
                                         'first' % name}))
            return
        self._m_heartbeats.inc()
        self.hub.send(ep, (SERVE_KIND, {'ok': True, 'drain': drain}))

    # -- the tick: liveness, state machine, autoscaler, supervision --------

    def _tick_loop(self):
        while not self._stop:
            try:
                self.tick_once()
            except Exception as exc:   # noqa: BLE001 — the loop must live
                _LOG.error('fleet: tick error (%s: %s)',
                           type(exc).__name__, str(exc)[:200])
            self._sleep(0.25)

    def tick_once(self):
        now = self._clock()
        with self._lock:
            beats = {n: r['last_beat'] for n, r in self._replicas.items()}
        for name, last in beats.items():
            silent = now - last
            with self._lock:
                state = self.controller.state(name)
                if state in _ROUTABLE and silent > self.heartbeat_timeout:
                    self.controller.record_stranding(name)
                    missed = True
                else:
                    missed = False
            if missed:
                self._m_hb_misses.inc()
                _LOG.warning('fleet: replica %r silent for %.1fs '
                             '(heartbeat_timeout %.1fs); draining it',
                             name, silent, self.heartbeat_timeout)
        with self._lock:
            # replicas carry no outstanding book at the resolver (clients
            # replay their own in-flight requests), so draining replicas
            # quarantine on the next tick
            self.controller.tick({})
        if self.autoscale:
            self._autoscale_step()
        self._supervise()
        self._journal()
        if self._alerts is not None:
            self._alerts.maybe_evaluate(lambda: [telemetry.snapshot()])

    def _autoscale_step(self):
        decision = self.policy.decide(self.fleet_table())
        if decision == 'admit':
            if self.spawner is None:
                _LOG.warning('fleet: autoscaler wants a replica admitted '
                             'but no spawner is configured')
                return
            name = self.admit_replica()
            if name:
                self._m_admits.inc()
                _LOG.warning('fleet: SLO breach sustained — admitted '
                             'standby replica %r', name)
        elif decision == 'drain':
            victim = self._drain_victim()
            if victim and self._request_drain(victim):
                self._m_drains.inc()
                _LOG.warning('fleet: fleet idle — draining replica %r',
                             victim)

    def _drain_victim(self) -> Optional[str]:
        """Pick the replica an idle-drain retires: a routable one, managed
        preferred (we can actually stop it), youngest name last-in
        first-out."""
        rows = [r for r in self.fleet_table()
                if r['state'] in _ROUTABLE and not r['draining']]
        if not rows:
            return None
        with self._lock:
            managed = set(self._procs)
        rows.sort(key=lambda r: (r['replica'] in managed, r['replica']))
        return rows[-1]['replica']

    def _request_drain(self, name: str) -> bool:
        with self._lock:
            rec = self._replicas.get(name)
            if rec is None:
                return False
            rec['drain_wanted'] = True
            self.controller.force_drain(name)
            proc = self._procs.get(name)
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)   # graceful drain, exit 75
            except OSError:
                pass
        self._journal()
        return True

    def admit_replica(self) -> Optional[str]:
        """Spawn one managed replica (respecting ``max_replicas``);
        returns its name, or None at capacity / without a spawner."""
        if self.spawner is None:
            return None
        with self._lock:
            if len(self._procs) >= self.max_replicas:
                return None
        name = self._name_replica()
        proc = self.spawner(name)
        with self._lock:
            self._procs[name] = proc
        _LOG.info('fleet: spawned managed replica %r (pid %d)', name,
                  proc.pid)
        return name

    def _supervise(self):
        """Reap/respawn managed replica processes: a deliberate drain is
        retired (forgotten), a crash is respawned under the SAME name
        after a backoff — its re-registration re-admits it."""
        with self._lock:
            procs = dict(self._procs)
        for name, proc in procs.items():
            rc = proc.poll()
            if rc is None:
                continue
            with self._lock:
                rec = self._replicas.get(name)
                wanted = bool(rec and rec.get('drain_wanted'))
                if wanted:
                    self._procs.pop(name, None)
                    self._replicas.pop(name, None)
                    self._respawn_at.pop(name, None)
                    self.controller.forget(name)
                    state_cleared = True
                else:
                    state_cleared = False
                    due = self._respawn_at.get(name)
                    now = self._clock()
                    if due is None:
                        # a reaped corpse IS a stranding: walk the state
                        # machine now (healthy -> draining -> quarantined)
                        # instead of waiting out heartbeat silence — the
                        # respawn's re-registration re-admits it
                        self.controller.record_stranding(name)
                        backoff = self._respawn_backoff.setdefault(
                            name, Backoff(initial=0.2, maximum=5.0))
                        self._respawn_at[name] = now + backoff.next_delay()
                    elif now >= due:
                        self._respawn_at.pop(name, None)
                        self._procs[name] = self.spawner(name)
                        self._m_respawns.inc()
            if state_cleared:
                self._m_state(name).set(-1.0)
                _LOG.info('fleet: replica %r drained and exited %s; '
                          'retired', name, rc)
            elif name not in procs or proc.poll() is not None:
                with self._lock:
                    respawned = (name in self._procs
                                 and self._procs[name] is not proc)
                if respawned:
                    _LOG.warning('fleet: replica %r (exit %s) respawned '
                                 'under its old name', name, rc)

    def _journal(self):
        """Mirror controller transitions onto logs + gauges (the resolver
        is the one place the whole fleet's state is visible)."""
        with self._lock:
            events = self.controller.drain_transitions()
            states = {name: self.controller.state(name)
                      for name in self._replicas}
        for name, frm, to, _t in events:
            _LOG.warning('fleet: replica %s: %s -> %s', name, frm, to)
            self._m_transitions(frm, to).inc()
        for name, state in states.items():
            self._m_state(name).set(
                float(telemetry.HOST_STATE_CODES.get(state, -1)))
        self._m_replicas.set(float(
            sum(1 for s in states.values() if s in _ROUTABLE)))

    def _sleep(self, seconds: float):
        deadline = time.monotonic() + seconds
        while not self._stop and time.monotonic() < deadline:
            time.sleep(min(0.1, max(0.0, deadline - time.monotonic())))

    # -- rolling promote ---------------------------------------------------

    def _promote_async(self, ep, spec: str):
        def run():
            try:
                result = self.rolling_promote(spec)
            except (RegistryError, ServiceError, ServiceUnavailable,
                    RuntimeError, ValueError, TimeoutError) as exc:
                result = {'error': '%s: %s' % (type(exc).__name__, exc)}
            self.hub.send(ep, (SERVE_KIND, result))

        t = threading.Thread(target=run, name='fleet-promote', daemon=True)
        t.start()

    def rolling_promote(self, spec: str) -> Dict[str, Any]:
        """Walk the fleet replica-by-replica: each routable replica warms
        (materializes + compiles) the candidate version, and only then the
        registry champion flips — one atomic manifest swap that every
        replica is already hot for, so client p99 never blips."""
        line, selector = parse_spec(spec)
        registry = ModelRegistry(self.registry_root,
                                 lock_timeout=self.lock_timeout)
        version, _meta = registry.resolve(line, selector)
        warmed = []
        for row in self.fleet_table():
            if row['state'] not in _ROUTABLE:
                continue
            host, port = parse_endpoint(row['endpoint'])
            client = ServiceClient(host, port, timeout=120.0,
                                   name='fleet-promote')
            try:
                rep = client._call_admin(
                    {'op': 'warm', 'model': '%s@%s' % (line, version)},
                    timeout=120.0)
            finally:
                client.close()
            if rep.get('error'):
                raise RuntimeError(
                    'replica %r failed to warm %s@%s: %s — champion NOT '
                    'flipped' % (row['replica'], line, version,
                                 rep['error']))
            warmed.append(row['replica'])
            _LOG.info('fleet: replica %r warmed %s@%s', row['replica'],
                      line, version)
        registry.promote(line, version)
        self._m_promotes.inc()
        _LOG.info('fleet: rolling promote of %s@%s complete (%d replica(s) '
                  'warmed)', line, version, len(warmed))
        return {'ok': True, 'line': line, 'version': version,
                'warmed': warmed}

    # -- introspection -----------------------------------------------------

    def _status_info(self) -> Dict[str, Any]:
        """/statusz payload for the resolver metrics port: per-replica
        states, the routable count, and the fleet-level alert state."""
        with self._lock:
            states = {n: self.controller.state(n) for n in self._replicas}
            slos = {n: dict(r.get('slo') or {})
                    for n, r in self._replicas.items()}
        info: Dict[str, Any] = {
            'fleet_replicas': states,
            'progress': {'replicas': len(states),
                         'routable': sum(1 for s in states.values()
                                         if s in _ROUTABLE)},
            # live per-replica request table (main.py --status renders it)
            'requests': [{'replica': n,
                          'inflight': int(slos[n].get('inflight', 0)),
                          'p50_ms': float(slos[n].get('p50_ms', 0.0)),
                          'p99_ms': float(slos[n].get('p99_ms', 0.0)),
                          'received': int(slos[n].get('received', 0)),
                          'answered': int(slos[n].get('answered', 0)),
                          'draining': bool(slos[n].get('draining'))}
                         for n in sorted(slos)],
        }
        if self._alerts is not None:
            info['alerts'] = self._alerts.maybe_evaluate(
                lambda: [telemetry.snapshot()])
        return info

    def fleet_table(self) -> List[Dict[str, Any]]:
        """The replica table routers consume: name, endpoint, state, and
        the latest heartbeat SLO numbers."""
        with self._lock:
            snap = {n: dict(r) for n, r in self._replicas.items()}
            states = {n: self.controller.state(n) for n in snap}
        out = []
        for name in sorted(snap):
            rec = snap[name]
            slo = rec.get('slo') or {}
            out.append({'replica': name,
                        'endpoint': str(rec.get('endpoint', '')),
                        'pid': int(rec.get('pid', 0)),
                        'state': states[name],
                        'p50_ms': float(slo.get('p50_ms', 0.0)),
                        'p99_ms': float(slo.get('p99_ms', 0.0)),
                        'inflight': int(slo.get('inflight', 0)),
                        'shed': int(slo.get('shed', 0)),
                        'draining': bool(slo.get('draining')
                                         or rec.get('drain_wanted'))})
        return out

    def wait_routable(self, count: int, timeout: float = 120.0) -> bool:
        """Block until ``count`` replicas are registered and routable
        (managed replicas register asynchronously after spawn)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            table = self.fleet_table()
            if sum(1 for r in table if r['state'] in _ROUTABLE) >= count:
                return True
            time.sleep(0.05)
        return False

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            counts = self.controller.counts()
            fc_stats = dict(self.controller.stats)
            managed = sorted(self._procs)
        return {'resolver': True, 'port': self.port,
                'registry': self.registry_root, 'autoscale': self.autoscale,
                'managed': managed, 'counts': counts,
                'controller': fc_stats, 'replicas': self.fleet_table()}


class RoutedClient:
    """Client-side router over the fleet: the :class:`ServiceClient`
    surface (submit/collect/request/status/resolve), but every request is
    dispatched to a routable replica chosen through the resolver's fleet
    table, guarded by one :class:`ReplicaBreaker` per replica, and — on a
    dead-socket, timeout, or draining reply — transparently replayed
    against another replica for a byte-identical answer.

    Thread-safety matches ServiceClient: one submitter at a time per
    instance; concurrent load generators hold one RoutedClient each.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 name: str = '', refresh_interval: float = 2.0):
        self.timeout = float(timeout)
        self.name = name
        self._resolver = ServiceClient(host, int(port), timeout=timeout,
                                       name=name or 'router')
        self._refresh_interval = float(refresh_interval)
        self._lock = threading.Lock()
        self._table: Dict[str, Dict[str, Any]] = {}  # guarded-by: _lock
        self._clients: Dict[str, ServiceClient] = {}  # guarded-by: _lock
        self._breakers: Dict[str, ReplicaBreaker] = {}  # guarded-by: _lock
        self._last_refresh = 0.0
        self._rr = 0          # round-robin cursor
        self._rid = 0
        # rid -> (replica, replica-local rid, request kwargs) for replay
        self._book: Dict[int, Tuple[str, int, Dict[str, Any]]] = {}
        # floating spec -> (concrete line@version, expiry) — see _pin_spec
        self._pins: Dict[str, Tuple[str, float]] = {}  # guarded-by: _lock
        # replica that served the most recent dispatch (submit or replay)
        self.last_replica: Optional[str] = None
        self._m_requests = lambda replica: telemetry.counter(
            'router_requests_total', replica=replica)
        self._m_replays = telemetry.counter('router_replays_total')
        self._m_breaker_opens = telemetry.counter(
            'router_breaker_opens_total')
        self._refresh(force=True)

    def close(self):
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()
        self._resolver.close()

    # -- replica table -----------------------------------------------------

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < self._refresh_interval:
            return
        try:
            reply = self._resolver.fleet(timeout=self.timeout)
        except (ServiceUnavailable, TimeoutError) as exc:
            # keep routing on the stale table; the resolver being down
            # must not take the data plane with it
            _LOG.warning('router: resolver unreachable (%s); keeping the '
                         'stale replica table', exc)
            self._last_refresh = now
            return
        if reply.get('error') or not reply.get('fleet'):
            raise ServiceError(
                'endpoint is not a fleet resolver: %s'
                % (reply.get('error') or reply))
        with self._lock:
            self._table = {str(r['replica']): r
                           for r in reply.get('replicas', [])}
            for gone in set(self._clients) - set(self._table):
                self._clients.pop(gone).close()
            self._last_refresh = now

    def replicas(self) -> List[Dict[str, Any]]:
        self._refresh()     # rate-limited by refresh_interval
        with self._lock:
            return [dict(r) for r in self._table.values()]

    # -- routing -----------------------------------------------------------

    def _candidates(self) -> List[str]:
        """Routable replicas in dispatch order: closed breakers first
        (round-robin), then open breakers due a half-open probe."""
        with self._lock:
            names = [n for n, r in sorted(self._table.items())
                     if r.get('state') in _ROUTABLE
                     and not r.get('draining')]
            closed, probes = [], []
            for n in names:
                b = self._breakers.get(n)
                if b is None or b.state == 'closed':
                    closed.append(n)
                elif b.admits():
                    probes.append(n)
            if closed:
                self._rr = (self._rr + 1) % len(closed)
                closed = closed[self._rr:] + closed[:self._rr]
        return closed + probes

    def _client(self, name: str) -> ServiceClient:
        with self._lock:
            client = self._clients.get(name)
            endpoint = str(self._table[name]['endpoint'])
        if client is None:
            host, port = parse_endpoint(endpoint)
            client = ServiceClient(host, port, timeout=self.timeout,
                                   name=self.name, dial_retries=1,
                                   dial_backoff=0.05)
            with self._lock:
                self._clients[name] = client
        return client

    def _breaker(self, name: str) -> ReplicaBreaker:
        with self._lock:
            b = self._breakers.get(name)
            if b is None:
                b = self._breakers[name] = ReplicaBreaker()
            return b

    def _ok(self, name: str):
        self._breaker(name).record_success()

    def _fail(self, name: str):
        if self._breaker(name).record_failure():
            self._m_breaker_opens.inc()
            _LOG.warning('router: breaker OPEN for replica %r', name)
        with self._lock:
            client = self._clients.pop(name, None)
        if client is not None:
            client.close()

    def _pin_spec(self, spec: str) -> str:
        """Resolve a floating selector (``champion``/``previous``/
        ``latest``) to the concrete ``line@version`` it names right now,
        cached for one refresh interval and invalidated by :meth:`promote`.
        A request stranded mid-promote then replays against the SAME
        version it was first dispatched with — the byte-identity contract
        holds across a champion flip.  Concrete specs pass through;
        resolve failure degrades to the raw spec (each replica resolves
        it locally, as before pinning existed)."""
        line, selector = parse_spec(str(spec))
        if selector not in ('champion', 'previous', 'latest'):
            return str(spec)
        now = time.monotonic()
        with self._lock:
            hit = self._pins.get(spec)
            if hit is not None and hit[1] > now:
                return hit[0]
        try:
            reply = self.resolve(spec, timeout=self.timeout)
        except (ServiceUnavailable, TimeoutError):
            return str(spec)
        if reply.get('error') or reply.get('version') is None:
            return str(spec)
        pinned = '%s@%s' % (reply.get('line') or line, reply['version'])
        with self._lock:
            self._pins[str(spec)] = (pinned, now + self._refresh_interval)
        return pinned

    def _dispatch(self, req: Dict[str, Any],
                  prefer: Optional[str] = None) -> Tuple[str, int]:
        """Send ``req`` to the first admissible replica; (replica, local
        rid). Dial/send failures open that replica's breaker and move on;
        a second pass runs after a forced table refresh.  ``prefer`` moves
        a session-affine replica to the front of the candidate order when
        it is still routable (gateway affinity — never a hard pin)."""
        last: Optional[BaseException] = None
        trace = req.get(TRACE_KEY)
        t0 = time.time()
        for _attempt in range(2):
            names = self._candidates()
            if prefer is not None and prefer in names:
                names.remove(prefer)
                names.insert(0, prefer)
            for name in names:
                breaker = self._breaker(name)
                breaker.begin_probe()
                try:
                    client = self._client(name)
                    sub = client.submit(**req)
                except ServiceUnavailable as exc:
                    last = exc
                    self._fail(name)
                    continue
                self._m_requests(name).inc()
                self.last_replica = name
                if trace:
                    telemetry.trace_event('route_dispatch', ts=t0,
                                          dur=time.time() - t0,
                                          trace_id=trace, replica=name,
                                          breaker=breaker.state)
                return name, sub
            self._refresh(force=True)
        raise ServiceUnavailable(
            'no routable replica accepted the request (%d in table): %s'
            % (len(self._table), last))

    # -- the ServiceClient surface -----------------------------------------

    def submit(self, model: str, obs, hidden=None, legal=None,
               seed=None, replica: Optional[str] = None, trace=None) -> int:
        self._refresh()
        if trace is None and telemetry.trace_enabled():
            trace = telemetry.mint_trace_id()
        req = {'model': self._pin_spec(model), 'obs': obs, 'hidden': hidden,
               'legal': legal, 'seed': seed}
        if trace:
            # booked in the replay request itself, so a failover replay
            # dispatches with — and links to — the ORIGINAL trace id
            req[TRACE_KEY] = trace
        name, sub = self._dispatch(req, prefer=replica)
        with self._lock:
            self._rid += 1
            rid = self._rid
            self._book[rid] = (name, sub, req)
        return rid

    def collect(self, rid: int, timeout: Optional[float] = None
                ) -> Dict[str, Any]:
        with self._lock:
            entry = self._book.pop(rid, None)
        if entry is None:
            raise ValueError('unknown router rid %d' % rid)
        name, sub, req = entry
        try:
            reply = self._client(name).collect(sub, timeout=timeout)
            self._ok(name)
            return reply
        except (ServiceUnavailable, TimeoutError) as exc:
            self._fail(name)
            last: BaseException = exc
        except ServiceError as exc:
            if 'draining' not in str(exc):
                raise           # a real error reply: the service answered
            # a draining replica error-answers everything; it is about to
            # exit — stop routing there and replay elsewhere
            self._fail(name)
            last = exc
        # replay: requests are pure in (model@version, obs, seed), so the
        # reply from another replica is byte-identical
        attempts = max(2, len(self.replicas()) + 1)
        for _attempt in range(attempts):
            t_replay = time.time()
            name2, sub2 = self._dispatch(req)
            self._m_replays.inc()
            if req.get(TRACE_KEY):
                # link span: the replay carries the ORIGINAL trace id, so
                # the SIGKILL reads as one causal chain in the trace
                telemetry.trace_event('router_replay', ts=t_replay,
                                      dur=time.time() - t_replay,
                                      trace_id=req[TRACE_KEY], link='replay',
                                      from_replica=name, to_replica=name2)
            try:
                reply = self._client(name2).collect(sub2, timeout=timeout)
                self._ok(name2)
                return reply
            except (ServiceUnavailable, TimeoutError) as exc:
                self._fail(name2)
                last = exc
            except ServiceError as exc:
                if 'draining' not in str(exc):
                    raise
                self._fail(name2)
                last = exc
        raise ServiceUnavailable(
            'request could not be replayed on any replica: %s' % last) \
            from last

    def request(self, model: str, obs, hidden=None, legal=None, seed=None,
                timeout: Optional[float] = None,
                replica: Optional[str] = None, trace=None) -> Dict[str, Any]:
        return self.collect(self.submit(model, obs, hidden=hidden,
                                        legal=legal, seed=seed,
                                        replica=replica, trace=trace),
                            timeout=timeout)

    def status(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """The resolver's fleet-wide stats."""
        return self._resolver.status(timeout=timeout)

    def promote(self, spec: str, timeout: float = 600.0) -> Dict[str, Any]:
        """Rolling-promote ``line@selector`` across the fleet (blocks
        until every routable replica warmed and the champion flipped)."""
        reply = self._resolver._call_admin({'op': 'promote',
                                           'model': str(spec)},
                                          timeout=timeout)
        with self._lock:
            # the flip just moved every floating selector; drop stale pins
            self._pins.clear()
        return reply

    def resolve(self, spec: str, timeout: Optional[float] = None
                ) -> Dict[str, Any]:
        """Resolve ``line@selector`` against a routable replica."""
        for name in self._candidates():
            try:
                return self._client(name).resolve(spec, timeout=timeout)
            except (ServiceUnavailable, TimeoutError):
                self._fail(name)
        raise ServiceUnavailable('no routable replica to resolve against')


# ---------------------------------------------------------------------------
# the --serve-fleet entrypoint


def _replica_spawner(sargs: Dict[str, Any], resolver: ServiceResolver
                     ) -> Callable[[str], Any]:
    """Build the ``spawner(name)`` closure: one ``python -m
    handyrl_tpu.serving`` subprocess per replica, registering back against
    the resolver under its assigned name (ephemeral port; the register op
    carries the bound endpoint, so the resolver never parses child
    stdout)."""
    srv = dict(sargs.get('serving') or {})
    flt = dict(srv.get('fleet') or {})
    inf = dict(sargs.get('inference') or {})
    env_name = str((sargs.get('env') or {}).get('env', 'TicTacToe'))

    def spawn(name: str):
        cmd = [sys.executable, '-m', 'handyrl_tpu.serving',
               '--env', env_name,
               '--registry', resolver.registry_root,
               '--port', '0',
               '--line', str(srv.get('line', 'default')),
               '--engines', str(int(srv.get('engines', 1))),
               '--max-clients', str(int(srv.get('max_clients', 64))),
               '--drain-timeout', str(float(srv.get('drain_timeout', 30.0))),
               '--resolver', '127.0.0.1:%d' % resolver.port,
               '--replica', name,
               '--heartbeat', str(float(flt.get('heartbeat_interval', 2.0)))]
        if inf.get('batch_wait_ms') is not None:
            cmd += ['--wait-ms', str(float(inf['batch_wait_ms']))]
        if inf.get('max_batch') is not None:
            cmd += ['--max-batch', str(int(inf['max_batch']))]
        return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                cwd=os.getcwd())

    return spawn


def resolver_main(args, argv=None):
    """``main.py --serve-fleet``: resolver + N managed replicas until
    SIGTERM/SIGINT, then a fleet-wide graceful drain (replicas answer
    everything accepted and exit 75) and exit 75 ourselves. Prints one
    JSON ``fleet_ready`` line once every initial replica is routable."""
    sargs = dict(args['train_args'])
    sargs['env'] = dict(args['env_args'])
    srv = dict(sargs.get('serving') or {})
    flt = dict(srv.get('fleet') or {})
    n = int(flt.get('replicas', 2))

    telemetry.adopt_config(sargs)
    telemetry.set_process_label('fleet-resolver')
    telemetry.install_crash_dump()
    guard = PreemptionGuard().install()
    resolver = ServiceResolver(sargs)
    if n > 0 or bool(flt.get('autoscale', False)):
        resolver.spawner = _replica_spawner(sargs, resolver)
    resolver.start()
    for _ in range(n):
        resolver.admit_replica()
    if n and not resolver.wait_routable(n, timeout=180.0):
        _LOG.error('fleet: only %d/%d replicas registered in time',
                   sum(1 for r in resolver.fleet_table()
                       if r['state'] in _ROUTABLE), n)
    print(json.dumps({'fleet_ready': {
        'port': resolver.port, 'pid': os.getpid(), 'replicas': n,
        'registry': os.path.abspath(resolver.registry_root),
        'table': resolver.fleet_table()}}), flush=True)
    try:
        while not guard.requested():
            time.sleep(0.2)
        _LOG.warning('fleet: preemption signal received; draining the '
                     'fleet')
    finally:
        resolver.stop(drain=True)
        guard.uninstall()
    if guard.fired:
        raise SystemExit(PREEMPT_EXIT_CODE)
