"""Standalone service runner: ``python -m handyrl_tpu.serving [flags]``.

The ``main.py --serve`` mode serves whatever ``config.yaml`` describes;
this runner is the harness-friendly flavor (bench.py BENCH_MODE=serve,
scripts/serve_smoke.py, ad-hoc ops): every knob is a flag, defaults come
from the same config layer, and the ready line on stdout carries the bound
ports. Exit code follows the PreemptionGuard contract (75 after a SIGTERM
drain).
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='python -m handyrl_tpu.serving',
        description='standalone handyrl_tpu inference service '
                    '(docs/serving.md)')
    ap.add_argument('--env', default='TicTacToe',
                    help='environment name (builds the example observation '
                         'the engines materialize snapshots against)')
    ap.add_argument('--registry', default='models',
                    help='model-registry root (serving.registry_dir)')
    ap.add_argument('--port', type=int, default=0,
                    help='listen port (0 = ephemeral, reported on the '
                         'ready line)')
    ap.add_argument('--host', default='', help='bind host')
    ap.add_argument('--line', default='default',
                    help='default model line for bare-integer request ids')
    ap.add_argument('--engines', type=int, default=1)
    ap.add_argument('--max-clients', type=int, default=64)
    ap.add_argument('--drain-timeout', type=float, default=30.0)
    ap.add_argument('--metrics-port', type=int, default=0,
                    help='Prometheus /metrics port (0 = exporter off)')
    ap.add_argument('--wait-ms', type=float, default=None,
                    help='override inference.batch_wait_ms')
    ap.add_argument('--max-batch', type=int, default=None,
                    help='override inference.max_batch')
    args = ap.parse_args(argv)

    from ..config import apply_defaults
    from .service import serve_main

    inference = {}
    if args.wait_ms is not None:
        inference['batch_wait_ms'] = float(args.wait_ms)
    if args.max_batch is not None:
        inference['max_batch'] = int(args.max_batch)
    cfg = apply_defaults({
        'env_args': {'env': args.env},
        'train_args': {
            'inference': inference,
            'serving': {
                'port': args.port, 'host': args.host, 'line': args.line,
                'registry_dir': args.registry, 'engines': args.engines,
                'max_clients': args.max_clients,
                'drain_timeout': args.drain_timeout,
                'metrics_port': args.metrics_port,
            },
        },
    })
    serve_main(cfg, [])
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
