"""Standalone service runner: ``python -m handyrl_tpu.serving [flags]``.

The ``main.py --serve`` mode serves whatever ``config.yaml`` describes;
this runner is the harness-friendly flavor (bench.py BENCH_MODE=serve,
scripts/serve_smoke.py, ad-hoc ops): every knob is a flag, defaults come
from the same config layer, and the ready line on stdout carries the bound
ports. Exit code follows the PreemptionGuard contract (75 after a SIGTERM
drain).
"""

from __future__ import annotations

import argparse


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='python -m handyrl_tpu.serving',
        description='standalone handyrl_tpu inference service '
                    '(docs/serving.md)')
    ap.add_argument('--env', default='TicTacToe',
                    help='environment name (builds the example observation '
                         'the engines materialize snapshots against)')
    ap.add_argument('--registry', default='models',
                    help='model-registry root (serving.registry_dir)')
    ap.add_argument('--port', type=int, default=0,
                    help='listen port (0 = ephemeral, reported on the '
                         'ready line)')
    ap.add_argument('--host', default='', help='bind host')
    ap.add_argument('--line', default='default',
                    help='default model line for bare-integer request ids')
    ap.add_argument('--engines', type=int, default=1)
    ap.add_argument('--max-clients', type=int, default=64)
    ap.add_argument('--drain-timeout', type=float, default=30.0)
    ap.add_argument('--metrics-port', type=int, default=0,
                    help='Prometheus /metrics port (0 = exporter off)')
    ap.add_argument('--wait-ms', type=float, default=None,
                    help='override inference.batch_wait_ms')
    ap.add_argument('--max-batch', type=int, default=None,
                    help='override inference.max_batch')
    ap.add_argument('--engine-backend', default=None,
                    choices=('cpu', 'device'),
                    help='override inference.engine_backend (device lets '
                         'the engines claim a host-local accelerator)')
    # fleet membership (replica mode): register + heartbeat against a
    # resolver; a resolver-directed drain exits 75 like a SIGTERM drain
    ap.add_argument('--resolver', default='',
                    help='fleet resolver endpoint (host:port) to register '
                         'against (serving.fleet.resolver)')
    ap.add_argument('--replica', default='',
                    help='fleet replica name to register under (default: '
                         'resolver-assigned)')
    ap.add_argument('--heartbeat', type=float, default=None,
                    help='override serving.fleet.heartbeat_interval')
    ap.add_argument('--heartbeat-timeout', type=float, default=None,
                    help='override serving.fleet.heartbeat_timeout')
    # resolver mode: run the fleet control plane + managed replicas
    ap.add_argument('--fleet', action='store_true',
                    help='run a fleet resolver (+ --replicas managed '
                         'replica subprocesses) instead of one service')
    ap.add_argument('--replicas', type=int, default=None,
                    help='managed replicas the resolver spawns '
                         '(serving.fleet.replicas)')
    ap.add_argument('--min-replicas', type=int, default=None)
    ap.add_argument('--max-replicas', type=int, default=None)
    ap.add_argument('--autoscale', action='store_true',
                    help='enable the SLO-driven autoscaler')
    ap.add_argument('--slo-p99-ms', type=float, default=None,
                    help='autoscaler p99 latency target '
                         '(serving.fleet.slo_p99_ms)')
    # gateway mode: match gateway over an existing fleet resolver
    ap.add_argument('--gateway', action='store_true',
                    help='run a match gateway (server-held game sessions '
                         'over a fleet resolver) instead of a service')
    ap.add_argument('--gateway-model', default=None,
                    help='default opponent model spec '
                         '(serving.gateway.model)')
    ap.add_argument('--gateway-workers', type=int, default=None,
                    help='session worker threads '
                         '(serving.gateway.workers)')
    ap.add_argument('--max-sessions', type=int, default=None,
                    help='admission-control ceiling '
                         '(serving.gateway.max_sessions)')
    ap.add_argument('--ply-timeout', type=float, default=None,
                    help='per-ply inference deadline '
                         '(serving.gateway.ply_timeout)')
    ap.add_argument('--seed', type=int, default=None,
                    help='base seed for audited per-session env seeds')
    args = ap.parse_args(argv)

    from ..config import apply_defaults

    inference = {}
    if args.wait_ms is not None:
        inference['batch_wait_ms'] = float(args.wait_ms)
    if args.max_batch is not None:
        inference['max_batch'] = int(args.max_batch)
    if args.engine_backend is not None:
        inference['engine_backend'] = args.engine_backend
    fleet = {}
    gateway = {}
    if args.gateway:
        gateway['port'] = args.port
        gateway['metrics_port'] = args.metrics_port
        if args.resolver:
            gateway['resolver'] = args.resolver
        if args.gateway_model is not None:
            gateway['model'] = args.gateway_model
        if args.gateway_workers is not None:
            gateway['workers'] = int(args.gateway_workers)
        if args.max_sessions is not None:
            gateway['max_sessions'] = int(args.max_sessions)
        if args.ply_timeout is not None:
            gateway['ply_timeout'] = float(args.ply_timeout)
    if args.resolver:
        fleet['resolver'] = args.resolver
    if args.replica:
        fleet['replica'] = args.replica
    if args.heartbeat is not None:
        fleet['heartbeat_interval'] = float(args.heartbeat)
    if args.heartbeat_timeout is not None:
        fleet['heartbeat_timeout'] = float(args.heartbeat_timeout)
    if args.fleet:
        fleet['port'] = args.port
        if args.replicas is not None:
            fleet['replicas'] = int(args.replicas)
        if args.min_replicas is not None:
            fleet['min_replicas'] = int(args.min_replicas)
        if args.max_replicas is not None:
            fleet['max_replicas'] = int(args.max_replicas)
        if args.autoscale:
            fleet['autoscale'] = True
        if args.slo_p99_ms is not None:
            fleet['slo_p99_ms'] = float(args.slo_p99_ms)
    train_args = {
        'inference': inference,
        'serving': {
            'port': args.port, 'host': args.host, 'line': args.line,
            'registry_dir': args.registry, 'engines': args.engines,
            'max_clients': args.max_clients,
            'drain_timeout': args.drain_timeout,
            'metrics_port': args.metrics_port,
            'fleet': fleet,
            'gateway': gateway,
        },
    }
    if args.gateway:
        # gateway binds its own port; keep the service-layer port at the
        # argparse default so validate() does not see a double booking
        train_args['serving']['port'] = 0
        if args.seed is not None:
            train_args['seed'] = int(args.seed)
    cfg = apply_defaults({
        'env_args': {'env': args.env},
        'train_args': train_args,
    })
    if args.gateway:
        from .gateway import gateway_main
        gateway_main(cfg, [])
    elif args.fleet:
        from .fleet import resolver_main
        resolver_main(cfg, [])
    else:
        from .service import serve_main
        serve_main(cfg, [])
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
