"""Client side of the serving tier: framed-protocol transport + model proxies.

:class:`ServiceClient` owns one TCP connection to an
:class:`~.service.InferenceService` and speaks the same framed msgpack
``INFER_KIND`` protocol the worker<->gather pipes use, plus the
``SERVE_KIND`` admin frames (status / resolve). :class:`RemoteServiceModel`
wraps a client + a ``line@selector`` spec into the model surface the
evaluation agents dispatch on (``inference`` / ``init_hidden`` / ``act``),
so a match harness resolves models by name against the engine fleet
instead of holding params.

Reply canonicalization: scalar floats degrade to python floats across the
msgpack hop (the wire codec converts numpy scalars); ``act`` re-wraps the
sampled probability as ``np.float32`` so records built from service
replies stay byte-identical to locally-computed ones (the PR 5 contract).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import telemetry
from ..connection import (INFER_KIND, TRACE_KEY, connect_socket_connection,
                          is_infer)
from ..fault import Backoff

_LOG = telemetry.get_logger('serving')

# transport-layer exceptions that mean "the socket died", as opposed to a
# service-sent error frame (ValueError covers framing-layer corruption)
_TRANSPORT_ERRORS = (OSError, ConnectionError, EOFError, ValueError)

# Admin frames on a service connection (status / resolve / drain probes).
# Rides next to INFER_KIND; the Hub passes both through untyped.
SERVE_KIND = '__serve__'


def is_serve(msg) -> bool:
    """True for a serving-tier admin frame (request or reply)."""
    return (isinstance(msg, (list, tuple)) and len(msg) == 2
            and msg[0] == SERVE_KIND)


def parse_endpoint(endpoint: str) -> Tuple[str, int]:
    """``'host:port'`` -> (host, port); a bare port means localhost."""
    host, _, port = str(endpoint).rpartition(':')
    return host or 'localhost', int(port)


def canonicalize_reply(reply: Dict[str, Any]) -> Dict[str, Any]:
    """Restore the scalar dtype the engine computed: the wire codec turns
    ``np.float32`` scalars into python floats, and a record storing the
    python float would pickle to different bytes than the local path's."""
    if isinstance(reply.get('prob'), float):
        reply['prob'] = np.float32(reply['prob'])
    return reply


class ServiceError(RuntimeError):
    """The service answered a request with an error reply."""


class ServiceUnavailable(RuntimeError):
    """Transport-level failure: the service could not be dialed, or the
    socket died before a reply landed. DISTINCT from :class:`ServiceError`
    (the service itself answered with an error frame): an unavailable
    service never saw — or never answered — the request, and because
    requests are pure in ``(model@version, obs, seed)`` the caller (or the
    fleet router) may safely replay it against another replica for a
    byte-identical reply."""


class ServiceClient:
    """One client connection to an InferenceService endpoint.

    ``submit``/``collect`` split (so simultaneous requests pipeline into
    one engine batch, like the worker's act_send/act_recv); ``request`` is
    the one-shot convenience. Thread-safe for one submitter at a time per
    instance — concurrent load generators should hold one client each.

    Dialing retries ``dial_retries`` times with jittered backoff before
    raising :class:`ServiceUnavailable` (a restarting replica's listen
    socket is down for tens of milliseconds; callers should not crash on
    that). A socket that dies later surfaces as :class:`ServiceUnavailable`
    from ``submit``/``collect``; the next ``submit`` redials.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 name: str = '', dial_retries: int = 3,
                 dial_backoff: float = 0.2):
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self.name = name
        self.dial_retries = max(0, int(dial_retries))
        self.dial_backoff = float(dial_backoff)
        self.conn = None
        self._rid = 0
        self._box: Dict[int, Dict[str, Any]] = {}   # rid -> early reply
        self._admin: deque = deque()                # out-of-band serve frames
        self._traces: Dict[int, Tuple[Any, float]] = {}   # rid -> (tid, t0)
        self._lock = threading.Lock()
        self._connect()

    def _connect(self):
        backoff = Backoff(initial=self.dial_backoff, maximum=2.0)
        last: Optional[BaseException] = None
        for attempt in range(self.dial_retries + 1):
            try:
                self.conn = connect_socket_connection(self.host, self.port)
                return
            except _TRANSPORT_ERRORS as exc:
                last = exc
                if attempt < self.dial_retries:
                    time.sleep(backoff.next_delay())
        self.conn = None
        raise ServiceUnavailable(
            'cannot dial service %s:%d after %d attempt(s): %s'
            % (self.host, self.port, self.dial_retries + 1, last))

    def _drop(self, why: BaseException) -> ServiceUnavailable:
        """Close the dead socket and build the exception to raise; replies
        in flight on it are gone (the rid book dies with the socket)."""
        self.close()
        return ServiceUnavailable(
            'connection to service %s:%d lost: %s' % (self.host, self.port,
                                                      why))

    def close(self):
        conn, self.conn = self.conn, None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    # -- request path ------------------------------------------------------

    def submit(self, model: str, obs, hidden=None, legal=None,
               seed=None, trace=None) -> int:
        """Post one inference request for ``model`` (a ``line@selector``
        spec); returns its request id.

        ``trace`` is the serving-path trace context: the caller's id when
        one exists (router replays, gateway plies), else a fresh one is
        minted here — the request edge — whenever tracing is on. It rides
        in the body under ``TRACE_KEY`` so every downstream hop stamps the
        same id; the matching ``client_request`` span closes in
        :meth:`collect`."""
        if trace is None and telemetry.trace_enabled():
            trace = telemetry.mint_trace_id()
        with self._lock:
            self._rid += 1
            rid = self._rid
        body: Dict[str, Any] = {'rid': rid, 'model': str(model), 'obs': obs}
        if self.name:
            body['client'] = self.name
        if hidden is not None:
            body['hidden'] = hidden
        if legal is not None:
            body['legal'] = [int(a) for a in legal]
        if seed is not None:
            body['seed'] = [int(s) for s in seed]
        if trace:
            body[TRACE_KEY] = trace
            self._traces[rid] = (trace, time.time())  # graftlint: allow[GL001] wall-clock span timestamp for the Chrome trace only — never enters the reply or any episode record
        self._send((INFER_KIND, body))
        return rid

    def collect(self, rid: int, timeout: Optional[float] = None
                ) -> Dict[str, Any]:
        """The reply for ``rid`` (raises :class:`ServiceError` on an error
        reply, TimeoutError past the deadline)."""
        ctx = self._traces.pop(rid, None)
        if rid in self._box:
            reply = self._box.pop(rid)
        else:
            reply = self._await(lambda m: (is_infer(m)
                                           and m[1].get('rid') == rid),
                                timeout)
            if reply is None:
                raise TimeoutError('no service reply for rid %d within '
                                   '%.1fs' % (rid, timeout or self.timeout))
            reply = reply[1]
        if reply.get('error'):
            raise ServiceError(str(reply['error']))
        if ctx is not None:
            tid, t0 = ctx
            telemetry.trace_event('client_request', ts=t0,
                                  dur=time.time() - t0, trace_id=tid,  # graftlint: allow[GL001] wall-clock span duration for the Chrome trace only — never enters the reply or any episode record
                                  rid=rid, client=self.name or '')
        return canonicalize_reply(reply)

    def trace_of(self, rid: int):
        """The trace context minted for an in-flight ``rid`` (None when
        unsampled or already collected) — the router reads it to link
        replay spans to the original id."""
        ctx = self._traces.get(rid)
        return ctx[0] if ctx else None

    def request(self, model: str, obs, hidden=None, legal=None, seed=None,
                trace=None, timeout: Optional[float] = None
                ) -> Dict[str, Any]:
        return self.collect(self.submit(model, obs, hidden=hidden,
                                        legal=legal, seed=seed, trace=trace),
                            timeout=timeout)

    # -- admin frames ------------------------------------------------------

    def _call_admin(self, body: Dict[str, Any],
                    timeout: Optional[float] = None) -> Dict[str, Any]:
        self._send((SERVE_KIND, body))
        reply = self._await(is_serve, timeout)
        if reply is None:
            raise TimeoutError('no %r reply from the service'
                               % body.get('op'))
        return reply[1]

    def call_admin(self, body: Dict[str, Any],
                   timeout: Optional[float] = None) -> Dict[str, Any]:
        """Public admin round-trip: send one SERVE_KIND frame, await the
        reply body. The gateway client drives its whole session protocol
        (open/play/close) through this — admin frames interleave safely
        with in-flight inference replies (see :meth:`_await`)."""
        return self._call_admin(dict(body), timeout)

    def status(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """The service's live stats: lines/champions, request counters,
        drain state."""
        return self._call_admin({'op': 'status'}, timeout)

    def resolve(self, spec: str, timeout: Optional[float] = None
                ) -> Dict[str, Any]:
        """Ask the service what ``line@selector`` currently names."""
        return self._call_admin({'op': 'resolve', 'model': str(spec)},
                                timeout)

    def fleet(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """The fleet replica table (a resolver answers it; a plain service
        answers an unknown-op error body — see ``model_from_spec``)."""
        return self._call_admin({'op': 'fleet'}, timeout)

    # -- internals ---------------------------------------------------------

    def _send(self, msg):
        """Frame out one message, redialing a previously-dropped socket;
        transport death raises :class:`ServiceUnavailable` (retryable)."""
        if self.conn is None:
            self._connect()
        try:
            self.conn.send(msg)
        except _TRANSPORT_ERRORS as exc:
            raise self._drop(exc)

    def _await(self, want, timeout: Optional[float]):
        """Next frame matching ``want``; early inference replies are boxed,
        stray admin frames queued. None on deadline; a dead socket raises
        :class:`ServiceUnavailable` (retryable), never a raw OSError."""
        if want is is_serve and self._admin:
            return (SERVE_KIND, self._admin.popleft())
        if self.conn is None:
            raise ServiceUnavailable(
                'connection to service %s:%d is down (pending replies died '
                'with it)' % (self.host, self.port))
        deadline = time.monotonic() + (self.timeout if timeout is None
                                       else float(timeout))
        while True:
            remaining = deadline - time.monotonic()
            try:
                if remaining <= 0 or not self.conn.poll(remaining):
                    return None
                msg = self.conn.recv()
            except _TRANSPORT_ERRORS as exc:
                raise self._drop(exc)
            if want(msg):
                return msg
            if is_infer(msg) and isinstance(msg[1], dict):
                rid = msg[1].get('rid')
                if rid is not None:
                    self._box[rid] = msg[1]
                continue
            if is_serve(msg) and isinstance(msg[1], dict):
                self._admin.append(msg[1])


class RemoteServiceModel:
    """Model-surface proxy over a :class:`ServiceClient`: calls become
    request frames against one ``line@selector`` spec. ``init_hidden``
    returns None by design — the engine substitutes a fresh initial state
    for a None hidden, so the client needs no knowledge of the recurrent
    state's structure (same contract as the in-Gather RemoteModel)."""

    def __init__(self, client: ServiceClient, model: str):
        self.client = client
        self.model = str(model)

    def init_hidden(self, batch_shape=None):
        return None

    def inference(self, obs, hidden=None) -> Dict[str, Any]:
        return self.client.request(self.model, obs, hidden=hidden)['outputs']

    def act(self, obs, hidden, legal_actions, seed_seq) -> Dict[str, Any]:
        return self.client.request(self.model, obs, hidden=hidden,
                                   legal=legal_actions, seed=seed_seq)

    def close(self):
        self.client.close()


def model_from_spec(spec: str, timeout: float = 10.0) -> RemoteServiceModel:
    """``'serve://host:port/line@selector'`` -> a connected proxy model
    (owning its client connection).

    The endpoint may name either a single service or a fleet resolver: one
    ``fleet`` probe at connect time (a plain service answers an unknown-op
    error body) decides, and a resolver endpoint gets a
    :class:`~.fleet.RoutedClient` — so eval ``serve://`` specs transparently
    gain replica failover when pointed at a resolver."""
    rest = str(spec)
    if rest.startswith('serve://'):
        rest = rest[len('serve://'):]
    endpoint, _, model = rest.partition('/')
    if not model:
        raise ValueError('serve:// spec %r carries no line@selector path'
                         % spec)
    host, port = parse_endpoint(endpoint)
    client = ServiceClient(host, port, timeout=timeout)
    try:
        probe = client.fleet(timeout=min(timeout, 5.0))
    except (TimeoutError, ServiceUnavailable):
        probe = {}
    if probe.get('fleet'):
        client.close()
        from .fleet import RoutedClient   # lazy: fleet imports this module
        return RemoteServiceModel(RoutedClient(host, port, timeout=timeout),
                                  model)
    return RemoteServiceModel(client, model)
