"""The InferenceService: a long-lived model-serving process.

One process hosts one or more supervised :class:`~.inference.InferenceEngine`
fleets behind a TCP listener speaking the framed ``INFER_KIND`` protocol
(the exact frames engine-mode workers already emit), so eval servers,
league matches, worker fleets with ``serving.endpoint`` configured, and
external match traffic all hit one engine tier instead of each run growing
its own. Requests name models by ``line@selector`` against the
:class:`~.registry.ModelRegistry`; a promote flips what ``@champion``
resolves to between one tick and the next with zero failed requests.

Pieces:

* **Continuous batching** — requests from every connected client coalesce
  in the engine's intake queue (quiescence early-dispatch +
  ``inference.batch_wait_ms`` deadline + ``inference.max_batch`` cap,
  power-of-two row padding), one ``batch_inference`` per tick. Multiple
  engines (``serving.engines``) partition the model space so two lines
  never serialize behind each other's forwards.

* **Admission control, shed on overload** — a connection past
  ``serving.max_clients`` is refused with an error frame
  (``serve_shed_total``); a request past the engine's bounded intake queue
  is shed with an immediate error reply (``engine_shed_total``). Nothing
  queues without bound, nothing is dropped silently.

* **SLO telemetry** — per-client/per-model request-latency histograms
  (``serve_request_seconds{client=,model=}`` → p50/p95/p99), request and
  error counters, live in-flight/clients gauges, all in the process
  registry and on ``GET /metrics`` (``serving.metrics_port``).

* **Graceful drain** — SIGTERM (the PR 4 :class:`~.guard.PreemptionGuard`
  contract) stops admission, answers every request already accepted (new
  arrivals get an immediate ``draining`` error reply — answered, never
  dropped), waits out the engines up to ``serving.drain_timeout``, then
  exits 75 (EX_TEMPFAIL: supervisor, restart me). A service restart
  re-reads the registry manifest and recovers the exact serving set.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

from .. import telemetry
from ..connection import (FramedConnection, Hub, open_socket_connection,
                          connect_socket_connection, is_infer)
from ..connection import INFER_KIND, TRACE_KEY
from ..fault import Backoff
from ..guard import PREEMPT_EXIT_CODE, PreemptionGuard
from .client import SERVE_KIND, is_serve, parse_endpoint
from .registry import ModelRegistry, RegistryError, parse_spec

_LOG = telemetry.get_logger('serving')


def ring_percentile_ms(lats, q: float) -> float:
    """Nearest-rank percentile of a latency ring (seconds), in ms — the
    one SLO-snapshot definition shared by the service heartbeat and the
    gateway's per-ply latency gauge, so 'p99_ms' means the same thing on
    every surface."""
    if not lats:
        return 0.0
    lats = sorted(lats)
    return 1e3 * lats[int(round((len(lats) - 1) * float(q)))]


class _WarmSink:
    """Reply endpoint for synthetic warm-up requests (the rolling-promote
    walk): the engine's reply lands here instead of a client socket, so a
    replica can materialize + compile a model version end-to-end before the
    champion flips to it."""

    def __init__(self):
        self.done = threading.Event()
        self.reply: Dict[str, Any] = {}

    def deliver(self, msg: Dict[str, Any]):
        self.reply = msg or {}
        self.done.set()


class InferenceService:
    """One serving process: listener + Hub + registry-backed engine fleet.

    ``args`` is a train_args-style dict carrying an ``env`` block (the
    Gather convention): the env builds the example observation the engines
    materialize snapshots against; the ``serving`` and ``inference`` blocks
    carry the knobs. ``start()`` binds and spins the accept/dispatch
    threads; ``stop()`` drains and tears down. The service holds no
    per-episode state — clients may connect, crash, and reconnect at any
    ply (recurrent hidden state rides the requests, as in the worker tier).
    """

    def __init__(self, args: Dict[str, Any],
                 registry: Optional[ModelRegistry] = None):
        srv = dict(args.get('serving') or {})
        self._args = args
        self.host = str(srv.get('host') or '')
        self.port = int(srv.get('port', 9997))
        self.default_line = str(srv.get('line', 'default'))
        self.max_clients = max(1, int(srv.get('max_clients', 64)))
        self.drain_timeout = max(0.1, float(srv.get('drain_timeout', 30.0)))
        self.engines_n = max(1, int(srv.get('engines', 1)))
        self.metrics_port = int(srv.get('metrics_port') or 0)
        root = srv.get('registry_dir') or args.get('model_dir', 'models')
        self.registry = registry if registry is not None \
            else ModelRegistry(root,
                               lock_timeout=float(srv.get('lock_timeout',
                                                          10.0)))
        flt = dict(srv.get('fleet') or {})
        self.resolver_endpoint = str(flt.get('resolver') or '')
        self.replica_name = str(flt.get('replica') or '')
        self.advertise_host = str(flt.get('advertise') or '')
        self.heartbeat_interval = max(0.05,
                                      float(flt.get('heartbeat_interval',
                                                    2.0)))

        env = None
        self._example_obs = None
        if args.get('env'):
            from ..environment import make_env
            env = make_env(dict(args['env']))
            env.reset()
            self._example_obs = env.observation(env.players()[0])

        self._lock = threading.Lock()
        # (line, version) <-> engine-facing integer model handle; appended
        # by the dispatch thread, read by engine threads' snapshot fetches
        self._handles: Dict[Tuple[str, str], int] = {}   # guarded-by: _lock
        self._handle_meta: Dict[int, Tuple[str, str]] = {}  # guarded-by: _lock
        # (endpoint id, rid) -> (t0, model label, client label); written at
        # submit (dispatch thread), popped at reply (engine threads)
        self._pending: Dict[Tuple[int, Any], tuple] = {}  # guarded-by: _lock
        # recent request latencies (s) feeding the heartbeat SLO snapshot
        self._lat_ring: deque = deque(maxlen=512)         # guarded-by: _lock
        self._draining = False
        self._fleet_drain = False   # resolver told us to drain (autoscaler)
        self._stop = False
        self._sock: Optional[socket.socket] = None
        self.hub: Optional[Hub] = None
        self.engines: list = []
        self._exporter = None
        self._threads: list = []
        self.received = 0
        self.answered = 0
        self.refused = 0      # connections shed by the admission gate

        self._m_requests = lambda model, client: telemetry.counter(
            'serve_requests_total', model=model, client=client)
        self._m_latency = lambda model, client: telemetry.REGISTRY.histogram(
            'serve_request_seconds', model=model, client=client)
        self._m_errors = lambda reason: telemetry.counter(
            'serve_errors_total', reason=reason)
        self._m_shed = telemetry.counter('serve_shed_total')
        self._m_clients = telemetry.gauge('serve_clients')
        self._m_inflight = telemetry.gauge('serve_inflight')
        self._m_draining = telemetry.gauge('serve_draining')
        # SLO alert engine over this replica's own registry (shed burn
        # rate, heartbeat misses); evaluated on /statusz scrapes and the
        # heartbeat loop through one cadence-gated stream
        self._alerts = telemetry.AlertEngine.from_config(args)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> 'InferenceService':
        from ..inference import EngineSupervisor
        self._sock = open_socket_connection(self.port)
        self._sock.listen(self.max_clients + 8)
        self._sock.settimeout(0.5)
        self.port = self._sock.getsockname()[1]   # resolve port 0
        self.hub = Hub()
        self.engines = [
            EngineSupervisor(self._args, fetch_snapshot=self._fetch,
                             reply_fn=self._reply, clients=None,
                             example_obs=self._example_obs)
            for _ in range(self.engines_n)]
        if self.metrics_port and telemetry.enabled():
            self._exporter = telemetry.TelemetryExporter(
                lambda: [telemetry.snapshot()], port=self.metrics_port,
                status=self._status_info
            ).start()
            self.metrics_port = self._exporter.port
        loops = [(self._accept_loop, 'serve-accept'),
                 (self._dispatch_loop, 'serve-dispatch')]
        if self.resolver_endpoint:
            loops.append((self._fleet_loop, 'serve-heartbeat'))
        for target, name in loops:
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        _LOG.info('inference service listening on port %d (%d engine(s), '
                  'registry %s)', self.port, self.engines_n,
                  self.registry.root)
        return self

    def request_drain(self):
        """Begin graceful drain: no new work is admitted; everything
        already accepted is answered."""
        if not self._draining:
            self._draining = True
            self._m_draining.set(1.0)
            _LOG.warning('serving: drain requested — answering %d in-flight '
                         'request(s), refusing new work', self.inflight())

    def drained(self) -> bool:
        with self._lock:
            pending = bool(self._pending)
        return not pending

    def stop(self, drain: bool = True):
        """Drain (bounded by ``serving.drain_timeout``), then tear down the
        listener, engines, and exporter."""
        if drain:
            self.request_drain()
            deadline = time.monotonic() + self.drain_timeout
            while not self.drained() and time.monotonic() < deadline:
                time.sleep(0.02)
            if not self.drained():
                _LOG.error('serving: drain timeout (%.1fs) with %d '
                           'request(s) still unanswered',
                           self.drain_timeout, self.inflight())
        self._stop = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for engine in self.engines:
            engine.stop()
        # give the Hub's per-endpoint writers a beat to flush the final
        # replies out of their outboxes before the process goes away
        time.sleep(0.25)
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None

    # -- accept / admission ------------------------------------------------

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return            # listener closed: shutting down
            ep = FramedConnection(conn)
            if self.hub.count() >= self.max_clients:
                # admission control: refuse loudly instead of queueing a
                # client the engines cannot keep up with
                self.refused += 1
                self._m_shed.inc()
                try:
                    ep.send((SERVE_KIND,
                             {'error': 'service full (%d clients)'
                                       % self.max_clients}))
                finally:
                    ep.close()
                continue
            # clients may idle between matches: disable the silent-peer
            # deadline (dead sockets still detach on read/write errors)
            self.hub.attach(ep, liveness=0)
            self._m_clients.set(self.hub.count())

    # -- dispatch ----------------------------------------------------------

    def _dispatch_loop(self):
        import queue as _q
        while not self._stop:
            try:
                ep, msg = self.hub.recv(timeout=0.3)
            except _q.Empty:
                self._m_clients.set(self.hub.count())
                continue
            try:
                if is_infer(msg):
                    body = msg[1] if isinstance(msg[1], dict) else {}
                    self._submit(ep, body)
                elif is_serve(msg):
                    body = msg[1] if isinstance(msg[1], dict) else {}
                    self._admin(ep, body)
                else:
                    self.hub.send(ep, (SERVE_KIND,
                                       {'error': 'unknown frame kind'}))
            except Exception as exc:   # noqa: BLE001 — the loop must live
                _LOG.error('serving: dispatch error (%s: %s)',
                           type(exc).__name__, str(exc)[:200])

    def _client_label(self, ep, body: Dict[str, Any]) -> str:
        name = body.get('client')
        if name:
            return str(name)[:64]
        sock = getattr(ep, 'sock', None)
        try:
            peer = sock.getpeername()
            return '%s:%s' % peer[:2]
        except (OSError, AttributeError, TypeError):
            return 'unknown'

    def _error_reply(self, ep, body: Dict[str, Any], reason: str,
                     error: str):
        """Answer a request the service itself rejects (resolve failure,
        drain, missing fields): counted, tagged as an engine fault so
        worker clients fail over, and always SENT — a rejected request is
        still an answered request."""
        self._m_errors(reason).inc()
        self.answered += 1
        self.hub.send(ep, (INFER_KIND, {'rid': body.get('rid'),
                                        'engine_fault': True,
                                        'error': error}))

    def _submit(self, ep, body: Dict[str, Any]):
        self.received += 1
        if self._draining:
            self._error_reply(ep, body, 'draining',
                              'service draining (restart imminent)')
            return
        spec = body.get('model')
        try:
            if spec is not None:
                line, selector = parse_spec(str(spec))
            elif body.get('mid') is not None:
                # bare integer ids resolve as versions of the default line
                # (the worker EngineClient convention: version == epoch)
                line, selector = self.default_line, str(int(body['mid']))
            else:
                raise RegistryError('request names no model (neither '
                                    "'model' nor 'mid')")
            version, _meta = self.registry.resolve(line, selector)
        except (RegistryError, ValueError) as exc:
            self._error_reply(ep, body, 'resolve', str(exc))
            return
        handle = self._intern(line, version)
        model_label = '%s@%s' % (line, version)
        with self._lock:
            # the trace context (and its wall-clock arrival) rides in the
            # pending entry so _reply can close the serve_request span
            self._pending[(id(ep), body.get('rid'))] = (
                time.monotonic(), model_label,
                self._client_label(ep, body),
                body.get(TRACE_KEY), time.time())  # graftlint: allow[GL001] wall-clock span timestamp for the Chrome trace only — never enters the reply or any episode record
            self._m_inflight.set(len(self._pending))
        self.engines[handle % len(self.engines)].submit(
            ep, dict(body, mid=handle))

    def _intern(self, line: str, version: str) -> int:
        with self._lock:
            key = (line, version)
            handle = self._handles.get(key)
            if handle is None:
                handle = len(self._handles) + 1
                self._handles[key] = handle
                self._handle_meta[handle] = key
            return handle

    def _fetch(self, handle: int) -> Dict[str, Any]:
        """Engine-side snapshot fetch: handle -> registry bytes (CRC
        re-verified on every load)."""
        with self._lock:
            line, version = self._handle_meta[handle]
        return self.registry.load_snapshot(line, version)

    def _reply(self, ep, msg: Dict[str, Any]):
        """Engine reply fan-in: close the latency span, count, forward."""
        if isinstance(ep, _WarmSink):
            ep.deliver(msg)           # synthetic warm-up: no client socket
            return
        with self._lock:
            entry = self._pending.pop((id(ep), (msg or {}).get('rid')), None)
            self._m_inflight.set(len(self._pending))
            if entry is not None:
                self._lat_ring.append(time.monotonic() - entry[0])
        if entry is not None:
            t0, model_label, client_label, trace, t_wall = entry
            dt = time.monotonic() - t0
            self._m_latency(model_label, client_label).observe(dt)
            self._m_requests(model_label, client_label).inc()
            if msg.get('error'):
                self._m_errors('engine').inc()
            if trace:
                telemetry.trace_event('serve_request', ts=t_wall, dur=dt,
                                      trace_id=trace, model=model_label,
                                      client=client_label,
                                      replica=self.replica_name or '')
        self.answered += 1
        self.hub.send(ep, (INFER_KIND, msg))

    # -- admin frames ------------------------------------------------------

    def _admin(self, ep, body: Dict[str, Any]):
        op = body.get('op')
        if op == 'status':
            self.hub.send(ep, (SERVE_KIND, self.stats()))
        elif op == 'resolve':
            try:
                line, selector = parse_spec(str(body.get('model')))
                version, meta = self.registry.resolve(line, selector)
                self.hub.send(ep, (SERVE_KIND,
                                   {'line': line, 'version': version,
                                    'steps': meta.get('steps'),
                                    'architecture': meta.get('architecture')}))
            except (RegistryError, ValueError) as exc:
                self.hub.send(ep, (SERVE_KIND, {'error': str(exc)}))
        elif op == 'warm':
            self._warm(ep, str(body.get('model')))
        elif op == 'trace':
            # runtime tracing toggle (bench A/B legs flip the SAME warmed
            # process on and off instead of comparing two cold runs)
            telemetry.configure_tracing(str(body.get('dir') or ''),
                                        body.get('rate'), force=True)
            self.hub.send(ep, (SERVE_KIND,
                               {'ok': True,
                                'dir': telemetry.trace_dir(),
                                'rate': telemetry.trace_sample_rate()}))
        else:
            self.hub.send(ep, (SERVE_KIND,
                               {'error': 'unknown admin op %r' % (op,)}))

    def _warm(self, ep, spec: str):
        """Rolling-promote walk: materialize + compile ``line@selector``
        end-to-end by pushing one synthetic request (the example
        observation) through the engine, replying asynchronously — engine
        compiles must not wedge the dispatch loop."""
        if self._draining:
            self.hub.send(ep, (SERVE_KIND, {'error': 'service draining'}))
            return
        try:
            line, selector = parse_spec(spec)
            version, _meta = self.registry.resolve(line, selector)
        except (RegistryError, ValueError) as exc:
            self.hub.send(ep, (SERVE_KIND, {'error': str(exc)}))
            return
        if self._example_obs is None:
            # no env block: nothing to push through the engine; resolving
            # (and the CRC-verified load on first real request) is all we
            # can pre-pay
            self.hub.send(ep, (SERVE_KIND, {'ok': True, 'line': line,
                                            'version': version,
                                            'warmed': False}))
            return
        handle = self._intern(line, version)

        def run():
            sink = _WarmSink()
            self.engines[handle % len(self.engines)].submit(
                sink, {'rid': -1, 'mid': handle, 'obs': self._example_obs})
            ok = sink.done.wait(timeout=60.0)
            err = (sink.reply.get('error') if ok
                   else 'warm-up request timed out')
            reply = ({'ok': True, 'line': line, 'version': version,
                      'warmed': True} if ok and not err
                     else {'error': str(err)})
            self.hub.send(ep, (SERVE_KIND, reply))

        t = threading.Thread(target=run, name='serve-warm', daemon=True)
        t.start()

    # -- fleet membership --------------------------------------------------

    def fleet_drain_requested(self) -> bool:
        """True once the resolver directed this replica to drain (the
        autoscaler's scale-down path); ``serve_main`` then exits 75, the
        same supervisor contract as a SIGTERM drain."""
        return self._fleet_drain

    def poll_alerts(self):
        """Drive the alert engine from the owner's idle loop so rules
        fire/clear even when nothing scrapes /statusz."""
        if self._alerts is not None:
            self._alerts.maybe_evaluate(lambda: [telemetry.snapshot()])

    def _status_info(self) -> Dict[str, Any]:
        """/statusz payload for the serving metrics port: live SLO
        numbers, request progress, and the replica's alert state."""
        info: Dict[str, Any] = {
            'slo': self.slo_snapshot(),
            'progress': {'received': self.received,
                         'answered': self.answered,
                         'refused': self.refused,
                         'draining': bool(self._draining)},
        }
        if self._alerts is not None:
            info['alerts'] = self._alerts.maybe_evaluate(
                lambda: [telemetry.snapshot()])
        return info

    def slo_snapshot(self) -> Dict[str, Any]:
        """The live SLO numbers a heartbeat carries: recent p50/p99
        latency, shed + request counters, in-flight depth."""
        with self._lock:
            lats = sorted(self._lat_ring)
            inflight = len(self._pending)

        def pct(q: float) -> float:
            return ring_percentile_ms(lats, q)

        return {'p50_ms': pct(0.50), 'p99_ms': pct(0.99),
                'inflight': inflight,
                'shed': self.refused + sum(e.sheds for e in self.engines),
                'received': self.received, 'answered': self.answered,
                'draining': self._draining}

    def _fleet_reply(self, conn, timeout: float = 5.0) -> Dict[str, Any]:
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not conn.poll(remaining):
                raise TimeoutError('no resolver reply within %.1fs'
                                   % timeout)
            msg = conn.recv()
            if is_serve(msg) and isinstance(msg[1], dict):
                return msg[1]

    def _fleet_loop(self):
        """Register with the resolver, then heartbeat liveness + the SLO
        snapshot every ``heartbeat_interval``; a lost resolver is redialed
        with jittered backoff (re-registration under the same replica name
        is how a respawned replica is re-admitted). The heartbeat reply may
        carry a drain directive."""
        host, port = parse_endpoint(self.resolver_endpoint)
        advertise = self.advertise_host or self.host or '127.0.0.1'
        backoff = Backoff(initial=0.5, maximum=10.0)
        conn = None
        while not self._stop:
            try:
                if conn is None:
                    conn = connect_socket_connection(host, port)
                    body = {'op': 'register',
                            'endpoint': '%s:%d' % (advertise, self.port),
                            'pid': os.getpid()}
                    if self.replica_name:
                        body['replica'] = self.replica_name
                    conn.send((SERVE_KIND, body))
                    rep = self._fleet_reply(conn)
                    if rep.get('error'):
                        raise RuntimeError(str(rep['error']))
                    self.replica_name = str(rep.get('replica')
                                            or self.replica_name)
                    backoff.reset()
                    _LOG.info('serving: registered with resolver %s as '
                              'replica %r', self.resolver_endpoint,
                              self.replica_name)
                conn.send((SERVE_KIND, {'op': 'heartbeat',
                                        'replica': self.replica_name,
                                        'slo': self.slo_snapshot()}))
                rep = self._fleet_reply(conn)
                if rep.get('drain') and not self._draining:
                    _LOG.warning('serving: resolver directed replica %r to '
                                 'drain', self.replica_name)
                    self._fleet_drain = True
                    self.request_drain()
            except (OSError, ConnectionError, EOFError, ValueError,
                    TimeoutError, RuntimeError) as exc:
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:
                        pass
                    conn = None
                if not self._stop:
                    _LOG.warning('serving: resolver connection lost (%s: '
                                 '%s); redialing', type(exc).__name__,
                                 str(exc)[:200])
                self._sleep(backoff.next_delay())
                continue
            self._sleep(self.heartbeat_interval)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _sleep(self, seconds: float):
        deadline = time.monotonic() + seconds
        while not self._stop and time.monotonic() < deadline:
            time.sleep(min(0.1, max(0.0, deadline - time.monotonic())))

    # -- introspection -----------------------------------------------------

    def inflight(self) -> int:
        with self._lock:
            return len(self._pending)

    def stats(self) -> Dict[str, Any]:
        # local tallies, NOT the process-global telemetry registry: stats
        # must describe THIS service instance even when other engines share
        # the process (tests) or telemetry is disabled
        shed = self.refused + sum(e.sheds for e in self.engines)
        return {
            'port': self.port,
            'clients': self.hub.count() if self.hub is not None else 0,
            'received': self.received,
            'answered': self.answered,
            'inflight': self.inflight(),
            'shed': shed,
            'draining': self._draining,
            'engines': len(self.engines),
            'replica': self.replica_name,
            'resolver': self.resolver_endpoint,
            'engine_requests': sum(e.requests_served for e in self.engines),
            'engine_batches': sum(e.batches_run for e in self.engines),
            'lines': {line: {'champion': entry['champion'],
                             'previous': entry['previous'],
                             'versions': sorted(entry['versions'])}
                      for line, entry in self.registry.describe().items()},
        }


def serve_main(args, argv=None):
    """``main.py --serve``: run the service until SIGTERM/SIGINT, then
    drain and exit 75 (the PreemptionGuard supervisor contract). Prints one
    JSON ready-line on stdout so harnesses can discover the bound ports."""
    sargs = dict(args['train_args'])
    sargs['env'] = dict(args['env_args'])
    inf = dict(sargs.get('inference') or {})
    if str(inf.get('engine_backend', 'cpu')) == 'device':
        from .. import setup_compile_cache
        setup_compile_cache()
    else:
        from ..connection import force_cpu_backend
        force_cpu_backend()
    from ..environment import prepare_env
    prepare_env(sargs['env'])

    telemetry.adopt_config(sargs)
    telemetry.set_process_label('serve')
    telemetry.install_crash_dump()
    guard = PreemptionGuard().install()
    service = InferenceService(sargs).start()
    print(json.dumps({'serving_ready': {
        'port': service.port, 'metrics_port': service.metrics_port,
        'pid': os.getpid(), 'registry': service.registry.root}}), flush=True)
    try:
        while not guard.requested() and not service.fleet_drain_requested():
            time.sleep(0.2)
            service.poll_alerts()
        if guard.requested():
            _LOG.warning('serving: preemption signal received; draining')
    finally:
        service.stop(drain=True)
        guard.uninstall()
    if guard.fired or service.fleet_drain_requested():
        # a resolver-directed drain exits through the same supervisor
        # contract as a SIGTERM: 75 = done cleanly, restartable
        raise SystemExit(PREEMPT_EXIT_CODE)
