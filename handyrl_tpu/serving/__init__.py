"""Standalone model-serving tier: the InferenceEngine grown into product infra.

Until PR 10, the Sebulba-style engine (inference.py) was born and died
inside a Gather — nothing outside one training run could reach it. This
package promotes it into a long-lived service that outlives any single run,
the MindSpeed-RL-style separation of inference into its own dataflow stage
with its own lifecycle, versioning, and SLOs:

* :mod:`.registry` — a **versioned ModelRegistry** grown from the
  ModelVault idea: named model *lines*, each with a pinned "champion" plus
  rolling candidate versions, atomic promote/rollback built on the
  CRC-verified checkpoint machinery (utils/fs.py). Registry state is one
  atomic JSON manifest, so a service restart recovers the exact serving
  set, and checkpoint-retention GC never collects a pinned version.

* :mod:`.service` — the **InferenceService** process (``main.py --serve``
  or ``python -m handyrl_tpu.serving``): one or more supervised
  InferenceEngines behind the existing framed ``INFER_KIND`` protocol over
  TCP, continuous batching via the engine's coalescing/pad_to_bucket
  machinery, admission control with shed-on-overload, per-client/per-model
  request-latency histograms on ``/metrics``, and graceful drain on
  SIGTERM under the PR 4 PreemptionGuard contract (exit 75 = restart me;
  every accepted request is answered before exit).

* :mod:`.client` — the client side: :class:`~.client.ServiceClient` speaks
  the framed protocol to a service endpoint, and
  :class:`~.client.RemoteServiceModel` presents the model surface the
  agents/evaluators dispatch on, so ``eval_server``/``eval_client`` and
  league-style match traffic all resolve models by ``name@version``
  against one engine fleet (``serve://host:port/name@version`` /
  ``registry://root/name@version`` model specs in evaluation.load_model).

Worker fleets join the same tier: an :class:`~.inference.EngineClient`
with ``serving.endpoint`` configured dials the remote service instead of
the in-Gather engine, keeping its timeout/retry/circuit-breaker failover —
a dead service degrades to the per-worker path byte-identically.
"""

from .registry import (ModelRegistry, RegistryError, parse_spec,
                       pinned_checkpoint_paths)

__all__ = ['ModelRegistry', 'RegistryError', 'parse_spec',
           'pinned_checkpoint_paths']
