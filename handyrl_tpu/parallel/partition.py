"""Regex partition rules: named pytree paths -> PartitionSpec -> NamedSharding.

The learner's compiled programs (ops/train_step.py, ops/fused_pipeline.py)
take explicit in/out shardings over the ('data', 'model') mesh instead of
relying on input placement. This module is the ONE place those shardings
come from: a ``match_partition_rules``-style engine (the fmengine/EasyLM
idiom) walks the param/optimizer/batch-stats pytree, names every leaf by
its '/'-joined key path (e.g. ``params/params/conv0/kernel`` or
``opt_state/2/mu/params/head/bias``), and assigns the spec of the FIRST
rule whose regex matches. Scalars and single-element leaves always
replicate — a partitioned Adam ``count`` makes no sense on any mesh.

Data parallelism is the default (``DEFAULT_RULES`` replicates every
parameter; the batch shards along 'data'); tensor-parallel layouts are a
config edit away (``parallel.partition_rules`` in config.yaml), not a code
change — the 'model' mesh axis already exists for them.

The same layout vocabulary describes checkpoints: ``checkpoint_layout``
summarizes the mesh shape + rules into the manifest written next to
``trainer_state.ckpt`` / ``models/<epoch>.ckpt`` (utils/fs.py), so a
checkpoint saved under one device/host count restores under another with
the mismatch logged instead of silently assumed.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS, batch_sharding, replicated_sharding

# pure data parallelism: every parameter/optimizer leaf replicated, the
# batch sharded along 'data' (the Podracer layout) — what the learner runs
# unless config parallel.partition_rules says otherwise
DEFAULT_RULES: Tuple[Tuple[str, P], ...] = ((r'.*', P()),)

# checkpoint layout-manifest format version (bump on incompatible change)
LAYOUT_FORMAT = 1


def leaf_path(path) -> str:
    """'/'-joined name of a tree_flatten_with_path key path."""
    parts = []
    for key in path:
        if isinstance(key, jax.tree_util.DictKey):
            parts.append(str(key.key))
        elif isinstance(key, jax.tree_util.SequenceKey):
            parts.append(str(key.idx))
        elif isinstance(key, jax.tree_util.GetAttrKey):
            parts.append(str(key.name))
        elif isinstance(key, jax.tree_util.FlattenedIndexKey):
            parts.append(str(key.key))
        else:   # unknown key kind: fall back to its repr, stripped
            parts.append(str(key).strip('.[]\'"'))
    return '/'.join(parts)


def spec_from_entry(entry) -> P:
    """Config-form spec -> PartitionSpec.

    ``None``/``[]`` replicate; a string names one mesh axis; a list maps
    array dims to axes positionally, with ``None``/``'null'``/``''``
    entries unsharded (``['data']`` -> P('data'), ``[None, 'model']`` ->
    P(None, 'model')).
    """
    if entry is None:
        return P()
    if isinstance(entry, P):
        return entry
    if isinstance(entry, str):
        return P(entry)
    axes = [None if a in (None, 'null', '') else str(a) for a in entry]
    return P(*axes)


def normalize_rules(rules) -> Tuple[Tuple[str, P], ...]:
    """[(regex, config-form spec), ...] -> ((regex, PartitionSpec), ...)."""
    out = []
    for pattern, spec in rules:
        out.append((str(pattern), spec_from_entry(spec)))
    return tuple(out)


def rules_from_config(args: Dict[str, Any]) -> Tuple[Tuple[str, P], ...]:
    """The train_args['parallel'] rule set, catch-all-replicate-terminated.

    An operator writing rules for a few kernels must not crash every
    unmatched bias, so config-sourced rule sets get the DEFAULT_RULES
    catch-all appended; ``match_partition_rules`` itself stays strict for
    library callers.
    """
    par = args.get('parallel') or {}
    user = par.get('partition_rules') or ()
    if not user:
        return DEFAULT_RULES
    return normalize_rules(user) + DEFAULT_RULES


def pure_data_parallel(rules) -> bool:
    """True when every rule replicates (no tensor-parallel specs) — the
    precondition for the shard_map'd fused pipeline, whose gradient psum
    assumes a fully replicated train state."""
    return all(len(tuple(spec)) == 0 for _, spec in normalize_rules(rules))


def match_partition_rules(rules, tree) -> Any:
    """Pytree of PartitionSpec for ``tree`` per the first matching rule.

    Scalar / single-element leaves replicate regardless of rules. A leaf
    no rule matches raises — end the rule list with ``('.*', P())`` (what
    ``rules_from_config`` does for config-sourced rules) to default to
    replication instead.
    """
    rules = normalize_rules(rules)

    def spec_of(path, leaf):
        shape = tuple(getattr(leaf, 'shape', ()) or ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        name = leaf_path(path)
        for pattern, spec in rules:
            if re.search(pattern, name) is not None:
                return spec
        raise ValueError(
            'no partition rule matches leaf %r (shape %s); end the rule '
            'list with a catch-all (".*", []) to replicate by default'
            % (name, shape))

    return jax.tree_util.tree_map_with_path(spec_of, tree)


def validate_specs(mesh: Mesh, tree, specs) -> None:
    """Fail fast when a spec's sharded dims don't divide the mesh axes —
    the XLA error for that names neither the leaf nor the rule."""
    def check(path, leaf, spec):
        shape = tuple(getattr(leaf, 'shape', ()) or ())
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            axes = axis if isinstance(axis, (tuple, list)) else (axis,)
            size = 1
            for a in axes:
                if a not in mesh.shape:
                    raise ValueError(
                        'partition spec %s for %r names unknown mesh axis '
                        '%r (mesh axes: %s)' % (spec, leaf_path(path), a,
                                                tuple(mesh.shape)))
                size *= int(mesh.shape[a])
            if dim >= len(shape) or shape[dim] % size != 0:
                raise ValueError(
                    'leaf %r shape %s dim %d is not divisible by mesh '
                    'axis %r (size %d)' % (leaf_path(path), shape, dim,
                                           axis, size))

    jax.tree_util.tree_map_with_path(check, tree, specs)


def tree_shardings(mesh: Mesh, tree, rules=DEFAULT_RULES) -> Any:
    """Pytree of NamedSharding for ``tree`` from the rule engine, with the
    divisibility of every sharded dim validated up front."""
    specs = match_partition_rules(rules, tree)
    validate_specs(mesh, tree, specs)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                  is_leaf=lambda x: isinstance(x, P))


def batch_shardings(mesh: Mesh) -> NamedSharding:
    """The batch prefix sharding: every leaf splits its leading (batch)
    dim along 'data' (a bare sharding is a pytree prefix in jax.jit)."""
    return batch_sharding(mesh)


def host_to_global_batch(mesh: Mesh, local_batch):
    """Multi-process meshes: assemble the GLOBAL sharded batch from each
    process's local rows (every process holds its own slice; nothing is
    replicated or gathered). Single-process meshes should use
    ``mesh.shard_batch`` instead — it also counts transfer bytes."""
    sharding = batch_sharding(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)), local_batch)


# ---------------------------------------------------------------------------
# checkpoint layout manifests (mesh-shape-portable restore)


def serializable_rules(rules) -> list:
    """((regex, PartitionSpec), ...) -> JSON-safe [[regex, [axes...]], ...]."""
    out = []
    for pattern, spec in normalize_rules(rules):
        out.append([pattern, [list(a) if isinstance(a, (tuple, list))
                              else a for a in spec]])
    return out


def checkpoint_layout(mesh: Optional[Mesh], rules=DEFAULT_RULES,
                      steps: Optional[int] = None) -> Dict[str, Any]:
    """The layout manifest describing how a checkpoint's train state was
    laid out at save time. The state itself is serialized as full
    (host-gathered) arrays, so restore under ANY mesh shape is exact; the
    manifest makes the mesh change explicit instead of silent."""
    layout: Dict[str, Any] = {
        'format': LAYOUT_FORMAT,
        'mesh': ({axis: int(n) for axis, n in mesh.shape.items()}
                 if mesh is not None else None),
        'devices': int(np.prod(list(mesh.shape.values()))) if mesh is not None
                   else 1,
        'processes': int(jax.process_count()),
        'partition_rules': serializable_rules(rules),
    }
    if steps is not None:
        layout['steps'] = int(steps)
    return layout


def describe_mesh(layout: Optional[Dict[str, Any]]) -> str:
    """Human-readable mesh description of a layout manifest (logging)."""
    if not layout or not layout.get('mesh'):
        return 'single device'
    mesh = layout['mesh']
    return 'x'.join('%s=%d' % (axis, mesh[axis]) for axis in sorted(mesh))
