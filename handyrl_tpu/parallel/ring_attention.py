"""Ring attention: sequence-parallel exact attention over a mesh axis.

The reference handles long sequences purely by windowing (truncated-BPTT
windows + chunked episode storage, SURVEY.md §5.7) and contains no attention
layers. This module makes long-context attention a first-class capability of
the framework for attention-based policy nets: queries stay resident on each
device's sequence shard while key/value shards rotate around the ring via
``ppermute`` (one hop per step, riding ICI), with the numerically-stable
online-softmax accumulation of Liu et al. 2023 (Ring Attention,
arXiv:2310.01889) / Milakov & Gimelshein 2018 (online softmax).

``ring_attention(q, k, v, mesh, axis)`` == exact softmax attention; each
device only ever holds 1/N of the sequence. Tested against full attention on
the 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from functools import partial

from jax import lax
try:
    # jax >= 0.8: jax.shard_map, replication check named check_vma
    shard_map = partial(jax.shard_map, check_vma=False)
except AttributeError:
    from jax.experimental.shard_map import shard_map
    shard_map = partial(shard_map, check_rep=False)
from jax.sharding import Mesh, PartitionSpec as P


def _block_attention(q, k, v, m_prev, l_prev, o_prev, scale):
    """One blockwise attention step with online-softmax accumulation.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D);
    m/l: running max / normalizer (B, H, Tq); o: unnormalized output.
    """
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k) * scale          # (B,H,Tq,Tk)
    m_block = s.max(axis=-1)                                  # (B,H,Tq)
    m_new = jnp.maximum(m_prev, m_block)
    p = jnp.exp(s - m_new[..., None])                         # (B,H,Tq,Tk)
    correction = jnp.exp(m_prev - m_new)
    l_new = l_prev * correction + p.sum(axis=-1)
    o_new = (o_prev * correction[..., None]
             + jnp.einsum('bhqk,bkhd->bhqd', p, v))
    return m_new, l_new, o_new


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, axis: str = 'data',
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Exact multi-head attention with the sequence sharded over ``axis``.

    Args: q, k, v of shape (B, T, H, D) with T divisible by the mesh axis
    size. Returns (B, T, H, D) attention output, sharded like q.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = mesh.shape[axis]

    def local_fn(q_loc, k_loc, v_loc):
        B, Tq, H, D = q_loc.shape
        idx = lax.axis_index(axis)
        m = jnp.full((B, H, Tq), -jnp.inf, q_loc.dtype)
        l = jnp.zeros((B, H, Tq), q_loc.dtype)
        o = jnp.zeros((B, H, Tq, D), q_loc.dtype)

        def body(i, carry):
            m, l, o, k_cur, v_cur = carry
            m, l, o = _block_attention(q_loc, k_cur, v_cur, m, l, o, scale)
            # rotate k/v one hop around the ring
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_nxt = lax.ppermute(k_cur, axis, perm)
            v_nxt = lax.ppermute(v_cur, axis, perm)
            return m, l, o, k_nxt, v_nxt

        m, l, o, _, _ = lax.fori_loop(0, n, body, (m, l, o, k_loc, v_loc))
        out = o / l[..., None]                                # normalize
        return jnp.einsum('bhqd->bqhd', out)

    spec = P(None, axis, None, None)
    return shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Reference single-device attention for parity checks."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v)
