"""Multi-host TPU initialization helpers.

On a multi-host pod slice every host runs the same program; JAX needs the
distributed runtime initialized before first use so `jax.devices()` sees the
global device set. The learner's mesh helpers (parallel/mesh.py) then span
hosts transparently: data-parallel sharding puts the gradient all-reduce on
ICI within a slice and DCN across slices.

Typical launch (one learner process per host):

    from handyrl_tpu.parallel import multihost
    multihost.initialize()           # no-op on single-host
    ...
    train_main(args)

Worker hosts (CPU episode generators) do NOT call this — they are plain
processes speaking the framed-TCP protocol to the learner host.
"""

from __future__ import annotations

import os
from typing import Optional


def _enable_cpu_collectives():
    """CPU backend: cross-process collectives need the gloo transport.

    XLA:CPU's default collective implementation refuses multi-process
    computations outright ("Multiprocess computations aren't implemented on
    the CPU backend"); the gloo implementation shipped with jaxlib handles
    them. Must be set BEFORE ``jax.distributed.initialize`` creates the
    backend. A no-op on non-CPU platforms, older jaxlibs without the flag,
    and when the operator already chose an implementation.
    """
    import jax

    platform = (os.environ.get('JAX_PLATFORMS', '').strip().lower()
                or str(getattr(jax.config, 'jax_platforms', None) or ''))
    if 'cpu' not in platform:
        return
    if 'jax_cpu_collectives_implementation' not in jax.config.values:
        return
    current = jax.config.values.get('jax_cpu_collectives_implementation')
    if current and current != 'none':
        return   # operator already chose (gloo/mpi); leave it alone
    jax.config.update('jax_cpu_collectives_implementation', 'gloo')


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Initialize jax.distributed when running multi-host; returns True when
    distributed mode was activated.

    With no arguments, uses the standard cluster-environment autodetection
    (TPU pod metadata / JAX_COORDINATOR_ADDRESS etc.); single-host runs are
    detected and left untouched.
    """
    import jax

    if coordinator_address is None:
        # NB: MEGASCALE_COORDINATOR_ADDRESS is deliberately NOT consulted —
        # it names libtpu's multislice DCN transport endpoint, not the
        # jax.distributed coordinator service
        coordinator_address = next(
            (os.environ[k] for k in
             ('JAX_COORDINATOR_ADDRESS', 'COORDINATOR_ADDRESS')
             if os.environ.get(k)), None)
        if coordinator_address is None:
            return False
    if num_processes is None and os.environ.get('JAX_NUM_PROCESSES'):
        num_processes = int(os.environ['JAX_NUM_PROCESSES'])
    if process_id is None and os.environ.get('JAX_PROCESS_ID'):
        process_id = int(os.environ['JAX_PROCESS_ID'])

    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh(model_parallel: int = 1):
    """Mesh over ALL devices in the (possibly multi-host) job."""
    from .mesh import make_mesh
    import jax
    return make_mesh(jax.devices(), model_parallel=model_parallel)


def is_coordinator() -> bool:
    import jax
    return jax.process_index() == 0
