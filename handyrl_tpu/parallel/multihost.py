"""Multi-host TPU initialization helpers.

On a multi-host pod slice every host runs the same program; JAX needs the
distributed runtime initialized before first use so `jax.devices()` sees the
global device set. The learner's mesh helpers (parallel/mesh.py) then span
hosts transparently: data-parallel sharding puts the gradient all-reduce on
ICI within a slice and DCN across slices.

Typical launch (one learner process per host):

    from handyrl_tpu.parallel import multihost
    multihost.initialize()           # no-op on single-host
    ...
    train_main(args)

Worker hosts (CPU episode generators) do NOT call this — they are plain
processes speaking the framed-TCP protocol to the learner host.
"""

from __future__ import annotations

import os
from typing import Optional


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Initialize jax.distributed when running multi-host; returns True when
    distributed mode was activated.

    With no arguments, uses the standard cluster-environment autodetection
    (TPU pod metadata / JAX_COORDINATOR_ADDRESS etc.); single-host runs are
    detected and left untouched.
    """
    import jax

    explicit = coordinator_address is not None
    env_driven = any(os.environ.get(k) for k in
                     ('JAX_COORDINATOR_ADDRESS', 'COORDINATOR_ADDRESS',
                      'MEGASCALE_COORDINATOR_ADDRESS'))
    if not explicit and not env_driven:
        return False

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def global_mesh(model_parallel: int = 1):
    """Mesh over ALL devices in the (possibly multi-host) job."""
    from .mesh import make_mesh
    import jax
    return make_mesh(jax.devices(), model_parallel=model_parallel)


def is_coordinator() -> bool:
    import jax
    return jax.process_index() == 0
