"""Device mesh and sharding helpers.

Replaces the reference's single-node nn.DataParallel (train.py:339-340) with
jax.sharding over a named mesh: the batch is sharded along 'data', params are
replicated, and XLA inserts the gradient all-reduce over ICI. A 'model' axis
is reserved so tensor-parallel specs can be added without changing call
sites.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = 'data'
MODEL_AXIS = 'model'


def make_mesh(devices: Optional[Sequence] = None, model_parallel: int = 1) -> Mesh:
    """(n/model_parallel, model_parallel) mesh over the given devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % model_parallel == 0, (n, model_parallel)
    grid = np.array(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dim split across the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch):
    """Device-put a host batch with its leading dim sharded over 'data'."""
    spec = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, spec), batch)


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k
