"""Device mesh and sharding helpers.

Replaces the reference's single-node nn.DataParallel (train.py:339-340) with
jax.sharding over a named mesh: the batch is sharded along 'data', params are
replicated, and XLA inserts the gradient all-reduce over ICI. A 'model' axis
is reserved so tensor-parallel specs can be added without changing call
sites (parallel/partition.py maps regex rules over the param/optimizer
pytree onto these axes).

``shard_batch`` is the host->device staging primitive: each device receives
ONLY its shard's slice of a host batch (``jax.make_array_from_callback``
builds per-device buffers from host slices — never a full-array replication
that is then resharded), and the bytes actually staged are counted on the
``mesh_shard_bytes_total`` telemetry counter so the 1/N-per-device transfer
contract is observable, not assumed.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry

DATA_AXIS = 'data'
MODEL_AXIS = 'model'

# host->device bytes staged by shard_batch, summed over the addressable
# shards it built (per-device bytes = total batch bytes / data-axis size;
# a replicated placement of the same batch would count devices x bytes)
_SHARD_BYTES = telemetry.counter('mesh_shard_bytes_total')


def make_mesh(devices: Optional[Sequence] = None, model_parallel: int = 1) -> Mesh:
    """(n/model_parallel, model_parallel) mesh over the given devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    assert n % model_parallel == 0, (n, model_parallel)
    grid = np.array(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) dim split across the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _place_host_leaf(arr: np.ndarray, sharding: NamedSharding):
    """Build the sharded device array from per-shard HOST slices: each
    addressable device is handed exactly its slice's bytes. The counter
    reflects what actually crossed to each device (replicating dims — the
    'model' axis, or a scalar — count once per holding device, which is
    what the wire really carries)."""
    out = jax.make_array_from_callback(arr.shape, sharding,
                                       lambda idx: arr[idx])
    if telemetry.enabled():
        _SHARD_BYTES.inc(sum(s.data.nbytes for s in out.addressable_shards))
    return out


def shard_batch(mesh: Mesh, batch, specs=None):
    """Place a batch with its leading dim sharded over 'data'.

    Host (numpy) leaves are staged per shard — device i receives only its
    1/N slice. Leaves already on device are resharded by XLA
    (``device_put``), which is what the fused pipeline's loop-state layout
    pass wants. Scalars replicate. ``specs`` optionally overrides the
    per-leaf PartitionSpec pytree (prefix or full; default = P('data')).
    """
    data = batch_sharding(mesh)
    repl = replicated_sharding(mesh)

    def place(x, spec=None):
        sharding = (NamedSharding(mesh, spec) if isinstance(spec, P)
                    else spec) if spec is not None else None
        if isinstance(x, jax.Array):
            return jax.device_put(x, sharding or
                                  (data if np.ndim(x) else repl))
        arr = np.asarray(x)
        if sharding is None:
            sharding = data if arr.ndim else repl
        return _place_host_leaf(arr, sharding)

    if specs is None:
        return jax.tree_util.tree_map(place, batch)
    return jax.tree_util.tree_map(
        place, batch, specs,
        is_leaf=lambda x: isinstance(x, (P, NamedSharding)))


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k
