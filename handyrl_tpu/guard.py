"""Learner-side crash/corruption resilience.

PR 2 made the *actor fleet* survive kills and severed sockets; this module
hardens the learner itself — the remaining single fragile point on a
preemptible TPU pod. Three legs, wired through train.py / utils/fs.py:

* :class:`PreemptionGuard` — SIGTERM/SIGINT become a cooperative stop flag
  the training loops check at safe points (batch boundary, epoch boundary).
  On trigger the learner flushes a full atomic checkpoint (TrainState +
  trainer_state + episode accounting), writes a final ``metrics_jsonl``
  record tagged ``preempted``, tears down its children, and exits with
  :data:`PREEMPT_EXIT_CODE` — the supervisor contract: *restart me, I will
  resume* (``restart_epoch: -1`` auto-resolves the newest valid
  checkpoint). A third signal is an operator override and kills the
  process immediately with the conventional ``128 + signum``.

* :class:`NonFiniteGuard` — escalation policy over the on-device all-finite
  check the update step performs each SGD step (ops/train_step.py: a
  non-finite loss, global grad norm, or lr leaves params/optimizer
  untouched and raises the ``nonfinite`` metric). The host observes those
  counts on its existing lazy metric fetch — no extra sync on the hot
  path — and per ``guard.nonfinite_policy`` skips (count only), rolls the
  TrainState back to the last good checkpoint after ``rollback_after``
  consecutive bad updates (or a loss-spike z-score trip), or aborts.

* Checkpoint integrity helpers — resume-time selection of the newest
  numbered checkpoint that passes the CRC32 sidecar verification
  (utils/fs.py), so a bit-flipped or truncated ``models/<epoch>.ckpt``
  falls back to the previous valid epoch instead of crashing the restart.

Chaos injectors (``HANDYRL_TPU_CHAOS``, parsed by fault.parse_chaos):
``preempt=<s>`` SIGTERMs this process after a fixed delay; ``nanstep=<n>``
/ ``nanepoch=<e>`` + ``nanburst=<k>`` poison the lr of ``k`` updates
starting at global SGD step ``n`` (or right after epoch ``e``'s
checkpoint), driving the skip/rollback machinery end to end.
"""

from __future__ import annotations

import math
import os
import signal
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry
from .fault import parse_chaos

_LOG = telemetry.get_logger('guard')

# EX_TEMPFAIL: the supervisor contract — a learner exiting with this code
# snapshotted successfully and asks to be restarted into the resume path
# (docs/large_scale_training.md "Preemption and recovery").
PREEMPT_EXIT_CODE = 75


class PreemptionGuard:
    """SIGTERM/SIGINT → cooperative stop flag (checked at safe points).

    ``install`` is a no-op off the main thread (the CPython signal API
    requirement) and when ``enabled`` is False; ``uninstall`` restores the
    previous handlers so an in-process Learner (tests) leaves the host
    interpreter's signal disposition untouched.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self.signum: Optional[int] = None
        self._event = threading.Event()
        self._count = 0
        self._previous: Dict[int, Any] = {}

    def install(self) -> 'PreemptionGuard':
        if not self.enabled or self._previous:
            return self
        if threading.current_thread() is not threading.main_thread():
            return self
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._previous[sig] = signal.signal(sig, self._handle)
            except (ValueError, OSError):   # exotic embedding: stay passive
                self._previous.pop(sig, None)
                break
        return self

    def uninstall(self):
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._previous = {}

    def _handle(self, signum, frame):
        self._count += 1
        self.signum = signum
        self._event.set()
        if self._count == 1:
            # The handler runs between bytecodes on the main thread, which
            # may hold the recorder/registry locks — dump from a side
            # thread so the blackbox write can never deadlock the handler.
            t = threading.Thread(
                target=telemetry.dump_blackbox, args=('preempt',),
                kwargs={'signum': int(signum)}, daemon=True)
            t.start()
        if self._count >= 3:
            # operator insists: skip the graceful snapshot entirely
            os._exit(128 + signum)

    @property
    def fired(self) -> bool:
        return self._event.is_set()

    def requested(self) -> bool:
        return self._event.is_set()


class NonFiniteGuard:
    """Host-side escalation policy over the device's per-update finiteness
    flag. ``observe`` folds one drained metrics group in and returns the
    action the trainer must take: None (clean), 'skip' (count and carry
    on), 'rollback' (restore the last good checkpoint), 'abort'."""

    def __init__(self, cfg: Optional[Dict[str, Any]] = None):
        cfg = cfg or {}
        self.policy = str(cfg.get('nonfinite_policy') or 'rollback')
        self.rollback_after = max(1, int(cfg.get('rollback_after') or 8))
        self.zscore = float(cfg.get('loss_spike_zscore') or 0.0)
        self.consecutive = 0
        self.total_bad = 0
        self.rollbacks = 0
        # EMA loss statistics for the optional spike trip
        self._loss_mean = 0.0
        self._loss_var = 0.0
        self._loss_n = 0

    def observe(self, bad: int, good: int,
                loss_mean: Optional[float] = None) -> Optional[str]:
        if bad:
            self.total_bad += bad
            self.consecutive += bad
            telemetry.record_event('guard', 'nonfinite updates', bad=int(bad),
                                   consecutive=int(self.consecutive))
            if self.policy == 'abort':
                telemetry.dump_blackbox('nonfinite-abort', bad=int(bad),
                                        total_bad=int(self.total_bad))
                return 'abort'
            if (self.policy == 'rollback'
                    and self.consecutive >= self.rollback_after):
                telemetry.record_event('guard', 'nonfinite rollback',
                                       consecutive=int(self.consecutive))
                return 'rollback'
            return 'skip'
        if good:
            self.consecutive = 0
            if loss_mean is not None and math.isfinite(loss_mean):
                return self._observe_loss(loss_mean)
        return None

    def _observe_loss(self, loss: float) -> Optional[str]:
        """EMA mean/variance z-score over per-drain loss means: a finite
        but exploding loss trips the same rollback as a NaN burst. Needs
        ``loss_spike_zscore`` > 0 and ~20 warmup samples."""
        trip = None
        if self.zscore > 0 and self._loss_n >= 20:
            std = math.sqrt(max(self._loss_var, 1e-12))
            if abs(loss - self._loss_mean) > self.zscore * std:
                trip = 'rollback' if self.policy == 'rollback' else None
                if trip:
                    telemetry.record_event('guard', 'loss spike rollback',
                                           loss=round(loss, 6))
                    _LOG.warning('guard: loss spike %.4g (mean %.4g, '
                                 'std %.4g) tripped the z-score guard',
                                 loss, self._loss_mean, std)
        self._loss_n += 1
        alpha = 0.99
        delta = loss - self._loss_mean
        self._loss_mean += (1 - alpha) * delta
        self._loss_var = alpha * (self._loss_var + (1 - alpha) * delta ** 2)
        return trip

    def reset_streak(self):
        """Called after a rollback (or a rollback that had nowhere to go):
        the restored state starts a fresh streak and fresh loss stats."""
        self.consecutive = 0
        self._loss_n = 0
        self._loss_mean = 0.0
        self._loss_var = 0.0


class ChaosNaN:
    """``nanstep``/``nanepoch``/``nanburst`` injection bookkeeping.

    ``due(step, count)`` answers whether any of the ``count`` updates
    dispatched starting at global SGD step ``step`` should be poisoned,
    and CONSUMES the burst budget when it fires — a rollback that rewinds
    the step counter back into the window must not re-trigger the
    injection forever. ``nanepoch`` arms lazily (train.py arms it at the
    matching epoch boundary, once a rollback target exists on disk).
    """

    def __init__(self, chaos: Optional[Dict[str, float]] = None):
        chaos = parse_chaos() if chaos is None else chaos
        self.at = int(chaos['nanstep']) if 'nanstep' in chaos else None
        self.epoch = int(chaos['nanepoch']) if 'nanepoch' in chaos else None
        self.burst = max(1, int(chaos.get('nanburst', 1)))
        self.remaining = self.burst if (self.at is not None
                                        or self.epoch is not None) else 0

    def arm(self, at: int):
        """Start (or restart) the injection window at step ``at``."""
        if self.at is None:
            self.at = int(at)

    def due(self, step: int, count: int = 1) -> bool:
        if self.at is None or self.remaining <= 0 or step + count <= self.at:
            return False
        self.remaining -= count
        return True


def arm_chaos_preempt(chaos: Optional[Dict[str, float]] = None):
    """``HANDYRL_TPU_CHAOS=preempt=<s>``: SIGTERM this process after a
    fixed delay — the test/soak stand-in for a TPU pod preemption notice."""
    chaos = parse_chaos() if chaos is None else chaos
    delay = chaos.get('preempt')
    if not delay:
        return None

    def _fire():
        print('chaos: preempting learner (SIGTERM)', flush=True)
        os.kill(os.getpid(), signal.SIGTERM)

    timer = threading.Timer(float(delay), _fire)
    timer.daemon = True
    timer.start()
    return timer


# ---------------------------------------------------------------------------
# checkpoint selection (integrity-verified resume / rollback targets)


def numbered_checkpoints(model_dir: str) -> List[int]:
    """Sorted epochs of the ``<epoch>.ckpt`` files present in model_dir."""
    try:
        names = os.listdir(model_dir)
    except OSError:
        return []
    out = []
    for name in names:
        stem, dot, ext = name.partition('.')
        if dot and ext == 'ckpt' and stem.isdigit():
            out.append(int(stem))
    return sorted(out)


def newest_valid_epoch(model_dir: str, at_most: Optional[int] = None
                       ) -> Tuple[int, List[int]]:
    """Newest numbered checkpoint epoch passing CRC verification (0 when
    none), plus the list of newer epochs that were discarded as invalid."""
    from .utils.fs import verify_checkpoint
    discarded: List[int] = []
    for epoch in reversed(numbered_checkpoints(model_dir)):
        if at_most is not None and epoch > at_most:
            continue
        ok, reason = verify_checkpoint(
            os.path.join(model_dir, '%d.ckpt' % epoch))
        if ok:
            return epoch, discarded
        _LOG.error('discarding checkpoint %d.ckpt: %s', epoch, reason)
        discarded.append(epoch)
    return 0, discarded


# ---------------------------------------------------------------------------
# episode ingest guard


def _all_finite(x) -> bool:
    if x is None:
        return True
    if isinstance(x, dict):
        return all(_all_finite(v) for v in x.values())
    if isinstance(x, (list, tuple)):
        return all(_all_finite(v) for v in x)
    arr = np.asarray(x)
    if arr.dtype.kind not in 'fc':
        return True
    return bool(np.isfinite(arr).all())


def episode_is_finite(episode: Dict[str, Any]) -> bool:
    """True when the episode's outcome and decoded per-moment observations/
    rewards/values/returns are all finite. Undecodable payloads count as
    poisoned — one bad actor must not contaminate every future batch."""
    try:
        if not _all_finite(episode.get('outcome')):
            return False
        from .ops.batch import decompress_moments
        for moment in decompress_moments(episode.get('moment') or []):
            for key in ('observation', 'reward', 'value', 'return'):
                if not _all_finite(moment.get(key)):
                    return False
    except Exception:
        return False
    return True
