"""Model wrapper: Flax module + params with a numpy inference edge.

TPU-native counterpart of the reference ModelWrapper (model.py:33-74). A
"model" here is the pair (architecture, params pytree); the wrapper owns a
jit-compiled apply and presents the same numpy-in/numpy-out single-sample
``inference`` the generators/agents expect, plus a batched path used by the
vectorized actors. Params travel over the wire as msgpack bytes + the
architecture name — never as pickled code.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from . import models as model_zoo
from .utils.tree import map_structure


def _to_numpy(x):
    return jax.tree_util.tree_map(np.asarray, x)


def module_config(module) -> Dict[str, Any]:
    """Non-default, wire-safe (str/int/float/bool) dataclass fields of a
    flax module. dtype-like fields are intentionally skipped: they change
    numerics, not the param-tree structure, and the training side pins them
    explicitly."""
    config: Dict[str, Any] = {}
    for f in dataclasses.fields(module):
        if f.name in ('parent', 'name'):
            continue
        v = getattr(module, f.name)
        if isinstance(v, (str, int, float, bool)) and v != f.default:
            config[f.name] = v
    return config


@functools.lru_cache(maxsize=64)
def _jitted_apply(module):
    """One jit per module configuration (flax modules hash by their fields),
    shared across wrapper instances — workers re-fetching params every epoch
    reuse the compiled program instead of re-tracing."""
    return jax.jit(module.apply)


class ModelWrapper:
    """Holds (module, params); provides jitted single/batched inference."""

    def __init__(self, module, params=None, seed: int = 0):
        self.module = module
        self.params = params
        self.seed = seed
        self._apply = _jitted_apply(module)

    # -- params lifecycle -------------------------------------------------
    def ensure_params(self, example_obs) -> None:
        """Initialize params from an example observation if not set."""
        if self.params is None:
            obs = map_structure(lambda v: jnp.asarray(v)[None], example_obs)
            hidden = self.init_hidden((1,))
            self.params = self.module.init(jax.random.PRNGKey(self.seed), obs, hidden)

    # -- hidden state -----------------------------------------------------
    def init_hidden(self, batch_shape=None):
        """None => single-sample numpy state (for host actors); otherwise a
        device pytree with the given leading batch shape."""
        if not hasattr(self.module, 'init_hidden'):
            return None
        if batch_shape is None:
            return _to_numpy(self.module.init_hidden(()))
        return self.module.init_hidden(tuple(batch_shape))

    # -- inference --------------------------------------------------------
    def inference(self, obs, hidden=None) -> Dict[str, Any]:
        """Single sample: numpy in, numpy out, batch dim handled here."""
        if getattr(self.module, 'norm_kind', None) == 'batchstats' \
                and not getattr(self, '_warned_b1', False):
            # ADVICE r4: the pure batch-statistics investigation norm
            # degrades to per-sample (instance) statistics at B=1 — a
            # different network function than trained. norm_kind='batch'
            # (full BatchNorm, running averages) does not have this trap.
            import warnings
            warnings.warn(
                "norm_kind='batchstats' model used on a sequential B=1 "
                "inference path: normalization falls back to per-sample "
                "statistics, a different function than trained. Use "
                "norm_kind='batch' (running-average BatchNorm) for "
                "sequential host evaluation.", RuntimeWarning)
            self._warned_b1 = True
        self.ensure_params(obs)
        obs_b = map_structure(lambda v: None if v is None else jnp.asarray(v)[None], obs)
        hidden_b = None
        if hidden is not None:
            hidden_b = jax.tree_util.tree_map(lambda v: jnp.asarray(v)[None], hidden)
        outputs = self._apply(self.params, obs_b, hidden_b)
        out = {}
        for k, v in outputs.items():
            if v is None:
                continue
            if k == 'hidden':
                out[k] = jax.tree_util.tree_map(lambda a: np.asarray(a)[0], v)
            else:
                out[k] = np.asarray(v)[0]
        return out

    def batch_inference(self, obs, hidden=None) -> Dict[str, Any]:
        """Batched actor path: leading batch dim already present."""
        self.ensure_params(map_structure(lambda v: v[0], obs))
        outputs = self._apply(self.params, jax.tree_util.tree_map(jnp.asarray, obs), hidden)
        return {k: v for k, v in outputs.items() if v is not None}

    # -- wire format ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Architecture name + non-default constructor config + raw param
        bytes (safe to ship cross-process). The config entry carries plain
        (str/int/float/bool) dataclass fields that differ from the
        architecture's defaults — e.g. GeisterNet(norm_kind='batch') — so a
        worker rebuilding the model from the wire gets the same module
        function, not the registry default (param trees differ between norm
        kinds; silently rebuilding the default would fail deserialization
        at best)."""
        assert self.params is not None, 'snapshot of uninitialized model'
        snap = {
            'architecture': model_zoo.architecture_name(self.module),
            'params': serialization.to_bytes(self.params),
        }
        config = module_config(self.module)
        if config:
            snap['config'] = config
        return snap

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any], example_obs,
                      params_template=None) -> 'ModelWrapper':
        """Rebuild a model from an architecture-name + params-bytes snapshot.

        ``params_template`` (a params pytree of the same architecture) skips
        the module.init trace — callers that materialize many snapshots of
        one architecture (e.g. the worker model vault, every epoch) pay the
        init exactly once."""
        module = model_zoo.build(snap['architecture'], **snap.get('config', {}))
        wrapper = cls(module)
        if params_template is None:
            wrapper.ensure_params(example_obs)
            wrapper.params = serialization.from_bytes(wrapper.params,
                                                      snap['params'])
        else:
            wrapper.params = serialization.from_bytes(params_template,
                                                      snap['params'])
        return wrapper

    def load_params_bytes(self, raw: bytes, example_obs) -> None:
        self.ensure_params(example_obs)
        self.params = serialization.from_bytes(self.params, raw)

    def params_bytes(self) -> bytes:
        assert self.params is not None
        return serialization.to_bytes(self.params)


class RandomModel:
    """Non-parametric stand-in: replays zero outputs shaped like a probe
    inference, which after legal-action masking yields uniform random play
    (reference model.py:65-74)."""

    def __init__(self, wrapper: ModelWrapper, example_obs):
        probe = wrapper.inference(example_obs, wrapper.init_hidden())
        self.output_dict = {k: np.zeros_like(v) for k, v in probe.items()
                            if k != 'hidden'}

    def init_hidden(self, batch_shape=None):
        return None

    def inference(self, *args, **kwargs):
        return self.output_dict
