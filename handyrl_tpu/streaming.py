"""Streaming partial-episode ingest: learner-side chunk reassembly.

With the ``streaming:`` config block enabled, workers (and the device
actor backend) flush fixed-T window chunks of in-flight episodes through
the existing upload path instead of holding completed episodes
(generation.py ``build_chunk``). This module owns the learner half: the
:class:`ChunkAssembler` merges arriving chunks back into episodes.

Two invariants carry the whole design:

* **Purity** — a host-path episode is a pure function of
  (seed, sample_key, params), so chunk boundaries (a pure function of the
  ply index and T) are too. A re-issued attempt of a stranded task
  regenerates byte-identical chunks under the SAME sample_key; assemblies
  are therefore keyed by sample_key and duplicate chunks (re-issue
  overlap, resend-buffer replays, restart recovery) merge instead of
  double-counting. Device-actor streams carry ``record_version`` — their
  episodes are NOT sample_key-pure (the block seed differs per attempt) —
  so those assemblies key by task_id and never merge across attempts.

* **Byte-identity** — chunk moments ship with ``'return': None`` and the
  final chunk carries the outcome; reassembly concatenates the decoded
  windows and hands them to ``generation.finalize_episode_record`` — the
  same return fill, block grid and compression every whole-episode
  producer uses — so the reassembled record's training-visible bytes (the
  decoded moment stream, filled returns, outcome) are bit-identical to a
  whole-episode upload's. The raw bz2 block bytes are the canonical
  (pickle fixed-point) encoding, which can differ from the worker's
  fresh-object encoding only in pickle memo layout, never in content
  (pinned by tests/test_streaming.py).

While an episode is in flight the assembler exposes a PARTIAL buffer
entry (``'partial': True``, provisional zero outcome, returns None) made
of the contiguous chunk prefix: streaming.chunk_steps is validated to be
a multiple of compress_steps, so the chunk-local bz2 blocks land on the
whole-episode block grid and ``ops/batch.py`` windows into them
unchanged. Entries are mutated append-only in an order safe for the
concurrent batcher readers (blocks first, then the step count).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from . import telemetry
from .generation import finalize_episode_record
from .ops.batch import decompress_moments


def streaming_enabled(args: Dict[str, Any]) -> bool:
    return bool((args.get('streaming') or {}).get('enabled'))


def chunk_key(chunk: Dict[str, Any]):
    """Assembly/dedupe key for one chunk.

    Host-contract streams (pure per sample_key) merge across re-issued
    attempts; ``record_version``-stamped device streams are per-attempt
    and key by task_id. None for a chunk that carries neither key (never
    produced by this codebase; screened out defensively)."""
    args = chunk.get('args') or {}
    skey = args.get('sample_key')
    if not chunk.get('record_version') and skey is not None:
        return ('k', int(skey))
    tid = args.get('task_id')
    if tid is not None:
        return ('t', int(tid))
    return None


class ChunkAssembler:
    """Merge streamed chunks back into episode records.

    ``add`` is called from the learner's server thread only (and from
    spool recovery before the fleet attaches); the entries it exposes are
    read concurrently by the batcher threads. One assembler per learner.
    """

    def __init__(self, args: Dict[str, Any], check_finite: bool = True,
                 clock=time.time):
        self.args = args
        self._check_finite = bool(check_finite)
        self._clock = clock
        self._open: Dict[Any, dict] = {}
        self._m_open = telemetry.gauge('streaming_open_assemblies')
        self._m_done = telemetry.counter(
            'streaming_reassembled_episodes_total')

    # -- ingest -----------------------------------------------------------

    def add(self, chunk: Dict[str, Any], mark: Optional[int] = None) -> dict:
        """Fold one (already ledger-screened) chunk into its assembly.

        ``mark`` is the spool index the chunk was WAL'd under (the GC
        horizon must not pass an open assembly's first mark). Returns a
        dict with ``status``:

        * ``'dropped'`` — unkeyed/duplicate/poisoned chunk, nothing to do;
        * ``'open'`` — partial data landed; ``entry`` is the live buffer
          entry and ``new`` says whether the caller must insert it;
        * ``'complete'`` — the episode reassembled; ``record`` is the
          canonical record (already swapped into ``entry``), or None when
          a poisoned chunk froze the assembly (the task still completes);
          ``final_args`` is the closing chunk attempt's task args.
        """
        key = chunk_key(chunk)
        if key is None:
            return {'status': 'dropped'}
        asm = self._open.get(key)
        if asm is None:
            asm = self._open[key] = {
                'chunks': {}, 'final_ci': None, 'outcome': None,
                'final_args': None, 'next': 0, 'entry': None,
                'mark': mark, 'poisoned': False, 'touched': self._clock(),
                'stamped': bool(chunk.get('record_version')),
            }
            self._m_open.set(len(self._open))
        asm['touched'] = self._clock()
        if mark is not None and (asm['mark'] is None or mark < asm['mark']):
            asm['mark'] = mark
        ci = int(chunk.get('chunk', 0))
        if ci in asm['chunks']:
            return {'status': 'dropped'}     # duplicate window (merged)
        if self._check_finite:
            from . import guard as guard_mod
            if not guard_mod.episode_is_finite(
                    {'outcome': chunk.get('outcome'),
                     'moment': chunk.get('moment') or []}):
                # freeze: the clean contiguous prefix stays usable, but no
                # further data is exposed and the record is dropped whole
                asm['poisoned'] = True
        try:
            moments = ([] if asm['poisoned']
                       else decompress_moments(chunk.get('moment') or []))
        except Exception:
            asm['poisoned'] = True
            moments = []
        asm['chunks'][ci] = {'moments': moments,
                             'blocks': list(chunk.get('moment') or [])}
        if chunk.get('final'):
            asm['final_ci'] = ci
            asm['outcome'] = chunk.get('outcome')
            asm['final_args'] = dict(chunk.get('args') or {})
        new = self._expose(asm, chunk)
        fin = asm['final_ci']
        if fin is not None:
            if asm['poisoned']:
                # a poisoned stream still closes its TASK once every
                # window landed (mirroring the whole-episode path, where
                # admit completes the task before the guard drops the
                # record) — otherwise the deadline loop would re-issue
                # the same deterministic poison forever
                if all(c in asm['chunks'] for c in range(fin + 1)):
                    return self._complete(key, asm, new)
            elif asm['next'] > fin:
                return self._complete(key, asm, new)
        return {'status': 'open', 'entry': asm['entry'], 'new': new}

    def _expose(self, asm: dict, chunk: Dict[str, Any]) -> bool:
        """Extend the live buffer entry with the contiguous chunk prefix.

        Mutation order is the thread-safety contract with the batcher
        readers: blocks are appended BEFORE the step count moves, so a
        concurrent window selection never indexes past decoded data."""
        new = False
        now = time.time()
        while not asm['poisoned'] and asm['next'] in asm['chunks']:
            ci = asm['next']
            moments = asm['chunks'][ci]['moments']
            blocks = asm['chunks'][ci]['blocks']
            entry = asm['entry']
            if entry is None and moments:
                players = list(moments[0]['return'].keys())
                entry = asm['entry'] = {
                    'args': dict(chunk.get('args') or {}),
                    'outcome': {p: 0.0 for p in players},   # provisional
                    'moment': [], 'steps': 0, 'partial': True,
                    'recv_time': now, 'chunk_recv': [],
                    'chunk_steps': int((self.args.get('streaming') or {})
                                       .get('chunk_steps', 32)),
                }
                if asm['stamped']:
                    entry['record_version'] = 1
                new = True
            if entry is not None:
                entry['moment'].extend(blocks)
                entry['chunk_recv'].append(now)
                entry['steps'] += len(moments)
            asm['next'] = ci + 1
        return new

    def _complete(self, key, asm: dict, new: bool) -> dict:
        """All windows landed: build the canonical record and swap it into
        the live entry (readers mid-swap see a consistent prefix)."""
        self._open.pop(key, None)
        self._m_open.set(len(self._open))
        record = None
        entry = asm['entry']
        if not asm['poisoned']:
            moments: List[dict] = []
            for ci in range(asm['final_ci'] + 1):
                moments.extend(asm['chunks'][ci]['moments'])
            record = finalize_episode_record(
                asm['outcome'], moments, self.args, asm['final_args'])
        if record is not None:
            if asm['stamped']:
                record['record_version'] = 1
            self._m_done.inc()
            if entry is None:
                # single-shot completion (episode shorter than T, or a
                # recovery replay): expose the finished record directly
                entry = asm['entry'] = dict(record)
                entry['chunk_recv'] = [time.time()]
                entry['chunk_steps'] = int(
                    (self.args.get('streaming') or {})
                    .get('chunk_steps', 32))
                new = True
            else:
                entry['args'] = record['args']
                entry['moment'] = record['moment']
                entry['outcome'] = record['outcome']
                entry['steps'] = record['steps']
                entry.pop('partial', None)
        return {'status': 'complete', 'record': record, 'entry': entry,
                'final_args': asm['final_args'], 'new': new}

    # -- bookkeeping ------------------------------------------------------

    def min_open_mark(self) -> Optional[int]:
        """Lowest spool index any open assembly's chunks were WAL'd under:
        the epoch GC horizon is held back to it so a restart can still
        replay every chunk of a partially-delivered episode."""
        marks = [asm['mark'] for asm in self._open.values()
                 if asm['mark'] is not None]
        return min(marks) if marks else None

    def open_count(self) -> int:
        return len(self._open)

    def reap(self, older_than: float) -> list:
        """Abandon assemblies untouched for ``older_than`` seconds;
        returns the reaped keys (the caller drops their ledger book).

        A host-contract assembly is normally finished by the re-issued
        attempt (same sample_key), but a device-actor stream whose attempt
        died can never complete (the re-issue keys a new task_id) — and
        either way an assembly must not pin the spool GC horizon forever.
        The exposed partial entry (clean, screened data) stays in the
        buffer with its provisional outcome."""
        now = self._clock()
        stale = [key for key, asm in self._open.items()
                 if now - asm['touched'] > older_than]
        for key in stale:
            self._open.pop(key, None)
            telemetry.counter('streaming_abandoned_assemblies_total').inc()
        if stale:
            self._m_open.set(len(self._open))
        return stale
