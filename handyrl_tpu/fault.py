"""Fault-tolerance primitives for the distributed actor fleet.

Three small pieces shared by the transport (connection.py), the actor tree
(worker.py) and the learner's RPC server (train.py):

* :class:`Backoff` — exponential reconnect delays with jitter, so a fleet
  of gathers that lost the same server does not stampede it in lockstep
  when it comes back.

* :class:`TaskLedger` — the server's outstanding-task book. Every
  generation/eval assignment is tracked per endpoint with a deadline;
  tasks stranded by a detach or a deadline miss are re-queued for the next
  'args' request, and late duplicate uploads (a gather resending an RPC it
  never saw the ack for) are dropped exactly once — so ``num_episodes`` /
  ``num_results`` accounting converges instead of drifting when actors
  churn (the seed assigned tasks fire-and-forget, train.py:1523-1548).

* :func:`parse_chaos` — the ``HANDYRL_TPU_CHAOS`` fault-injection knobs
  used by the chaos tests and available for soak runs:
  ``kill_gather=<mean s>`` (the worker host SIGKILLs a random gather child
  on an exponential clock), ``kill_worker=<mean s>`` (each worker process
  self-destructs after an exponentially distributed lifetime),
  ``max_kills=<n>``, ``seed=<n>``.
"""

from __future__ import annotations

import copy
import os
import random
import time
from collections import defaultdict, deque
from typing import Any, Dict, Optional


class Backoff:
    """Exponential backoff with jitter: delays double from ``initial`` up to
    ``maximum``; each delay is uniformly jittered into
    ``[(1 - jitter) * d, d]`` so synchronized failures desynchronize."""

    def __init__(self, initial: float = 1.0, maximum: float = 30.0,
                 factor: float = 2.0, jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.initial = float(initial)
        self.maximum = float(maximum)
        self.factor = float(factor)
        self.jitter = float(jitter)
        self._rng = rng or random
        self._cur = self.initial

    def next_delay(self) -> float:
        base = min(self._cur, self.maximum)
        self._cur = min(self._cur * self.factor, self.maximum)
        return base * (1.0 - self.jitter * self._rng.random())

    def reset(self):
        self._cur = self.initial


class TaskLedger:
    """Outstanding-task book for the learner's 4-RPC server.

    ``assign`` stamps a fresh ``task_id`` into the task payload and books it
    against the endpoint it was sent to, with a deadline. ``admit`` filters
    an upload batch: items completing a booked task pass (and close the
    book), items with an unknown ``task_id`` are duplicates (a resent RPC
    whose first copy already landed) and are dropped, items with no
    ``task_id`` pass untouched (pre-ledger peers). ``fail_endpoint`` /
    ``reap`` move stranded tasks to the re-issue queue, which ``next_reissue``
    serves ahead of fresh assignments — re-issues must NOT re-increment the
    server's num_episodes/num_results counters, which is exactly why they
    bypass the fresh-task construction path.
    """

    def __init__(self, deadline: float = 300.0, clock=time.time):
        self.deadline = float(deadline)
        self._clock = clock
        self._tasks: Dict[int, tuple] = {}          # tid -> (endpoint, base, expires)
        self._by_endpoint: Dict[Any, set] = defaultdict(set)
        self._reissue: deque = deque()
        self._next_tid = 0
        self.stats: Dict[str, int] = {
            'assigned': 0, 'completed': 0, 'duplicates': 0,
            'reissued': 0, 'expired': 0, 'endpoint_failures': 0,
        }

    # -- assignment / completion --

    def assign(self, endpoint, role_args: Dict[str, Any]) -> int:
        """Book ``role_args`` against ``endpoint`` and stamp its task_id."""
        tid, self._next_tid = self._next_tid, self._next_tid + 1
        base = copy.deepcopy(
            {k: v for k, v in role_args.items() if k != 'task_id'})
        role_args['task_id'] = tid
        self._tasks[tid] = (endpoint, base, self._clock() + self.deadline)
        self._by_endpoint[endpoint].add(tid)
        self.stats['assigned'] += 1
        return tid

    def complete(self, tid) -> bool:
        """Close the book on ``tid``. False (and counted) for duplicates."""
        entry = self._tasks.pop(tid, None)
        if entry is None:
            self.stats['duplicates'] += 1
            return False
        owners = self._by_endpoint.get(entry[0])
        if owners is not None:
            owners.discard(tid)
            if not owners:
                self._by_endpoint.pop(entry[0], None)
        self.stats['completed'] += 1
        return True

    def admit(self, items):
        """Filter an upload batch through the book (see class docstring)."""
        out = []
        for item in items:
            if item is None:            # failed episode: deadline re-issues it
                out.append(item)
                continue
            tid = (item.get('args') or {}).get('task_id')
            if tid is None or self.complete(tid):
                out.append(item)
        return out

    # -- loss handling --

    def _strand(self, tid):
        endpoint, base, _expires = self._tasks.pop(tid)
        owners = self._by_endpoint.get(endpoint)
        if owners is not None:
            owners.discard(tid)
            if not owners:
                self._by_endpoint.pop(endpoint, None)
        self._reissue.append(base)
        self.stats['reissued'] += 1

    def fail_endpoint(self, endpoint) -> int:
        """Re-queue every task booked against a detached endpoint."""
        tids = list(self._by_endpoint.get(endpoint, ()))
        for tid in tids:
            self._strand(tid)
        if tids:
            self.stats['endpoint_failures'] += 1
        return len(tids)

    def reap(self, now: Optional[float] = None) -> int:
        """Re-queue every task past its deadline (slow/silently-lost work)."""
        now = self._clock() if now is None else now
        expired = [tid for tid, (_ep, _base, exp) in self._tasks.items()
                   if exp <= now]
        for tid in expired:
            self._strand(tid)
        self.stats['expired'] += len(expired)
        return len(expired)

    def next_reissue(self) -> Optional[Dict[str, Any]]:
        return self._reissue.popleft() if self._reissue else None

    # -- observability --

    def outstanding(self) -> int:
        return len(self._tasks)

    def pending_reissue(self) -> int:
        return len(self._reissue)


def parse_chaos(spec: Optional[str] = None) -> Dict[str, float]:
    """Parse ``HANDYRL_TPU_CHAOS`` (or an explicit spec string) into a dict
    of float knobs; empty/unset means chaos off. Malformed entries are
    ignored rather than crashing a production run."""
    if spec is None:
        spec = os.environ.get('HANDYRL_TPU_CHAOS', '')
    out: Dict[str, float] = {}
    for part in (spec or '').split(','):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition('=')
        try:
            out[key.strip()] = float(value)
        except ValueError:
            print('ignoring malformed HANDYRL_TPU_CHAOS entry %r' % part)
    return out
