"""Fault-tolerance primitives for the distributed actor fleet.

Three small pieces shared by the transport (connection.py), the actor tree
(worker.py) and the learner's RPC server (train.py):

* :class:`Backoff` — exponential reconnect delays with jitter, so a fleet
  of gathers that lost the same server does not stampede it in lockstep
  when it comes back.

* :class:`TaskLedger` — the server's outstanding-task book. Every
  generation/eval assignment is tracked per endpoint with a deadline;
  tasks stranded by a detach or a deadline miss are re-queued for the next
  'args' request, and late duplicate uploads (a gather resending an RPC it
  never saw the ack for) are dropped exactly once — so ``num_episodes`` /
  ``num_results`` accounting converges instead of drifting when actors
  churn (the seed assigned tasks fire-and-forget, train.py:1523-1548).

* :class:`FleetController` — the learner's per-host health state machine
  (healthy / degraded / draining / quarantined), fed by ledger strandings
  and heartbeat fault telemetry. It drives the elastic assignment policy:
  flapping hosts stop receiving fresh tasks (drain-before-detach), sit out
  a quarantine period, and are re-admitted afterwards.

* :func:`parse_chaos` — the ``HANDYRL_TPU_CHAOS`` fault-injection knobs
  used by the chaos tests and available for soak runs:
  ``kill_gather=<mean s>`` (the worker host SIGKILLs a random gather child
  on an exponential clock), ``kill_worker=<mean s>`` (each worker process
  self-destructs after an exponentially distributed lifetime),
  ``max_kills=<n>``, ``seed=<n>``; plus the inference-tier injectors
  ``enginekill=<mean s>`` (the host InferenceEngine thread crashes),
  ``enginestall=<mean s>`` (the engine wedges mid-tick while holding
  requests), ``enginestall_secs=<s>`` (length of an injected stall) and
  ``engine_max_faults=<n>`` (per-process injection budget) — consumed by
  ``inference.EngineSupervisor``.
"""

from __future__ import annotations

import copy
import os
import random
import time
from collections import defaultdict, deque
from typing import Any, Dict, Optional

from . import telemetry
from .utils.fs import atomic_write_bytes

# sentinel endpoint for tasks restored from a persisted ledger snapshot:
# their original endpoints died with the previous learner process
RESTORED_ENDPOINT = '<restored>'


class Backoff:
    """Exponential backoff with jitter: delays double from ``initial`` up to
    ``maximum``; each delay is uniformly jittered into
    ``[(1 - jitter) * d, d]`` so synchronized failures desynchronize."""

    def __init__(self, initial: float = 1.0, maximum: float = 30.0,
                 factor: float = 2.0, jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.initial = float(initial)
        self.maximum = float(maximum)
        self.factor = float(factor)
        self.jitter = float(jitter)
        self._rng = rng or random
        self._cur = self.initial

    def next_delay(self) -> float:
        base = min(self._cur, self.maximum)
        self._cur = min(self._cur * self.factor, self.maximum)
        return base * (1.0 - self.jitter * self._rng.random())

    def reset(self):
        self._cur = self.initial


class TaskLedger:
    """Outstanding-task book for the learner's 4-RPC server.

    ``assign`` stamps a fresh ``task_id`` into the task payload and books it
    against the endpoint it was sent to, with a deadline. ``admit`` filters
    an upload batch: items completing a booked task pass (and close the
    book), items with an unknown ``task_id`` are duplicates (a resent RPC
    whose first copy already landed) and are dropped, items with no
    ``task_id`` pass untouched (pre-ledger peers). ``fail_endpoint`` /
    ``reap`` move stranded tasks to the re-issue queue, which ``next_reissue``
    serves ahead of fresh assignments — re-issues must NOT re-increment the
    server's num_episodes/num_results counters, which is exactly why they
    bypass the fresh-task construction path.

    With a :class:`LedgerJournal` attached (``self.journal``) the book is
    durable: assignments and strandings journal immediately, completions
    are batched (``flush_journal`` — the server calls it AFTER the
    episode spool append, so "admitted but completion unjournaled" is the
    only crash window and spool recovery closes it by cancelling the
    spooled task_ids). ``restore_state`` repopulates the book from a
    snapshot+delta replay: restored outstanding tasks re-issue with their
    ORIGINAL payloads — including the server-stamped ``sample_key`` —
    ahead of fresh work, unless a reattached gather's replayed upload
    completes them first.
    """

    def __init__(self, deadline: float = 300.0, clock=time.time):
        self.deadline = float(deadline)
        self._clock = clock
        self._tasks: Dict[int, tuple] = {}          # tid -> (endpoint, base, expires)
        self._by_endpoint: Dict[Any, set] = defaultdict(set)
        self._reissue: deque = deque()
        self._restored_reissue: deque = deque()     # (tid, base) from restore
        self._strandings: deque = deque(maxlen=4096)  # (endpoint, reason, t)
        self._next_tid = 0
        self.journal: Optional['LedgerJournal'] = None
        self._pending_complete: list = []   # ('c', tid) / ('q', key) ops
        # streaming ingest (streaming.py): per-assembly chunk-index dedupe
        # book, keyed like the assembler (sample_key for host-contract
        # streams, task_id for device ones). Closed keys move to a bounded
        # ring so resend-buffer replays of a finished episode's chunks
        # still screen as duplicates.
        self._chunks: Dict[Any, set] = {}
        self._closed_chunk_keys: 'deque' = deque(maxlen=4096)
        self._closed_chunk_set: set = set()
        self.stats: Dict[str, int] = {
            'assigned': 0, 'completed': 0, 'duplicates': 0,
            'reissued': 0, 'expired': 0, 'endpoint_failures': 0,
        }

    # -- assignment / completion --

    def assign(self, endpoint, role_args: Dict[str, Any]) -> int:
        """Book ``role_args`` against ``endpoint`` and stamp its task_id.

        The booked copy is the FULL role_args (deep-copied, minus the
        task_id): a re-issue replays it verbatim, so server-stamped fields
        like the league's opponent assignment (``league_opponent`` /
        ``league_seat`` / ``opponent``, train.py server()) survive a
        stranded task bit-identically — the replacement worker plays the
        same member, and rating accounting never double-books a draw."""
        tid, self._next_tid = self._next_tid, self._next_tid + 1
        base = copy.deepcopy(
            {k: v for k, v in role_args.items() if k != 'task_id'})
        role_args['task_id'] = tid
        self._tasks[tid] = (endpoint, base, self._clock() + self.deadline)
        self._by_endpoint[endpoint].add(tid)
        self.stats['assigned'] += 1
        if self.journal is not None:
            self.journal.record('a', tid, base)
        if telemetry.trace_enabled():
            # the trace context is born here: the server-stamped sample_key
            # becomes the trace_id every later hop derives independently
            ttid = telemetry.episode_trace_id(role_args)
            if ttid:
                telemetry.trace_event('task_assign', trace_id=ttid,
                                      task_id=tid)
        return tid

    def complete(self, tid) -> bool:
        """Close the book on ``tid``. False (and counted) for duplicates."""
        entry = self._tasks.pop(tid, None)
        if entry is None:
            self.stats['duplicates'] += 1
            return False
        owners = self._by_endpoint.get(entry[0])
        if owners is not None:
            owners.discard(tid)
            if not owners:
                self._by_endpoint.pop(entry[0], None)
        self.stats['completed'] += 1
        if self.journal is not None:
            # deferred: the server flushes AFTER the spool append, so a
            # kill between admit and flush recovers the episode from the
            # spool (whose task_id then cancels the restored book entry)
            self._pending_complete.append(('c', tid))
        return True

    def admit(self, items):
        """Filter an upload batch through the book (see class docstring).
        Each admitted booked item also closes its trace chain's delivery
        hop: an ``ingest`` trace event stamped with the shared trace_id."""
        out = []
        tracing = telemetry.trace_enabled()
        for item in items:
            if item is None:            # failed episode: deadline re-issues it
                out.append(item)
                continue
            args = item.get('args') or {}
            tid = args.get('task_id')
            if tid is None or self.complete(tid):
                out.append(item)
                if tracing and tid is not None:
                    ttid = telemetry.episode_trace_id(args)
                    if ttid:
                        telemetry.trace_event('ingest', trace_id=ttid,
                                              task_id=tid)
        return out

    def admit_chunks(self, items):
        """Duplicate-screen a streamed chunk batch (streaming.py).

        Unlike :meth:`admit`, a chunk does NOT close its task — the task
        completes when the assembler reports the episode whole
        (:meth:`complete_chunked`). The screen is per (assembly key,
        chunk_index): re-issued attempts of a pure host-contract task
        share the sample_key, so their regenerated chunks merge here
        instead of double-counting; chunks of an already-closed assembly
        (resend-buffer replays after completion) drop like any duplicate
        upload. Accepted deliveries journal as ``p`` ops, so a restarted
        learner's screen picks up exactly where the book left off."""
        from .streaming import chunk_key
        out = []
        tracing = telemetry.trace_enabled()
        for chunk in items:
            if chunk is None:
                continue
            key = chunk_key(chunk)
            ci = int(chunk.get('chunk', 0))
            if key is None or key in self._closed_chunk_set \
                    or ci in self._chunks.get(key, ()):
                self.stats['duplicates'] += 1
                telemetry.counter('chunk_duplicates_total').inc()
                continue
            self._chunks.setdefault(key, set()).add(ci)
            if self.journal is not None:
                # deferred like completions: the 'p' op must land AFTER
                # the spool append, or a kill between them would leave a
                # delivery journaled whose bytes no WAL replay can produce
                self._pending_complete.append(
                    ('p', (int((chunk.get('args') or {})
                               .get('task_id') or -1), list(key), ci)))
            telemetry.counter('chunks_ingested_total').inc()
            out.append(chunk)
            if tracing:
                args = chunk.get('args') or {}
                ttid = telemetry.episode_trace_id(args)
                if ttid:
                    telemetry.trace_event('ingest', trace_id=ttid,
                                          task_id=args.get('task_id'),
                                          chunk=ci)
        return out

    def seed_chunk(self, key, ci: int):
        """Re-seed the dedupe book during spool recovery (the replayed
        chunks were already journaled; no new delta op)."""
        self._chunks.setdefault(key, set()).add(int(ci))

    def complete_chunked(self, key, tid) -> bool:
        """Close the book on a fully-reassembled streamed episode: the
        owning task completes (the final chunk's tid, or — when that
        attempt's book entry already closed — whichever open task still
        carries the assembly's sample_key), and the assembly key moves to
        the closed ring so stragglers screen as duplicates."""
        done = tid is not None and self.complete(tid)
        if not done and isinstance(key, (list, tuple)) \
                and len(key) == 2 and key[0] == 'k':
            for other_tid, (_ep, base, _exp) in list(self._tasks.items()):
                if isinstance(base, dict) \
                        and base.get('sample_key') == key[1] \
                        and base.get('role') == 'g':
                    done = self.complete(other_tid)
                    break
        k = self._close_chunk_key(key)
        self._pending_complete.append(('q', k))
        return done

    def _close_chunk_key(self, key):
        """Drop ``key``'s chunk book and move it into the bounded closed
        ring (stragglers/resends of a finished assembly screen as dups)."""
        k = tuple(key) if isinstance(key, list) else key
        self._chunks.pop(k, None)
        if k not in self._closed_chunk_set:
            if len(self._closed_chunk_keys) == self._closed_chunk_keys.maxlen:
                self._closed_chunk_set.discard(self._closed_chunk_keys[0])
            self._closed_chunk_keys.append(k)
            self._closed_chunk_set.add(k)
        return k

    def seed_closed_chunks(self, keys):
        """Mark assemblies that spool recovery already completed as closed
        (no journal op: the recovery feed re-derives them every restart),
        so a reattached gather's resend replays screen as duplicates
        instead of re-assembling an already-counted episode."""
        for key in keys:
            self._close_chunk_key(key)

    def abandon_chunks(self, key):
        """Drop an abandoned assembly's dedupe state (assembler reap)."""
        k = tuple(key) if isinstance(key, list) else key
        if self._chunks.pop(k, None) is not None:
            self._pending_complete.append(('q', k))

    # -- loss handling --

    def _strand(self, tid, reason: str = 'detach'):
        endpoint, base, _expires = self._tasks.pop(tid)
        owners = self._by_endpoint.get(endpoint)
        if owners is not None:
            owners.discard(tid)
            if not owners:
                self._by_endpoint.pop(endpoint, None)
        self._reissue.append(base)
        self._strandings.append((endpoint, reason, self._clock()))
        self.stats['reissued'] += 1
        if self.journal is not None:
            self.journal.record('s', tid)
        telemetry.record_event('stranding', str(endpoint), reason=reason)

    def fail_endpoint(self, endpoint) -> int:
        """Re-queue every task booked against a detached endpoint."""
        tids = list(self._by_endpoint.get(endpoint, ()))
        for tid in tids:
            self._strand(tid)
        if tids:
            self.stats['endpoint_failures'] += 1
        return len(tids)

    def reap(self, now: Optional[float] = None) -> int:
        """Re-queue every task past its deadline (slow/silently-lost work)."""
        now = self._clock() if now is None else now
        expired = [tid for tid, (_ep, _base, exp) in self._tasks.items()
                   if exp <= now]
        for tid in expired:
            self._strand(tid, reason='deadline')
        self.stats['expired'] += len(expired)
        return len(expired)

    def next_reissue(self) -> Optional[Dict[str, Any]]:
        # restored outstanding tasks (a previous learner's in-flight book)
        # go first; cancel() is the guard — a None return means the task
        # already closed (replayed upload / spool recovery / reap), so a
        # restored entry is never issued twice
        while self._restored_reissue:
            tid, base = self._restored_reissue.popleft()
            if self.cancel(tid) is not None:
                self.stats['reissued'] += 1
                telemetry.record_event('stranding', RESTORED_ENDPOINT,
                                       reason='restart')
                return copy.deepcopy(base)
        return self._reissue.popleft() if self._reissue else None

    def cancel(self, tid) -> Optional[Dict[str, Any]]:
        """Silently close ``tid`` (no duplicate counting, no re-issue);
        returns the booked base payload, or None when the book holds no
        such task. Used by spool recovery: an episode that reached the
        spool must neither re-issue nor double-count."""
        entry = self._tasks.pop(tid, None)
        if entry is None:
            return None
        owners = self._by_endpoint.get(entry[0])
        if owners is not None:
            owners.discard(tid)
            if not owners:
                self._by_endpoint.pop(entry[0], None)
        if self.journal is not None:
            self.journal.record('x', tid)
        return entry[1]

    # -- persistence --

    def flush_journal(self):
        """Journal the batched completions (called by the server after the
        spool append that makes those completions safe to forget)."""
        if self.journal is None or not self._pending_complete:
            self._pending_complete = []
            return
        for op, val in self._pending_complete:
            if op == 'q':
                # streamed assembly closed/abandoned: drop its chunk book
                self.journal.record('q', -1, key=list(val))
            elif op == 'p':
                tid, key, ci = val
                self.journal.record('p', tid, key=key, ci=ci)
            else:
                self.journal.record('c', val)
        self._pending_complete = []

    def snapshot_state(self) -> Dict[str, Any]:
        """The durable book: outstanding tasks, the re-issue queue, and
        the tid high-water mark (epoch-synchronous; deltas journal the
        between-epoch churn)."""
        state = {
            'tasks': {tid: entry[1] for tid, entry in self._tasks.items()},
            'reissue': [copy.deepcopy(b) for b in self._reissue],
            'next_tid': self._next_tid,
        }
        if self._chunks:
            # streamed-ingest dedupe book: [key, [chunk indices]] pairs
            # (list form — msgpack maps cannot key on tuples)
            state['chunks'] = [[list(k), sorted(cis)]
                               for k, cis in self._chunks.items()]
        return state

    def restore_state(self, state: Dict[str, Any]):
        """Repopulate the book from a :meth:`LedgerJournal.load` replay.
        Restored tasks are booked under :data:`RESTORED_ENDPOINT` with a
        fresh deadline and queued for priority re-issue (see
        ``next_reissue``); the stale-book re-issue queue is carried over
        verbatim."""
        now = self._clock()
        for tid, base in sorted((state.get('tasks') or {}).items()):
            tid = int(tid)
            self._tasks[tid] = (RESTORED_ENDPOINT, base,
                                now + self.deadline)
            self._by_endpoint[RESTORED_ENDPOINT].add(tid)
            self._restored_reissue.append((tid, base))
        self._reissue.extend(state.get('reissue') or ())
        self._next_tid = max(self._next_tid,
                             int(state.get('next_tid') or 0))
        for pair in state.get('chunks') or ():
            try:
                key, cis = pair
            except Exception:
                continue
            k = (str(key[0]), int(key[1]))
            self._chunks.setdefault(k, set()).update(int(c) for c in cis)

    # -- observability --

    def outstanding(self) -> int:
        return len(self._tasks)

    def outstanding_by_endpoint(self) -> Dict[Any, int]:
        """Open task count per endpoint (the fleet controller's drain
        policy waits on this before quarantining a flapping host)."""
        return {ep: len(tids) for ep, tids in self._by_endpoint.items()
                if tids}

    def pending_reissue(self) -> int:
        return len(self._reissue)

    def drain_stranding_events(self):
        """Consume the (endpoint, reason, time) stranding journal — one
        entry per task that had to be re-issued, attributed to the endpoint
        that lost it (the fleet controller's fault signal)."""
        events = list(self._strandings)
        self._strandings.clear()
        return events


class LedgerJournal:
    """Durable storage for the :class:`TaskLedger` book under ``model_dir``.

    Two files, mirroring the checkpoint cadence:

    * ``ledger.snap`` — the full book (outstanding tasks + re-issue queue
      + tid high-water mark + learner counters), atomically republished at
      every epoch sync (``snapshot``);
    * ``ledger.delta.wal`` — CRC-framed msgpack records journaled between
      snapshots: ``a`` (assign: tid + base payload), ``c`` (complete),
      ``s`` (strand → re-issue), ``x`` (cancel, no re-issue), ``p``
      (streamed chunk delivered: assembly key + chunk index) and ``q``
      (streamed assembly closed, its chunk book dropped). One
      O_APPEND write per record, no per-record fsync (same SIGKILL-vs-
      machine-crash stance as the episode spool); a torn tail truncates
      on load.

    msgpack — not JSON — because task payloads carry int-keyed dicts
    (``model_id``) that a JSON round trip would silently stringify,
    breaking the byte-identical re-issue contract. ``snapshot`` lands the
    snap BEFORE truncating the delta journal, and every delta op replays
    idempotently over a snapshot that already folded it in, so a crash
    between the two publishes still loads to the same book.
    """

    SNAP = 'ledger.snap'
    DELTA = 'ledger.delta.wal'

    def __init__(self, model_dir: str):
        # late import: connection pulls msgpack/numpy; fault stays
        # importable without them until a journal is actually built
        from .connection import pack, unpack
        from .utils.fs import append_framed_record, open_append, \
            read_framed_records
        self._pack, self._unpack = pack, unpack
        self._append_record = append_framed_record
        self._open_append = open_append
        self._read_records = read_framed_records
        self.snap_path = os.path.join(model_dir, self.SNAP)
        self.delta_path = os.path.join(model_dir, self.DELTA)
        self._delta_fd: Optional[int] = None

    def exists(self) -> bool:
        return (os.path.exists(self.snap_path)
                or os.path.exists(self.delta_path))

    def record(self, op: str, tid: int, base: Optional[dict] = None,
               **extra):
        """Append one delta op in a single torn-safe write. ``extra``
        carries op-specific fields (the streamed-chunk ``p``/``q`` ops'
        assembly ``key`` and chunk index ``ci``)."""
        if self._delta_fd is None:
            os.makedirs(os.path.dirname(self.delta_path) or '.',
                        exist_ok=True)
            self._delta_fd = self._open_append(self.delta_path)
        rec: Dict[str, Any] = {'op': op, 'tid': int(tid)}
        if base is not None:
            rec['base'] = base
        if extra:
            rec.update(extra)
        self._append_record(self._delta_fd, self._pack(rec))

    def snapshot(self, state: Dict[str, Any]):
        """Atomically republish the full book, then truncate the delta
        journal (snap first: a crash between the two replays stale deltas
        idempotently over the fresh snap)."""
        os.makedirs(os.path.dirname(self.snap_path) or '.', exist_ok=True)
        atomic_write_bytes(self.snap_path, self._pack(state))
        if self._delta_fd is not None:
            os.close(self._delta_fd)
            self._delta_fd = None
        atomic_write_bytes(self.delta_path, b'')

    def load(self) -> Optional[Dict[str, Any]]:
        """Replay snapshot + deltas into a restorable book, truncating a
        torn delta tail in place; None when nothing was ever journaled."""
        state = None
        try:
            with open(self.snap_path, 'rb') as f:
                state = self._unpack(f.read())
        except OSError:
            state = None
        except Exception:
            state = None          # corrupt snap: fall back to deltas only
        if not isinstance(state, dict):
            state = None
        tasks = dict((state or {}).get('tasks') or {})
        reissue = list((state or {}).get('reissue') or ())
        next_tid = int((state or {}).get('next_tid') or 0)
        # chunk book: keys round-trip through msgpack as lists; normalize
        # back to hashable tuples for delta folding
        chunks: Dict[Any, set] = {}
        closed_chunks: list = []
        for pair in (state or {}).get('chunks') or ():
            try:
                key, cis = pair
                chunks[(str(key[0]), int(key[1]))] = \
                    set(int(c) for c in cis)
            except Exception:
                continue
        records, valid_bytes, torn = self._read_records(self.delta_path)
        if torn:
            os.truncate(self.delta_path, valid_bytes)
        for payload in records:
            try:
                rec = self._unpack(payload)
                op, tid = rec['op'], int(rec['tid'])
            except Exception:
                continue
            if op == 'a':
                tasks[tid] = rec.get('base')
                next_tid = max(next_tid, tid + 1)
            elif op in ('c', 'x'):
                tasks.pop(tid, None)
            elif op == 's':
                base = tasks.pop(tid, None)
                if base is not None:
                    reissue.append(base)
            elif op == 'p':
                try:
                    key = rec['key']
                    k = (str(key[0]), int(key[1]))
                    chunks.setdefault(k, set()).add(int(rec['ci']))
                except Exception:
                    continue
            elif op == 'q':
                try:
                    key = rec['key']
                    k = (str(key[0]), int(key[1]))
                    chunks.pop(k, None)
                    if k not in closed_chunks:
                        closed_chunks.append(k)
                except Exception:
                    continue
        if state is None and not records:
            return None
        out = {'tasks': tasks, 'reissue': reissue, 'next_tid': next_tid,
               'extra': dict((state or {}).get('extra') or {})}
        if chunks:
            out['chunks'] = [[list(k), sorted(cis)]
                             for k, cis in chunks.items()]
        if closed_chunks:
            # assemblies closed AFTER the snapshot (delta-only 'q' ops):
            # their completions post-date the snapshot's counters, so spool
            # recovery must replay their chunks and re-derive the episode
            out['chunks_closed'] = [list(k) for k in closed_chunks]
        return out

    def close(self):
        if self._delta_fd is not None:
            os.close(self._delta_fd)
            self._delta_fd = None


class SessionLedger:
    """Session-affinity book for the match gateway: which replica each
    open session's recurrent state is warm on.

    The gateway keeps the authoritative hidden-state cache; this ledger
    only tracks the *affinity* (the replica whose engine last saw the
    session, so consecutive plies coalesce into the same engine batch)
    and journals the strandings when a replica dies. ``fail_replica``
    strands every session booked on a replica and returns them — the
    gateway then either hands each session off (its cached hidden rides
    the next request to a survivor) or replay-reconstructs it from the
    session journal. Mirrors :class:`TaskLedger`'s stranding telemetry so
    postmortems correlate session loss with host-state transitions."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._sessions: Dict[Any, Any] = {}          # sid -> replica
        self._by_replica: Dict[Any, set] = defaultdict(set)
        self._strandings: deque = deque(maxlen=4096)  # (sid, replica, why, t)
        self.stats: Dict[str, int] = {
            'booked': 0, 'moved': 0, 'released': 0,
            'stranded': 0, 'replica_failures': 0,
        }

    def book(self, sid, replica) -> None:
        """Bind a fresh session to the replica that served its first ply."""
        self.release(sid)
        self._sessions[sid] = replica
        self._by_replica[replica].add(sid)
        self.stats['booked'] += 1

    def move(self, sid, replica) -> Optional[Any]:
        """Re-pin ``sid`` (handoff / reconstruct landed elsewhere);
        returns the previous replica, or None if the session is new."""
        prev = self._sessions.get(sid)
        if prev == replica:
            return prev
        if prev is not None:
            owners = self._by_replica.get(prev)
            if owners is not None:
                owners.discard(sid)
                if not owners:
                    self._by_replica.pop(prev, None)
            self.stats['moved'] += 1
        else:
            self.stats['booked'] += 1
        self._sessions[sid] = replica
        self._by_replica[replica].add(sid)
        return prev

    def release(self, sid) -> bool:
        """Close the book on a finished/abandoned session."""
        replica = self._sessions.pop(sid, None)
        if replica is None:
            return False
        owners = self._by_replica.get(replica)
        if owners is not None:
            owners.discard(sid)
            if not owners:
                self._by_replica.pop(replica, None)
        self.stats['released'] += 1
        return True

    def replica_of(self, sid) -> Optional[Any]:
        return self._sessions.get(sid)

    def sessions_on(self, replica) -> list:
        return sorted(self._by_replica.get(replica, ()))

    def fail_replica(self, replica, reason: str = 'detach') -> list:
        """Strand every session pinned to a dead/draining replica; the
        caller decides handoff vs replay-reconstruct per session."""
        sids = self.sessions_on(replica)
        now = self._clock()
        for sid in sids:
            self._sessions.pop(sid, None)
            self._strandings.append((sid, replica, reason, now))
            telemetry.record_event('session_stranding', str(replica),
                                   reason=reason, session=str(sid))
        self._by_replica.pop(replica, None)
        self.stats['stranded'] += len(sids)
        if sids:
            self.stats['replica_failures'] += 1
        return sids

    def outstanding(self) -> int:
        return len(self._sessions)

    def outstanding_by_replica(self) -> Dict[Any, int]:
        return {rep: len(sids) for rep, sids in self._by_replica.items()
                if sids}

    def drain_stranding_events(self):
        """Consume the (sid, replica, reason, time) stranding journal."""
        events = list(self._strandings)
        self._strandings.clear()
        return events


# host health states, in escalation order (numeric codes for the
# fleet_host_state gauge live in telemetry.HOST_STATE_CODES)
HOST_HEALTHY = 'healthy'
HOST_DEGRADED = 'degraded'
HOST_DRAINING = 'draining'
HOST_QUARANTINED = 'quarantined'


class FleetController:
    """Per-host health state machine for the learner's elastic fleet
    control: decide, per task-assignment, whether a host should receive
    fresh work — instead of only detecting death after the fact.

    Inputs are two fault streams per host key:

    * **strandings** — tasks the ledger had to re-issue because this host's
      endpoint detached or blew its deadline (the hard signal);
    * **soft faults** — engine restarts / worker failovers reported up the
      heartbeat telemetry (the host self-healed, but it is struggling).

    State machine (every host starts ``healthy``; all windows slide):

    * ``healthy -> degraded`` — ≥ ``degrade_after`` fault signals of either
      kind within ``health_window`` seconds. Degraded hosts still receive
      tasks; the state exists to make trouble visible before it escalates.
    * ``degraded -> healthy`` — a full quiet ``health_window``.
    * ``healthy/degraded -> draining`` — ≥ ``quarantine_after`` STRANDINGS
      within the window: the host is flapping. Draining stops fresh
      assignments but lets booked tasks finish (drain-before-detach) —
      in-flight episodes that can still land, land.
    * ``draining -> quarantined`` — the host's outstanding book is empty
      (completed or re-issued elsewhere). The quarantine clock starts.
    * ``quarantined -> healthy`` — ``quarantine_period`` seconds later the
      host is re-admitted with a cleared fault history (one fresh chance;
      renewed flapping walks the same path with no special casing).

    ``admits(host)`` is the assignment gate the server consults; draining
    and quarantined hosts get 'idle' placeholder tasks instead of work.
    Transitions are journaled for ``drain_transitions`` (the server logs
    them and mirrors them onto ``fleet_host_state`` gauges).
    """

    def __init__(self, degrade_after: int = 1, quarantine_after: int = 3,
                 health_window: float = 120.0,
                 quarantine_period: float = 60.0, clock=time.time):
        self.degrade_after = max(1, int(degrade_after))
        self.quarantine_after = max(1, int(quarantine_after))
        self.health_window = float(health_window)
        self.quarantine_period = float(quarantine_period)
        self._clock = clock
        self._state: Dict[str, str] = {}
        self._strands: Dict[str, deque] = defaultdict(deque)   # event times
        self._softs: Dict[str, deque] = defaultdict(deque)
        self._until: Dict[str, float] = {}          # quarantine expiry
        self._transitions: deque = deque(maxlen=4096)
        self.stats: Dict[str, int] = {
            'degraded': 0, 'quarantined': 0, 'readmitted': 0, 'withheld': 0}

    # -- queries -----------------------------------------------------------

    def observe(self, host: str) -> bool:
        """Register ``host`` (idempotent); True the first time."""
        if host in self._state:
            return False
        self._state[host] = HOST_HEALTHY
        return True

    def state(self, host: str) -> str:
        return self._state.get(host, HOST_HEALTHY)

    def admits(self, host: str) -> bool:
        """May ``host`` receive fresh task assignments right now?"""
        return self.state(host) in (HOST_HEALTHY, HOST_DEGRADED)

    def snapshot(self) -> Dict[str, str]:
        return dict(self._state)

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in (HOST_HEALTHY, HOST_DEGRADED, HOST_DRAINING,
                              HOST_QUARANTINED)}
        for state in self._state.values():
            out[state] += 1
        return out

    def drain_transitions(self):
        """Consume the (host, from_state, to_state, time) journal."""
        events = list(self._transitions)
        self._transitions.clear()
        return events

    # -- fault feeds -------------------------------------------------------

    def record_stranding(self, host: str, n: int = 1):
        now = self._clock()
        self._strands[host].extend([now] * max(1, int(n)))
        self._reassess(host, now)

    def record_soft_fault(self, host: str, n: int = 1):
        now = self._clock()
        self._softs[host].extend([now] * max(1, int(n)))
        self._reassess(host, now)

    # -- operator/supervisor overrides -------------------------------------
    # The serving fleet (serving/fleet.py) reuses this state machine over
    # inference replicas, where two transitions have no organic fault feed:
    # a deliberate scale-down drain, and a replica that proved itself
    # healthy by re-registering (a respawn) before the quarantine clock ran.

    def force_drain(self, host: str):
        """Deliberately drain ``host`` (autoscaler scale-down / operator
        action): stop fresh work now, quarantine once its book empties."""
        self.observe(host)
        if self.state(host) in (HOST_HEALTHY, HOST_DEGRADED):
            self._set(host, HOST_DRAINING)

    def readmit(self, host: str):
        """Re-admit ``host`` immediately with a cleared fault history —
        used when a quarantined replica demonstrably recovered (it
        re-registered with the resolver) before its quarantine expired."""
        self.observe(host)
        self._strands[host].clear()
        self._softs[host].clear()
        self._until.pop(host, None)
        if self.state(host) != HOST_HEALTHY:
            self._set(host, HOST_HEALTHY)
            self.stats['readmitted'] += 1

    def forget(self, host: str):
        """Drop ``host`` from the book entirely (replica deliberately
        retired; its key must not linger in snapshots or gauges)."""
        self._state.pop(host, None)
        self._strands.pop(host, None)
        self._softs.pop(host, None)
        self._until.pop(host, None)

    # -- transitions -------------------------------------------------------

    def _set(self, host: str, state: str):
        prev = self._state.get(host, HOST_HEALTHY)
        if prev == state:
            return
        self._state[host] = state
        self._transitions.append((host, prev, state, self._clock()))
        telemetry.record_event('transition', host, **{
            'from': prev, 'to': state})

    def _prune(self, host: str, now: float):
        horizon = now - self.health_window
        for dq in (self._strands[host], self._softs[host]):
            while dq and dq[0] < horizon:
                dq.popleft()

    def _reassess(self, host: str, now: float):
        self.observe(host)
        self._prune(host, now)
        state = self.state(host)
        strands = len(self._strands[host])
        faults = strands + len(self._softs[host])
        if (state in (HOST_HEALTHY, HOST_DEGRADED)
                and strands >= self.quarantine_after):
            self._set(host, HOST_DRAINING)
        elif state == HOST_HEALTHY and faults >= self.degrade_after:
            self._set(host, HOST_DEGRADED)
            self.stats['degraded'] += 1

    def tick(self, outstanding: Optional[Dict[str, int]] = None):
        """Time/drain-driven transitions; ``outstanding`` maps host key ->
        open ledger tasks (a draining host quarantines once it hits 0)."""
        now = self._clock()
        outstanding = outstanding or {}
        for host, state in list(self._state.items()):
            if state == HOST_DRAINING:
                if outstanding.get(host, 0) <= 0:
                    self._until[host] = now + self.quarantine_period
                    self._set(host, HOST_QUARANTINED)
                    self.stats['quarantined'] += 1
            elif state == HOST_QUARANTINED:
                if now >= self._until.get(host, 0.0):
                    self._strands[host].clear()
                    self._softs[host].clear()
                    self._set(host, HOST_HEALTHY)
                    self.stats['readmitted'] += 1
            elif state == HOST_DEGRADED:
                self._prune(host, now)
                if not self._strands[host] and not self._softs[host]:
                    self._set(host, HOST_HEALTHY)


def parse_chaos(spec: Optional[str] = None) -> Dict[str, float]:
    """Parse ``HANDYRL_TPU_CHAOS`` (or an explicit spec string) into a dict
    of float knobs; empty/unset means chaos off. Malformed entries are
    ignored rather than crashing a production run."""
    if spec is None:
        spec = os.environ.get('HANDYRL_TPU_CHAOS', '')
    out: Dict[str, float] = {}
    for part in (spec or '').split(','):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition('=')
        try:
            out[key.strip()] = float(value)
        except ValueError:
            print('ignoring malformed HANDYRL_TPU_CHAOS entry %r' % part)
    return out
