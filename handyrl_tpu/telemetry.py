"""Unified telemetry: metric registry, span timing, leveled logging, exporter.

One observability layer for the whole fleet (the Podracer lesson: scaling an
IMPALA-style learner/actor system is gated on *seeing* where time and
throughput go across processes). Four pieces, all stdlib-only:

* **MetricRegistry** — process-local labeled counters, gauges, and
  fixed-bucket histograms (p50/p95/p99 summaries). Thread-safe, and
  near-zero cost when disabled (``HANDYRL_TPU_TELEMETRY=0`` or the
  ``telemetry: false`` config knob): every mutator is a single flag check.
  ``snapshot()`` returns a plain-data dict that survives the msgpack wire
  codec, so worker and gather processes piggyback their registries on the
  existing heartbeat frames and the learner merges them fleet-wide
  (``merge_snapshots``: counters sum, gauges sum, histogram buckets add).

* **Spans** — lightweight timed sections recorded as observations of the
  ``stage_seconds{stage=...}`` histogram family, stamped with a run-scoped
  ``run_id``. The stage vocabulary subsumes the ingest StageTimer's
  canonical names (``INGEST_STAGES``): a bench row, a live epoch timing
  line, and an exported histogram all speak the same stage language.

* **Leveled logger** — ``get_logger()``; verbosity from
  ``HANDYRL_TPU_LOG_LEVEL`` (debug/info/warning/error, default info).
  Replaces the scattered bare ``print()`` status lines whose partial writes
  interleave mid-line across the process tree. The reference-format result
  lines (epoch / win rate / loss / updated model) stay on stdout — plot
  tooling parses those.

* **TelemetryExporter** — optional Prometheus-text-format HTTP endpoint
  (stdlib http.server; ``telemetry_port`` config knob, off by default)
  serving the learner's local registry plus the latest merged fleet
  snapshot. A busy port is retried and then falls back to an ephemeral
  one — an occupied port must never take the learner down.

* **Distributed tracing** — episode-lifecycle spans across the whole fleet
  (``HANDYRL_TPU_TRACE=<dir>`` or the ``telemetry.trace_dir`` knob). Every
  process appends Chrome-trace "complete" events (wall-clock microseconds,
  pid/tid, ``args.trace_id``) to ONE shared JSONL per run via single
  ``O_APPEND`` writes; the learner collates a valid Chrome/Perfetto JSON at
  shutdown and ``scripts/trace_report.py`` reduces either file to a
  generation→gradient critical-path summary. The trace context is the
  ``trace_id`` derived from the server-stamped task (``role`` +
  ``sample_key``): it rides the existing task/episode payloads through
  every hop — no new wire fields — so spans from the learner (task_assign,
  ingest, train_step), the gather (upload, engine_batch) and the workers
  (generate) link up by id. Sampling is DETERMINISTIC per trace_id
  (``telemetry.trace_sample_rate``): every process makes the same keep/drop
  decision for an episode without coordination. Span durations also land in
  the ``stage_seconds{stage=...}`` histogram family, so the trace file, the
  metrics registry and the timing lines share one stage vocabulary. Off
  (the default) every trace call is a single falsy-string check.
"""

from __future__ import annotations

import atexit
import bisect
import json
import logging
import os
import random
import re
import sys
import threading
import time
import uuid
import zlib
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# enable/disable switch (near-zero cost when off)

_ENABLED = os.environ.get('HANDYRL_TPU_TELEMETRY', '1').strip().lower() \
    not in ('0', 'false', 'off')


def enabled() -> bool:
    return _ENABLED


def set_enabled(flag: bool):
    """Flip collection globally; mirrored into the environment so spawned
    children (batchers, gathers, workers) inherit the choice."""
    global _ENABLED
    _ENABLED = bool(flag)
    os.environ['HANDYRL_TPU_TELEMETRY'] = '1' if _ENABLED else '0'


# the flight recorder rides the same master switch but also has its own
# (bench.py's recorder A/B isolates the ring cost from metric/span cost)
_RECORDER_ON = True


def set_recorder_enabled(flag: bool):
    global _RECORDER_ON
    _RECORDER_ON = bool(flag)


# ---------------------------------------------------------------------------
# run id: one identity for every record/span of a training run

_RUN_ID = os.environ.get('HANDYRL_TPU_RUN_ID') or uuid.uuid4().hex[:12]


def run_id() -> str:
    return _RUN_ID


def set_run_id(rid: Optional[str]):
    """Adopt the learner's run id (workers receive it in the merged config);
    mirrored into the environment so spawned children inherit it."""
    global _RUN_ID
    if rid:
        _RUN_ID = str(rid)
        os.environ['HANDYRL_TPU_RUN_ID'] = _RUN_ID


# ---------------------------------------------------------------------------
# distributed tracing (Chrome-trace events over one shared per-run JSONL)

# Default per-config knobs for the ``telemetry`` block (a bare bool in the
# config is accepted as {'enabled': <bool>} for back-compat).
TELEMETRY_DEFAULTS: Dict[str, Any] = {
    'enabled': True, 'trace_dir': '', 'trace_sample_rate': 1.0,
    'blackbox_dir': 'blackbox', 'recorder_events': 256,
    'metrics_rotate_mb': 0, 'alerts': {},
    # compiled-performance plane (docs/observability.md): device-memory
    # gauges, the retrace sentinel, and the host-block decomposition
    'perf_plane': True, 'retrace': 'warn', 'retrace_warmup_epochs': 1}


def config_block(args: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Normalize the ``telemetry`` config knob: bool (legacy collection
    switch) or a block with ``enabled`` / ``trace_dir`` /
    ``trace_sample_rate``."""
    raw = (args or {}).get('telemetry', True)
    if isinstance(raw, dict):
        out = dict(TELEMETRY_DEFAULTS)
        out.update(raw)
        return out
    return {**TELEMETRY_DEFAULTS, 'enabled': bool(raw)}


class _TraceState:
    """Per-process trace sink: destination dir, sample rate, event buffer."""

    def __init__(self):
        self.dir = os.environ.get('HANDYRL_TPU_TRACE', '').strip()
        rate = os.environ.get('HANDYRL_TPU_TRACE_RATE', '').strip()
        try:
            self.rate = min(1.0, max(0.0, float(rate))) if rate else 1.0
        except ValueError:
            self.rate = 1.0
        self.label = 'proc'
        self.lock = threading.Lock()
        # event buffer + its one-shot metadata flag share the sink lock
        # (lexical discipline checked by graftlint GL004; *_locked helpers
        # are called with it held)
        self.buf: List[str] = []          # guarded-by: lock
        self.meta_done = False            # guarded-by: lock


_TRACE = _TraceState()
_TRACE_FLUSH_AT = 128      # buffered events per O_APPEND write


def trace_enabled() -> bool:
    return bool(_TRACE.dir)


def trace_dir() -> str:
    return _TRACE.dir


def trace_sample_rate() -> float:
    return _TRACE.rate


def configure_tracing(trace_dir: Optional[str] = None,
                      sample_rate: Optional[float] = None,
                      force: bool = False):
    """Adopt trace settings from the run config, mirrored into the
    environment so spawned children (batchers, gathers, workers) inherit
    them. An operator-set ``HANDYRL_TPU_TRACE`` / ``HANDYRL_TPU_TRACE_RATE``
    wins over config values unless ``force`` (tests, bench A/B runs)."""
    if sample_rate is not None and (force or
                                    not os.environ.get('HANDYRL_TPU_TRACE_RATE')):
        _TRACE.rate = min(1.0, max(0.0, float(sample_rate)))
        os.environ['HANDYRL_TPU_TRACE_RATE'] = '%g' % _TRACE.rate
    if trace_dir is not None and (force or
                                  not os.environ.get('HANDYRL_TPU_TRACE')):
        trace_flush()
        with _TRACE.lock:   # a racing trace_event must not emit its meta
            _TRACE.dir = str(trace_dir).strip()   # line into the old sink
            _TRACE.meta_done = False
        os.environ['HANDYRL_TPU_TRACE'] = _TRACE.dir


def set_process_label(label: str):
    """Human-readable process name for the trace viewer's process rows
    (learner / gather-N / worker-N / batcher-N)."""
    _TRACE.label = str(label)


def adopt_config(args: Optional[Dict[str, Any]]):
    """One call for every process that receives the merged run config:
    run id, the collection switch, the trace destination/sampling, and the
    flight-recorder geometry."""
    args = args or {}
    set_run_id(args.get('run_id'))
    tel = config_block(args)
    if not tel.get('enabled', True):
        set_enabled(False)
    configure_tracing(tel.get('trace_dir') or None,
                      tel.get('trace_sample_rate'))
    configure_recorder(tel.get('recorder_events'),
                       tel.get('blackbox_dir'))
    configure_perf_plane(tel.get('perf_plane'), tel.get('retrace'))


def episode_trace_id(task_args: Optional[Dict[str, Any]]) -> Optional[str]:
    """The trace context: derived from the server-stamped task identity
    (``role`` + ``sample_key``), so every process holding the task or an
    episode/result payload built from it computes the SAME id with no new
    wire fields. None when the payload carries no sample_key (local
    fallback streams, pre-ledger peers)."""
    if not isinstance(task_args, dict):
        return None
    skey = task_args.get('sample_key')
    if skey is None:
        return None
    return '%s%d' % (str(task_args.get('role') or 'g'), int(skey))


_MINT_LOCK = threading.Lock()
_MINT_SEQ = [0]                       # guarded-by: _MINT_LOCK


def mint_trace_id() -> str:
    """Serving-path trace context: a fresh request-scoped id (``r<pid
    hash><seq>``), minted once at the edge (``ServiceClient.submit`` /
    a gateway ply) and carried inside the INFER/admin payload so every
    downstream hop — router, replica, engine, failover replay — stamps
    the SAME id. Unlike :func:`episode_trace_id` there is no
    server-stamped identity to recompute from, so the id itself crosses
    the wire (absent key = unsampled; old peers ignore it)."""
    with _MINT_LOCK:
        _MINT_SEQ[0] += 1
        seq = _MINT_SEQ[0]
    return 'r%x.%d' % (os.getpid() & 0xFFFFFF, seq)


def trace_sampled(trace_id) -> bool:
    """Deterministic keep/drop for one episode: hash-based on the trace_id,
    so the learner, gather and worker agree without coordination."""
    if not _TRACE.dir:
        return False
    rate = _TRACE.rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(str(trace_id).encode()) % 10000) < rate * 10000


def _emit_locked(line: str):
    if not _TRACE.meta_done:
        _TRACE.meta_done = True
        _TRACE.buf.append(json.dumps(
            {'name': 'process_name', 'ph': 'M', 'pid': os.getpid(), 'tid': 0,
             'args': {'name': '%s-%d' % (_TRACE.label, os.getpid())}}))
    _TRACE.buf.append(line)
    if len(_TRACE.buf) >= _TRACE_FLUSH_AT:
        _flush_locked()


def trace_event(name: str, ts: Optional[float] = None, dur: float = 0.0,
                trace_id=None, always: bool = False, **args):
    """Record one Chrome-trace complete event ("ph": "X"; instants are
    zero-duration spans). ``ts``/``dur`` are wall-clock seconds (converted
    to the microseconds the viewers expect — wall time, so events align
    across processes). Sampling: a truthy ``trace_id`` decides
    deterministically; ``always`` bypasses (callers who already sampled);
    otherwise batch-level events sample probabilistically at the same
    rate."""
    if not _TRACE.dir:
        return
    if trace_id:
        if not trace_sampled(trace_id):
            return
        args['trace_id'] = trace_id
    elif not always:
        rate = _TRACE.rate
        if rate < 1.0 and random.random() >= rate:
            return
    args['run_id'] = _RUN_ID
    try:
        tid = threading.get_native_id()
    except AttributeError:
        tid = threading.get_ident() & 0x7FFFFFFF
    ev = {'name': name, 'cat': 'handyrl', 'ph': 'X',
          'ts': int((time.time() if ts is None else ts) * 1e6),
          'dur': max(0, int(dur * 1e6)),
          'pid': os.getpid(), 'tid': tid, 'args': args}
    with _TRACE.lock:
        _emit_locked(json.dumps(ev))


@contextmanager
def trace_span(name: str, trace_id=None, **args):
    """Timed section: always folded into the ``stage_seconds{stage=...}``
    histogram family; additionally written to the trace file when tracing
    is on (and the id — or the rate, for id-less spans — samples it)."""
    t_wall = time.time()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        REGISTRY.observe_stage(name, dt)
        if _TRACE.dir:
            trace_event(name, ts=t_wall, dur=dt, trace_id=trace_id, **args)


def trace_stage(stage: str, seconds: float, count: int = 1):
    """Batch-level stage event (the StageTimer mirror): one span covering
    the just-finished timed section, rate-sampled."""
    if not _TRACE.dir:
        return
    trace_event(stage, ts=time.time() - seconds, dur=seconds, count=count)


def _flush_locked():
    buf = _TRACE.buf
    if not buf or not _TRACE.dir:
        return
    _TRACE.buf = []
    try:
        os.makedirs(_TRACE.dir, exist_ok=True)
        path = os.path.join(_TRACE.dir, 'trace-%s.jsonl' % _RUN_ID)
        data = ('\n'.join(buf) + '\n').encode()
        # one O_APPEND write per flush: complete lines, atomic offset —
        # every fleet process appends to the same per-run file safely
        fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
        try:
            os.write(fd, data)
        finally:
            os.close(fd)
    except OSError:
        pass   # tracing must never take the run down


def trace_flush():
    if not _TRACE.dir:
        return
    with _TRACE.lock:
        _flush_locked()


atexit.register(trace_flush)


def finalize_trace() -> Optional[str]:
    """Collate this run's JSONL event stream into a valid Chrome-trace /
    Perfetto JSON file (``<dir>/trace-<run_id>.json``); returns the path
    (None when tracing is off or nothing was recorded). Written atomically
    (temp + rename); the JSONL stays the append-forever source of truth."""
    if not _TRACE.dir:
        return None
    trace_flush()
    src = os.path.join(_TRACE.dir, 'trace-%s.jsonl' % _RUN_ID)
    events: List[Dict[str, Any]] = []
    try:
        with open(src) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue   # torn tail line from a killed process
    except OSError:
        return None
    if not events:
        return None
    out = os.path.join(_TRACE.dir, 'trace-%s.json' % _RUN_ID)
    try:
        # atomic publish through the shared fs helper (GL003): a collate
        # interrupted mid-write must not leave a half-JSON next to the
        # intact JSONL source of truth
        from .utils.fs import atomic_write_bytes
        atomic_write_bytes(out, json.dumps(
            {'traceEvents': events, 'displayTimeUnit': 'ms'}).encode('utf-8'))
    except OSError:
        return None
    return out


# ---------------------------------------------------------------------------
# leveled logger (multi-process safe: one line per record, stderr)

_LOG_CONFIGURED = False
_LOG_LOCK = threading.Lock()


def _log_level() -> int:
    name = os.environ.get('HANDYRL_TPU_LOG_LEVEL', 'info').strip().lower()
    return {'debug': logging.DEBUG, 'info': logging.INFO,
            'warning': logging.WARNING, 'warn': logging.WARNING,
            'error': logging.ERROR}.get(name, logging.INFO)


def get_logger(name: str = 'handyrl_tpu') -> logging.Logger:
    """A logger under the ``handyrl_tpu`` root, configured once per process:
    complete single lines to stderr (no more dot streams and status prints
    from N processes splicing mid-line), level from HANDYRL_TPU_LOG_LEVEL."""
    global _LOG_CONFIGURED
    root = logging.getLogger('handyrl_tpu')
    if not _LOG_CONFIGURED:
        with _LOG_LOCK:
            if not _LOG_CONFIGURED:
                handler = logging.StreamHandler(sys.stderr)
                handler.setFormatter(logging.Formatter(
                    '[%(asctime)s %(levelname).1s %(process)d %(name)s] '
                    '%(message)s', datefmt='%H:%M:%S'))
                root.addHandler(handler)
                # every leveled line also lands in the flight-recorder
                # ring, so a blackbox dump carries the process's last
                # log context alongside spans/transitions/guard trips
                root.addHandler(_RecorderLogHandler())
                root.setLevel(_log_level())
                root.propagate = False
                _LOG_CONFIGURED = True
    if name in ('', 'handyrl_tpu'):
        return root
    return root.getChild(name.replace('handyrl_tpu.', '', 1))


# ---------------------------------------------------------------------------
# flight recorder: bounded ring of recent events, dumped on abnormal death

RECORDER_EVENTS_DEFAULT = 256


class FlightRecorder:
    """Bounded in-memory ring of this process's recent events: leveled log
    lines, span completions, state-machine transitions, and guard trips.

    Every fleet process keeps one (learner, gathers, workers, inference
    supervisors, serving services, the fleet resolver). When the process
    dies abnormally — uncaught fatal error, PreemptionGuard signal,
    NonFiniteGuard abort, or a supervisor declaring a child dead — the ring
    is dumped atomically (``utils/fs``) to
    ``<blackbox_dir>/<role>-<pid>-<run_id>.json`` so
    ``scripts/postmortem.py`` can reconstruct each corpse's last seconds
    without a debugger. Recording is one deque append under a lock and
    honours the global telemetry switch (``telemetry: false`` disables it
    with the rest of the plane).
    """

    def __init__(self, capacity: int = RECORDER_EVENTS_DEFAULT):
        self._lock = threading.Lock()
        # ring + counters share one lock (graftlint GL004 discipline)
        self._events: deque = deque(maxlen=max(16, int(capacity)))  # guarded-by: _lock
        self._total = 0                 # guarded-by: _lock
        self._dumps: List[str] = []     # guarded-by: _lock

    @property
    def capacity(self) -> int:
        with self._lock:
            return self._events.maxlen or 0

    def set_capacity(self, capacity: int):
        cap = max(16, int(capacity))
        with self._lock:
            if cap != self._events.maxlen:
                self._events = deque(self._events, maxlen=cap)

    def record(self, kind: str, msg: str, **fields):
        if not (_ENABLED and _RECORDER_ON):
            return
        ev = {'t': round(time.time(), 6), 'kind': str(kind),
              'msg': str(msg)[:500]}
        if fields:
            ev.update(fields)
        with self._lock:
            self._events.append(ev)
            self._total += 1

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(ev) for ev in self._events]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            held = len(self._events)
            return {'events': held, 'total': self._total,
                    'dropped': max(0, self._total - held),
                    'capacity': self._events.maxlen,
                    'dumps': list(self._dumps)}

    def dump(self, reason: str, directory: Optional[str] = None,
             context: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Atomically write the ring (plus a summarized registry snapshot)
        to the blackbox file for this process. Returns the path, or None
        when dumping is disabled (empty dir) or the write failed — a dump
        must never take the dying process down harder."""
        directory = _BLACKBOX_DIR if directory is None else directory
        if not directory:
            return None
        role = re.sub(r'[^A-Za-z0-9_.-]', '_', _TRACE.label or 'proc')
        path = os.path.join(directory,
                            '%s-%d-%s.json' % (role, os.getpid(), _RUN_ID))
        payload = {
            'schema': 'handyrl_tpu.blackbox/1',
            'role': _TRACE.label, 'pid': os.getpid(), 'run_id': _RUN_ID,
            'reason': str(reason), 'time': round(time.time(), 6),
            'stats': self.stats(), 'events': self.events(),
            'metrics': summarize(REGISTRY.snapshot()),
        }
        if context:
            payload['context'] = context
        try:
            os.makedirs(directory, exist_ok=True)
            from .utils.fs import atomic_write_bytes
            atomic_write_bytes(path, json.dumps(payload).encode('utf-8'))
        except Exception:
            return None
        with self._lock:
            if path not in self._dumps:
                self._dumps.append(path)
        return path


class _RecorderLogHandler(logging.Handler):
    """Mirror leveled log lines into the flight-recorder ring."""

    def emit(self, record):  # noqa: D102 (logging API)
        try:
            _RECORDER.record('log', record.getMessage(),
                             level=record.levelname, logger=record.name)
        except Exception:
            pass   # the recorder must never break logging


_RECORDER = FlightRecorder(
    int(os.environ.get('HANDYRL_TPU_RECORDER_EVENTS')
        or RECORDER_EVENTS_DEFAULT))
_BLACKBOX_DIR = os.environ.get('HANDYRL_TPU_BLACKBOX', 'blackbox')


def recorder() -> FlightRecorder:
    return _RECORDER


def recorder_stats() -> Dict[str, Any]:
    return _RECORDER.stats()


def blackbox_dir() -> str:
    return _BLACKBOX_DIR


def configure_recorder(events: Optional[int] = None,
                       directory: Optional[str] = None,
                       force: bool = False):
    """Adopt recorder geometry from the run config, mirrored into the
    environment so spawned children inherit it. Operator-set
    ``HANDYRL_TPU_RECORDER_EVENTS`` / ``HANDYRL_TPU_BLACKBOX`` win over
    config values unless ``force`` (tests, bench A/B runs)."""
    global _BLACKBOX_DIR
    if events is not None and (force or
                               not os.environ.get('HANDYRL_TPU_RECORDER_EVENTS')):
        _RECORDER.set_capacity(int(events))
        os.environ['HANDYRL_TPU_RECORDER_EVENTS'] = str(_RECORDER.capacity)
    if directory is not None and (force or
                                  not os.environ.get('HANDYRL_TPU_BLACKBOX')):
        _BLACKBOX_DIR = str(directory).strip()
        os.environ['HANDYRL_TPU_BLACKBOX'] = _BLACKBOX_DIR


def record_event(kind: str, msg: str, **fields):
    """Append one event to this process's flight-recorder ring (a single
    deque append under a lock; a no-op with telemetry disabled)."""
    _RECORDER.record(kind, msg, **fields)


def dump_blackbox(reason: str, **context) -> Optional[str]:
    """Dump the flight recorder for an abnormal-death reason (fatal-error,
    preempt, nonfinite-abort, crash declarations). Idempotent per process:
    a later dump atomically replaces the earlier file with a fresher
    ring."""
    path = _RECORDER.dump(reason, context=context or None)
    if path:
        counter('blackbox_dumps_total').inc()
        get_logger('recorder').warning('blackbox dump (%s): %s',
                                       reason, path)
        trace_flush()
    return path


_CRASH_HOOK_INSTALLED = False


def install_crash_dump():
    """Chain ``sys.excepthook`` so an uncaught fatal error dumps the flight
    recorder before the traceback prints. Installed once per process at
    the fleet entry points (learner, gather, worker, serving service,
    fleet resolver). KeyboardInterrupt is left to the PreemptionGuard
    path; SystemExit never reaches the hook."""
    global _CRASH_HOOK_INSTALLED
    if _CRASH_HOOK_INSTALLED:
        return
    _CRASH_HOOK_INSTALLED = True
    prev = sys.excepthook

    def hook(tp, val, tb):
        if not issubclass(tp, KeyboardInterrupt):
            try:
                record_event('fatal', '%s: %s' % (tp.__name__, val))
                dump_blackbox('fatal-error',
                              error='%s: %s' % (tp.__name__, str(val)[:200]))
            except Exception:
                pass   # dumping must never mask the real traceback
        prev(tp, val, tb)

    sys.excepthook = hook


# ---------------------------------------------------------------------------
# metric key codec: 'name' or 'name{k="v",k2="v2"}' (label keys sorted)

_NAME_RE = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*$')


def metric_key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ','.join('%s="%s"' % (k, str(labels[k]).replace('"', "'"))
                     for k in sorted(labels))
    return '%s{%s}' % (name, inner)


def split_key(key: str) -> Tuple[str, str]:
    """('name', 'k="v",...') — the label string is '' when unlabeled."""
    if '{' not in key:
        return key, ''
    name, _, rest = key.partition('{')
    return name, rest.rstrip('}')


def relabel(snapshot: Dict[str, Any], **labels) -> Dict[str, Any]:
    """A copy of ``snapshot`` with ``labels`` appended to every metric key
    (the exporter tags the merged fleet snapshot with source="fleet")."""
    extra = ','.join('%s="%s"' % (k, v) for k, v in sorted(labels.items()))

    def rekey(key: str) -> str:
        name, inner = split_key(key)
        inner = (inner + ',' + extra) if inner else extra
        return '%s{%s}' % (name, inner)

    out = dict(snapshot)
    for section in ('counters', 'gauges'):
        out[section] = {rekey(k): v
                        for k, v in (snapshot.get(section) or {}).items()}
    out['hists'] = {rekey(k): dict(v)
                    for k, v in (snapshot.get('hists') or {}).items()}
    return out


# ---------------------------------------------------------------------------
# metrics

# Default histogram buckets: latency-oriented, seconds. Fixed per metric for
# the life of the process so fleet merges are bucket-aligned.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# Canonical ingest-path stage vocabulary, shared by StageTimer epoch lines,
# BENCH_MODE=ingest rows, and the stage_seconds histogram family. The old
# aggregate 'compute' stage is decomposed into 'dispatch' (the async
# compiled-step call returning) and 'host_block' (block_until_ready / lazy
# metric fetch — the host pinned to the device stream), which is what the
# device-utilization proxy is computed from.
INGEST_STAGES: Tuple[str, ...] = (
    'select', 'decode', 'assemble', 'ipc', 'h2d', 'dispatch', 'host_block')

# Row-count buckets for batching histograms (e.g. the inference engine's
# engine_batch_rows): powers of two matching the padded dispatch buckets.
BATCH_ROW_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)

# Policy-lag buckets: how many epochs behind the learner the params that
# generated a consumed sample were (the policy_lag_epochs histogram).
LAG_EPOCH_BUCKETS: Tuple[float, ...] = (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32,
                                        48, 64)

# Sample-age buckets (seconds from learner ingest to consumption): buffer
# dwell spans far past the latency-oriented DEFAULT_BUCKETS.
AGE_SECOND_BUCKETS: Tuple[float, ...] = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25,
                                         50, 100, 250, 500, 1000)

# XLA compile durations (jax.monitoring events): seconds, up to the
# minutes-long recurrent-net compiles.
COMPILE_SECOND_BUCKETS: Tuple[float, ...] = (0.01, 0.05, 0.1, 0.25, 0.5, 1,
                                             2.5, 5, 10, 30, 60, 120, 300)

# Numeric encoding of the fleet controller's host health states
# (fault.FleetController) for the per-host ``fleet_host_state`` gauge
# family and the serving fleet's per-replica ``fleet_replica_state``
# gauges: monotone in severity, so operators can alert on `value >= 2`
# (draining or quarantined = the host/replica is not receiving fresh
# work). The serving resolver additionally uses -1 for a retired replica.
HOST_STATE_CODES: Dict[str, int] = {
    'healthy': 0, 'degraded': 1, 'draining': 2, 'quarantined': 3}


class Counter:
    """Monotonic labeled counter."""

    __slots__ = ('_lock', 'value')

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0

    def inc(self, n: int = 1):
        if not _ENABLED:
            return
        with self._lock:
            self.value += n


class Gauge:
    """Last-value labeled gauge."""

    __slots__ = ('_lock', 'value')

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def set(self, v: float):
        if not _ENABLED:
            return
        with self._lock:
            self.value = float(v)

    def add(self, n: float = 1.0):
        if not _ENABLED:
            return
        with self._lock:
            self.value += n


class Histogram:
    """Fixed-bucket histogram with closed-form percentile summaries.

    ``bounds`` are ascending upper edges; observations land in the first
    bucket whose bound is >= the value (one overflow bucket past the last
    bound). Quantiles interpolate linearly inside the winning bucket —
    exact enough for p50/p95/p99 dashboards at 14 buckets.
    """

    __slots__ = ('_lock', 'bounds', 'buckets', 'sum', 'count')

    def __init__(self, lock: threading.Lock,
                 bounds: Sequence[float] = DEFAULT_BUCKETS):
        self._lock = lock
        self.bounds = tuple(float(b) for b in bounds)
        self.buckets = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float):
        if not _ENABLED:
            return
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.buckets[i] += 1
            self.sum += v
            self.count += 1

    def observe_agg(self, total: float, n: int):
        """Fold ``n`` events totalling ``total`` in (a StageTimer batch):
        the mean lands in one bucket, sum/count stay exact."""
        if not _ENABLED or n <= 0:
            return
        i = bisect.bisect_left(self.bounds, total / n)
        with self._lock:
            self.buckets[i] += n
            self.sum += total
            self.count += n

    def quantile(self, q: float) -> float:
        with self._lock:
            return hist_quantile(self.bounds, self.buckets, self.count, q)


def hist_quantile(bounds: Sequence[float], buckets: Sequence[int],
                  count: int, q: float) -> float:
    """Linear-interpolated quantile of a bucketed distribution (also used on
    merged fleet histograms, where no Histogram object exists)."""
    if count <= 0:
        return 0.0
    rank = q * count
    seen = 0.0
    for i, n in enumerate(buckets):
        if n <= 0:
            continue
        if seen + n >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            frac = (rank - seen) / n
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        seen += n
    return float(bounds[-1])


class MetricRegistry:
    """Process-local metric store. One lock guards every update (updates are
    a few arithmetic ops; the timed sections themselves run unlocked)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}    # guarded-by: _lock
        self._gauges: Dict[str, Gauge] = {}        # guarded-by: _lock
        self._hists: Dict[str, Histogram] = {}     # guarded-by: _lock

    def counter(self, name: str, **labels) -> Counter:
        key = metric_key(name, labels)
        # graftlint: allow[GL004] lock-free fast path; the dict only grows and setdefault under the lock makes the miss race benign
        c = self._counters.get(key)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(key, Counter(self._lock))
        return c

    def gauge(self, name: str, **labels) -> Gauge:
        key = metric_key(name, labels)
        # graftlint: allow[GL004] lock-free fast path; the dict only grows and setdefault under the lock makes the miss race benign
        g = self._gauges.get(key)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(key, Gauge(self._lock))
        return g

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels) -> Histogram:
        key = metric_key(name, labels)
        # graftlint: allow[GL004] lock-free fast path; the dict only grows and setdefault under the lock makes the miss race benign
        h = self._hists.get(key)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(key,
                                           Histogram(self._lock, buckets))
        return h

    @contextmanager
    def span(self, stage: str, parent: Optional[str] = None):
        """Timed section recorded under ``stage_seconds{stage=...}`` (plus a
        DEBUG structured event carrying the run id and a monotonic stamp).
        ``parent`` names the enclosing stage, keeping the select/decode/
        assemble/ipc/h2d/compute/drain vocabulary hierarchical."""
        if not _ENABLED:
            yield
            return
        labels = {'stage': stage}
        if parent:
            labels['parent'] = parent
        hist = self.histogram('stage_seconds', **labels)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            hist.observe(dt)
            _RECORDER.record('span', stage, seconds=round(dt, 6))
            log = get_logger('span')
            if log.isEnabledFor(logging.DEBUG):
                log.debug('span %s run=%s t=%.6f dur=%.6f parent=%s',
                          stage, _RUN_ID, time.monotonic(), dt, parent or '-')

    def observe_stage(self, stage: str, seconds: float, count: int = 1):
        """StageTimer mirror: fold an ingest-stage timing batch into the
        span histogram family (same canonical stage names)."""
        if not _ENABLED:
            return
        self.histogram('stage_seconds', stage=stage).observe_agg(
            seconds, count)
        _RECORDER.record('span', stage, seconds=round(seconds, 6),
                         count=count)

    def snapshot(self, reset: bool = False) -> Dict[str, Any]:
        """Plain-data (msgpack/json-safe) dump of every metric; with
        ``reset`` counters/histograms restart from zero (gauges keep their
        last value — they are levels, not flows)."""
        with self._lock:
            snap = {
                'run_id': _RUN_ID,
                'time': time.time(),
                'counters': {k: c.value for k, c in self._counters.items()},
                'gauges': {k: g.value for k, g in self._gauges.items()},
                'hists': {k: {'bounds': list(h.bounds),
                              'buckets': list(h.buckets),
                              'sum': h.sum, 'count': h.count}
                          for k, h in self._hists.items()},
            }
            if reset:
                for c in self._counters.values():
                    c.value = 0
                for h in self._hists.values():
                    h.buckets = [0] * len(h.buckets)
                    h.sum = 0.0
                    h.count = 0
        return snap


# the process-global registry every subsystem instruments against
REGISTRY = MetricRegistry()
counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
span = REGISTRY.span
snapshot = REGISTRY.snapshot


# ---------------------------------------------------------------------------
# fleet merge + summaries


def merge_snapshots(snaps: List[Optional[Dict[str, Any]]]
                    ) -> Dict[str, Any]:
    """Fleet-wide aggregate of per-process snapshots.

    Merge semantics: counters SUM (flows add across processes), gauges SUM
    (queue depths and rates add; per-peer resolution survives via labels —
    e.g. ``gather_episodes_per_sec{gather="3"}`` keys stay distinct),
    histogram buckets ADD elementwise when bounds agree. A peer whose
    bounds DISAGREE for a key is dropped for that key (never mis-binned)
    and the drop is counted: once in the merged
    ``telemetry_hist_bound_conflicts_total`` counter (so the conflict
    survives re-merging up the fleet tree and reaches the exposition) and
    once in the top-level ``hist_bound_conflicts`` field of the returned
    snapshot.
    """
    out: Dict[str, Any] = {'run_id': _RUN_ID, 'time': time.time(),
                           'counters': {}, 'gauges': {}, 'hists': {},
                           'peers': 0}
    conflicts = 0
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        out['peers'] += 1
        for k, v in (snap.get('counters') or {}).items():
            out['counters'][k] = out['counters'].get(k, 0) + v
        for k, v in (snap.get('gauges') or {}).items():
            out['gauges'][k] = out['gauges'].get(k, 0.0) + v
        for k, h in (snap.get('hists') or {}).items():
            cur = out['hists'].get(k)
            if cur is None:
                out['hists'][k] = {'bounds': list(h['bounds']),
                                   'buckets': list(h['buckets']),
                                   'sum': float(h['sum']),
                                   'count': int(h['count'])}
            elif list(cur['bounds']) == list(h['bounds']):
                cur['buckets'] = [a + b for a, b in
                                  zip(cur['buckets'], h['buckets'])]
                cur['sum'] += float(h['sum'])
                cur['count'] += int(h['count'])
            else:
                conflicts += 1
    if conflicts:
        key = 'telemetry_hist_bound_conflicts_total'
        out['counters'][key] = out['counters'].get(key, 0) + conflicts
        out['hist_bound_conflicts'] = conflicts
        get_logger('telemetry').warning(
            'merge_snapshots: dropped %d histogram(s) with mismatched '
            'bucket bounds (peers disagree on a histogram geometry)',
            conflicts)
    return out


def summarize(snap: Dict[str, Any]) -> Dict[str, Any]:
    """Compact form for metrics_jsonl: counters/gauges verbatim, histograms
    reduced to count/sum/p50/p95/p99 (full buckets stay wire-only)."""
    hists = {}
    for k, h in (snap.get('hists') or {}).items():
        n = int(h['count'])
        hists[k] = {
            'count': n, 'sum': round(float(h['sum']), 6),
            'p50': round(hist_quantile(h['bounds'], h['buckets'], n, 0.50), 6),
            'p95': round(hist_quantile(h['bounds'], h['buckets'], n, 0.95), 6),
            'p99': round(hist_quantile(h['bounds'], h['buckets'], n, 0.99), 6),
        }
    out = {'counters': dict(snap.get('counters') or {}),
           'gauges': {k: round(float(v), 6)
                      for k, v in (snap.get('gauges') or {}).items()},
           'hists': hists}
    if snap.get('peers') is not None:
        out['peers'] = snap['peers']
    return out


# ---------------------------------------------------------------------------
# SLO alert engine: declarative rules over merged registry/fleet snapshots

# Built-in alert catalog. Each rule is declarative: a metric selector
# (name or list of names, summed over matching label sets), a value kind
# (``value`` = current level, ``rate`` = per-second counter increase
# between evaluations, ``ratio`` = rate(metric)/rate(denominator) — the
# burn rate over the existing latency/shed counters), a comparison, a
# sustain window (``for`` seconds the breach must hold before firing) and
# a ``clear_for`` debounce before an active alert clears. ``arm_metric``
# keeps a rule silent until its subsystem has shown life (ingest stall
# must not fire before the first episode ever arrives). Custom rules from
# the ``telemetry.alerts`` config block override built-ins by name.
BUILTIN_ALERTS: Tuple[Dict[str, Any], ...] = (
    {'name': 'ingest_stall',
     'metric': 'learner_episodes_returned_total', 'kind': 'rate',
     'op': '<=', 'threshold': 0.0, 'for': 60.0,
     'arm_metric': 'learner_episodes_returned_total'},
    {'name': 'policy_lag_runaway',
     'metric': 'policy_lag_mean', 'kind': 'value',
     'op': '>', 'threshold': 16.0, 'for': 30.0},
    {'name': 'nonfinite_spike',
     'metric': 'guard_nonfinite_total', 'kind': 'rate',
     'op': '>', 'threshold': 0.2},
    {'name': 'serve_shed_burn',
     'metric': ['serve_shed_total', 'engine_shed_total'], 'kind': 'ratio',
     'denominator': ['serve_requests_total', 'engine_requests_total'],
     'op': '>', 'threshold': 0.05, 'for': 10.0},
    {'name': 'replica_quarantine_flap',
     'metric': ['fleet_replica_transitions_total',
                'fleet_host_transitions_total'],
     'labels': 'to="quarantined"', 'kind': 'rate',
     'op': '>', 'threshold': 0.05},
    {'name': 'heartbeat_misses',
     'metric': ['fleet_heartbeat_misses_total', 'hub_disconnects_total'],
     'kind': 'rate', 'op': '>', 'threshold': 0.0},
    # compiled-performance plane (docs/observability.md "Compiled-
    # performance plane"): sustained HBM pressure, and any post-warm-up
    # XLA recompilation (each one stalls the device for the full compile)
    {'name': 'hbm_pressure',
     'metric': 'device_mem_utilization', 'kind': 'value',
     'op': '>', 'threshold': 0.92, 'for': 30.0, 'clear_for': 30.0},
    {'name': 'retrace_storm',
     'metric': 'xla_retraces_total', 'kind': 'rate',
     'op': '>', 'threshold': 0.0, 'clear_for': 60.0},
    # league plane (docs/league.md): a pool that stops booking rated games
    # starves PFSP and freezes the promotion gate — armed only once the
    # first league game ever lands, so non-league runs stay silent
    {'name': 'league_rating_stall',
     'metric': 'league_games_total', 'kind': 'rate',
     'op': '<=', 'threshold': 0.0, 'for': 120.0,
     'arm_metric': 'league_games_total'},
    # match gateway (docs/serving.md "Match gateway"): the zero-loss
    # session contract — ANY dropped session is an incident (armed once
    # the gateway has ever opened one), and the per-ply latency SLO the
    # session tier promises on top of the fleet's request SLO
    {'name': 'session_drop',
     'metric': 'gateway_session_drops_total', 'kind': 'rate',
     'op': '>', 'threshold': 0.0, 'clear_for': 60.0,
     'arm_metric': 'gateway_sessions_opened_total'},
    {'name': 'gateway_ply_slo',
     'metric': 'gateway_ply_p99_ms', 'kind': 'value',
     'op': '>', 'threshold': 250.0, 'for': 15.0, 'clear_for': 30.0,
     'arm_metric': 'gateway_plies_total'},
    # durable training plane (docs/large_scale_training.md "Zero-loss
    # training plane"): a spool whose segment count keeps climbing means
    # GC has fallen behind the checkpoint consumption horizon (snapshots
    # stopped landing, or keep_segments is mis-sized) — disk is no longer
    # bounded; and ANY resend-buffer eviction is permanent episode loss
    # on a plane that promises zero, so the rate threshold is 0
    {'name': 'spool_growth',
     'metric': 'spool_segments', 'kind': 'value',
     'op': '>', 'threshold': 8.0, 'for': 60.0,
     'arm_metric': 'spool_bytes_total'},
    {'name': 'resend_buffer_loss',
     'metric': 'gather_resend_dropped_total', 'kind': 'rate',
     'op': '>', 'threshold': 0.0, 'clear_for': 60.0,
     'arm_metric': 'gather_uploads_total'},
)

_ALERT_OPS: Dict[str, Callable[[float, float], bool]] = {
    '>': lambda v, t: v > t, '>=': lambda v, t: v >= t,
    '<': lambda v, t: v < t, '<=': lambda v, t: v <= t,
}


def _metric_value(snaps: List[Optional[Dict[str, Any]]],
                  names, label_sub: str = '') -> float:
    """Sum a metric selector over snapshots: counters and gauges by value,
    histograms by observation count; label_sub (e.g. ``to="quarantined"``)
    restricts to matching label sets."""
    if isinstance(names, str):
        names = (names,)
    total = 0.0
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for section in ('counters', 'gauges'):
            for key, v in (snap.get(section) or {}).items():
                name, labels = split_key(key)
                if name in names and (not label_sub or label_sub in labels):
                    total += float(v)
        for key, h in (snap.get('hists') or {}).items():
            name, labels = split_key(key)
            if name in names and (not label_sub or label_sub in labels):
                total += int(h.get('count', 0))
    return total


class AlertRule:
    """One normalized rule plus its evaluation state (sustain/clear
    windows, last rate sample)."""

    def __init__(self, spec: Dict[str, Any]):
        self.name = str(spec['name'])
        self.metric = spec.get('metric') or ()
        self.denominator = spec.get('denominator') or ()
        self.kind = str(spec.get('kind', 'value'))
        self.labels = str(spec.get('labels', ''))
        self.op = str(spec.get('op', '>'))
        self.threshold = float(spec.get('threshold', 0.0))
        self.for_s = float(spec.get('for', 0.0))
        self.clear_for = float(spec.get('clear_for', 0.0))
        self.arm_metric = spec.get('arm_metric') or ()
        if self.kind not in ('value', 'rate', 'ratio'):
            raise ValueError('alert %r: unknown kind %r'
                             % (self.name, self.kind))
        if self.op not in _ALERT_OPS:
            raise ValueError('alert %r: unknown op %r' % (self.name, self.op))
        if self.kind == 'ratio' and not self.denominator:
            raise ValueError('alert %r: ratio needs a denominator'
                             % self.name)
        # evaluation state (engine-lock protected via AlertEngine)
        self.active = False
        self.fired = 0
        self.last_value = 0.0
        self.breach_since: Optional[float] = None
        self.ok_since: Optional[float] = None
        self._prev: Optional[Tuple[float, float, float]] = None  # t, num, den

    def _rates(self, snaps, now) -> Tuple[float, float]:
        num = _metric_value(snaps, self.metric, self.labels)
        den = _metric_value(snaps, self.denominator, self.labels) \
            if self.denominator else 0.0
        prev, self._prev = self._prev, (now, num, den)
        if prev is None or now <= prev[0]:
            return 0.0, 0.0
        dt = now - prev[0]
        return (max(0.0, num - prev[1]) / dt,
                max(0.0, den - prev[2]) / dt)

    def value(self, snaps, now) -> float:
        if self.kind == 'value':
            return _metric_value(snaps, self.metric, self.labels)
        num_rate, den_rate = self._rates(snaps, now)
        if self.kind == 'rate':
            return num_rate
        return (num_rate / den_rate) if den_rate > 0 else 0.0


class AlertEngine:
    """Evaluate declarative SLO rules against merged registry snapshots.

    One engine runs on the learner (against local + merged fleet
    snapshots), one on the fleet resolver, one in the serving service.
    Fired alerts land as ``alerts_active{alert=}`` gauges,
    ``alerts_fired_total{alert=}`` counters, WARNING log transitions,
    flight-recorder events, and — on the learner — an ``alerts`` block in
    every metrics_jsonl record. ``maybe_evaluate`` is cadence-gated so the
    learner loop, the epoch writer and /statusz scrapes share one
    evaluation stream (rates need a stable window)."""

    def __init__(self, rules: Optional[Sequence[Dict[str, Any]]] = None,
                 interval: float = 5.0):
        specs = BUILTIN_ALERTS if rules is None else rules
        self.interval = max(0.2, float(interval))
        self._lock = threading.Lock()
        self._rules = [AlertRule(dict(s)) for s in specs]  # guarded-by: _lock
        self._last: Dict[str, Any] = {'time': 0.0, 'active': [],
                                      'fired': {}, 'values': {}}  # guarded-by: _lock
        self._log = get_logger('alerts')

    @classmethod
    def from_config(cls, args: Optional[Dict[str, Any]]
                    ) -> Optional['AlertEngine']:
        """Build from the ``telemetry.alerts`` block: ``{builtin, interval,
        rules: [...]}`` (or a bare rule list; False/{'enabled': False}
        disables). Returns None with alerting or telemetry off."""
        tel = config_block(args)
        if not tel.get('enabled', True) or not _ENABLED:
            return None
        blk = tel.get('alerts')
        if blk is False:
            return None
        if isinstance(blk, (list, tuple)):
            blk = {'rules': list(blk)}
        if not isinstance(blk, dict):
            blk = {}
        if not blk.get('enabled', True):
            return None
        by_name: Dict[str, Dict[str, Any]] = {}
        if blk.get('builtin', True):
            for spec in BUILTIN_ALERTS:
                by_name[str(spec['name'])] = dict(spec)
        for spec in (blk.get('rules') or []):
            if isinstance(spec, dict) and spec.get('name'):
                merged = dict(by_name.get(str(spec['name'])) or {})
                merged.update(spec)
                by_name[str(spec['name'])] = merged
        return cls(list(by_name.values()),
                   interval=float(blk.get('interval', 5.0)))

    def rule_names(self) -> List[str]:
        with self._lock:
            return [r.name for r in self._rules]

    def evaluate(self, snaps: List[Optional[Dict[str, Any]]],
                 now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation pass; returns the ``alerts`` block."""
        now = time.time() if now is None else float(now)
        fired, cleared = [], []
        with self._lock:
            for rule in self._rules:
                armed = (not rule.arm_metric
                         or _metric_value(snaps, rule.arm_metric) > 0)
                value = rule.value(snaps, now)
                rule.last_value = value
                breach = armed and _ALERT_OPS[rule.op](value, rule.threshold)
                if breach:
                    rule.ok_since = None
                    if rule.breach_since is None:
                        rule.breach_since = now
                    if (not rule.active
                            and now - rule.breach_since >= rule.for_s):
                        rule.active = True
                        rule.fired += 1
                        fired.append((rule.name, value))
                else:
                    rule.breach_since = None
                    if rule.active:
                        if rule.ok_since is None:
                            rule.ok_since = now
                        if now - rule.ok_since >= rule.clear_for:
                            rule.active = False
                            rule.ok_since = None
                            cleared.append((rule.name, value))
            block = {
                'time': round(now, 3),
                'active': sorted(r.name for r in self._rules if r.active),
                'fired': {r.name: r.fired for r in self._rules if r.fired},
                'values': {r.name: round(r.last_value, 6)
                           for r in self._rules},
            }
            self._last = block
        for name, value in fired:
            counter('alerts_fired_total', alert=name).inc()
            gauge('alerts_active', alert=name).set(1)
            record_event('alert', 'fired %s (value=%g)' % (name, value),
                         alert=name, state='firing')
            self._log.warning('alert FIRING: %s (value=%g)', name, value)
        for name, value in cleared:
            gauge('alerts_active', alert=name).set(0)
            record_event('alert', 'cleared %s (value=%g)' % (name, value),
                         alert=name, state='cleared')
            self._log.warning('alert cleared: %s (value=%g)', name, value)
        return block

    def maybe_evaluate(self, collect: Callable[[], List[Dict[str, Any]]],
                       now: Optional[float] = None) -> Dict[str, Any]:
        """Cadence-gated evaluation: runs a pass at most every
        ``interval`` seconds, otherwise returns the cached block."""
        now = time.time() if now is None else float(now)
        with self._lock:
            fresh = now - float(self._last.get('time') or 0.0) < self.interval
        if fresh:
            return self.block()
        return self.evaluate(collect(), now)

    def block(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._last)

    def active(self) -> List[str]:
        with self._lock:
            return [r.name for r in self._rules if r.active]


# ---------------------------------------------------------------------------
# Prometheus text exposition


def _prom_value(v) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def render_prometheus(snaps: List[Dict[str, Any]]) -> str:
    """Render snapshots in Prometheus text exposition format 0.0.4.
    Caller guarantees key disjointness across snapshots (the fleet snapshot
    is relabeled with source="fleet")."""
    types: Dict[str, str] = {}
    lines_by_name: Dict[str, List[str]] = {}

    def emit(name: str, labelstr: str, value, kind: str):
        if not _NAME_RE.match(name):
            return
        types.setdefault(name, kind)
        body = '%s{%s}' % (name, labelstr) if labelstr else name
        lines_by_name.setdefault(name, []).append(
            '%s %s' % (body, _prom_value(value)))

    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for key, v in (snap.get('counters') or {}).items():
            name, labelstr = split_key(key)
            emit(name, labelstr, v, 'counter')
        for key, v in (snap.get('gauges') or {}).items():
            name, labelstr = split_key(key)
            emit(name, labelstr, v, 'gauge')
        for key, h in (snap.get('hists') or {}).items():
            name, labelstr = split_key(key)
            types.setdefault(name, 'histogram')
            cum = 0
            for bound, n in zip(list(h['bounds']) + ['+Inf'],
                                h['buckets']):
                cum += n
                le = ('+Inf' if bound == '+Inf'
                      else _prom_value(bound))
                ls = (labelstr + ',' if labelstr else '') + 'le="%s"' % le
                lines_by_name.setdefault(name, []).append(
                    '%s_bucket{%s} %d' % (name, ls, cum))
            suffix = '{%s}' % labelstr if labelstr else ''
            lines_by_name.setdefault(name, []).append(
                '%s_sum%s %s' % (name, suffix, _prom_value(h['sum'])))
            lines_by_name.setdefault(name, []).append(
                '%s_count%s %d' % (name, suffix, h['count']))

    out: List[str] = []
    for name in sorted(lines_by_name):
        out.append('# TYPE %s %s' % (name, types[name]))
        out.extend(lines_by_name[name])
    return '\n'.join(out) + ('\n' if out else '')


class TelemetryExporter:
    """Prometheus-style scrape endpoint on stdlib http.server.

    ``collect`` returns the snapshots to serve (called per scrape, so the
    endpoint always shows live registry values); ``port=0`` binds an
    ephemeral port (tests), a fixed port serves operators' scrape configs.
    ``/metrics`` answers the exposition text, ``/healthz`` a liveness
    ``ok`` line, ``/statusz`` a JSON health view (run identity, recorder
    stats, plus whatever the ``status`` callable contributes — active
    alerts, fleet states, run progress); every other path 404s.
    """

    def __init__(self, collect: Callable[[], List[Dict[str, Any]]],
                 port: int = 0, host: str = '',
                 status: Optional[Callable[[], Dict[str, Any]]] = None):
        self._collect = collect
        self._status = status
        self._host = host
        self._port = int(port)
        self._server = None
        self._thread = None

    @property
    def port(self) -> int:
        return self._port

    def status_payload(self) -> Dict[str, Any]:
        """The /statusz JSON: base process identity + recorder stats,
        overlaid with the owner's status callable (alerts, fleet states,
        progress, SLO snapshots)."""
        base: Dict[str, Any] = {
            'run_id': _RUN_ID, 'role': _TRACE.label, 'pid': os.getpid(),
            'time': round(time.time(), 3), 'recorder': recorder_stats()}
        if self._status is not None:
            extra = self._status()
            if isinstance(extra, dict):
                base.update(extra)
        return base

    def start(self) -> 'TelemetryExporter':
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, body: bytes, ctype: str):
                self.send_response(200)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split('?')[0]
                if path == '/healthz':
                    self._respond(b'ok\n', 'text/plain; charset=utf-8')
                    return
                if path == '/statusz':
                    try:
                        body = json.dumps(exporter.status_payload(),
                                          sort_keys=True).encode()
                    except Exception as exc:   # a broken status callable
                        self.send_error(500, str(exc)[:120])   # 500s, only
                        return
                    self._respond(body, 'application/json; charset=utf-8')
                    return
                if path not in ('/metrics', '/'):
                    self.send_error(404)
                    return
                try:
                    body = render_prometheus(exporter._collect()).encode()
                except Exception as exc:   # a broken collector must not
                    self.send_error(500, str(exc)[:120])   # kill the server
                    return
                self._respond(
                    body, 'text/plain; version=0.0.4; charset=utf-8')

            def log_message(self, fmt, *args):
                get_logger('exporter').debug(fmt, *args)

        # Bind with retry, then fall back to an ephemeral port: a stale
        # TIME_WAIT socket or a colliding process on the configured
        # telemetry_port must degrade the scrape target, not crash the
        # learner. The actual bound port is logged (and kept on .port).
        log = get_logger('exporter')
        requested = self._port
        attempts = ([requested] * 3 + [0]) if requested else [0]
        server, last_err = None, None
        for i, port in enumerate(attempts):
            try:
                server = ThreadingHTTPServer((self._host, port), Handler)
                break
            except OSError as exc:
                last_err = exc
                if port and i + 1 < len(attempts) and attempts[i + 1]:
                    log.warning('telemetry port %d bind failed (%s); '
                                'retrying', port, exc)
                    time.sleep(0.2 * (i + 1))
        if server is None:
            log.error('telemetry exporter could not bind any port (%s); '
                      'exporter disabled for this run', last_err)
            return self
        self._server = server
        self._server.daemon_threads = True
        self._port = self._server.server_address[1]
        if requested and self._port != requested:
            counter('telemetry_port_fallbacks_total').inc()
            log.warning('telemetry_port %d unavailable (%s); serving '
                        '/metrics on ephemeral port %d instead',
                        requested, last_err, self._port)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name='telemetry-exporter',
                                        daemon=True)
        self._thread.start()
        log.info('telemetry exporter serving /metrics on port %d',
                 self._port)
        return self

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


# ---------------------------------------------------------------------------
# XLA compile-event counters (jax.monitoring listeners)

_JAX_MONITORING_INSTALLED = False


def install_jax_monitoring() -> bool:
    """Subscribe to jax.monitoring and count XLA compile activity into the
    registry: ``xla_compile_events_total{event=...}`` (cache hits/misses,
    compile requests) and the ``xla_compile_seconds`` duration histogram
    (jaxpr trace / MLIR lowering / backend compile). Idempotent and
    version-tolerant — a jax without the monitoring API simply reports
    False. Catches unexpected recompiles (a new padded bucket shape, a
    donation-geometry change) that otherwise only show up as mystery
    latency spikes in the trace."""
    global _JAX_MONITORING_INSTALLED
    if _JAX_MONITORING_INSTALLED:
        return True
    try:
        import jax.monitoring as _jm
    except Exception:
        return False

    def _on_event(event, *a, **kw):
        try:
            if 'compil' in event:
                REGISTRY.counter('xla_compile_events_total',
                                 event=str(event).strip('/')).inc()
        except Exception:
            pass   # a metrics listener must never break a compile

    def _on_duration(event, duration, *a, **kw):
        try:
            if 'compil' in event:
                REGISTRY.histogram('xla_compile_seconds',
                                   buckets=COMPILE_SECOND_BUCKETS).observe(
                                       float(duration))
        except Exception:
            pass
        # Retrace sentinel: after mark_steady_state() every lowering event
        # is a recompile the steady-state train loop should never see.
        # Deliberately OUTSIDE the try/except so the abort policy's
        # RetraceError propagates into the jitted call site.
        if _STEADY['on'] and event == _RETRACE_EVENT:
            _note_retrace(event)

    try:
        _jm.register_event_listener(_on_event)
        _jm.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _JAX_MONITORING_INSTALLED = True
    return True


# ---------------------------------------------------------------------------
# Compiled-performance plane (docs/observability.md "Compiled-performance
# plane"): device-memory gauges, the steady-state retrace sentinel, and the
# dispatch/host_block utilization proxy. All process-local state lives in
# the two dicts below so tests can reset it cleanly.

RETRACE_POLICIES = ('warn', 'abort', 'off')

# The lowering duration event fires on every in-memory jit-cache miss —
# unlike backend_compile, which the persistent compilation cache can skip —
# so it is the reliable "a retrace happened" signal.
_RETRACE_EVENT = '/jax/core/compile/jaxpr_to_mlir_module_duration'

_PERF_PLANE: Dict[str, Any] = {
    'enabled': True, 'retrace': 'warn', 'last_mem': [], 'util': None}

_STEADY: Dict[str, Any] = {
    'on': False, 'since': 0.0, 'retraces': 0, 'note': '',
    'last_compile': '', 'filter_on': False}


class RetraceError(RuntimeError):
    """Raised at a jitted call site when a post-steady-state XLA retrace
    occurs under the ``abort`` policy (HANDYRL_TPU_RETRACE=abort)."""


def configure_perf_plane(enabled=None, retrace=None):
    """Adopt the ``telemetry.perf_plane`` / ``telemetry.retrace`` config
    knobs (called from adopt_config on every process in the fleet)."""
    if enabled is not None:
        _PERF_PLANE['enabled'] = bool(enabled)
    if retrace is not None:
        retrace = str(retrace).strip().lower()
        if retrace in RETRACE_POLICIES:
            _PERF_PLANE['retrace'] = retrace


def perf_plane_enabled() -> bool:
    return _ENABLED and bool(_PERF_PLANE['enabled'])


def retrace_policy() -> str:
    """Active retrace policy: the HANDYRL_TPU_RETRACE env knob (the CI
    override) wins over the ``telemetry.retrace`` config value."""
    env = os.environ.get('HANDYRL_TPU_RETRACE', '').strip().lower()
    if env in RETRACE_POLICIES:
        return env
    return _PERF_PLANE['retrace']


class _CompileNameFilter(logging.Filter):
    """Captures the callable/shape key from jax's ``jax_log_compiles``
    WARNING ("Compiling <fn> with global shapes and types [...]") — the
    only place jax names what it is compiling — and swallows the record so
    the sentinel, not jax, owns the operator-facing message."""

    def filter(self, record):
        try:
            msg = record.getMessage()
            if msg.startswith('Compiling'):
                key = msg.split('. Argument mapping', 1)[0]
                _STEADY['last_compile'] = key[:300]
                return False
            if msg.startswith('Finished '):
                # jax_log_compiles' per-phase "Finished tracing/lowering/
                # compilation" chatter — the sentinel owns the message
                return False
        except Exception:
            pass
        return True


_COMPILE_FILTER = _CompileNameFilter()
_COMPILE_LOGGERS = ('jax._src.interpreters.pxla', 'jax._src.dispatch')


def mark_steady_state(note: str = ''):
    """Declare warm-up over: from here on, every XLA compile is a retrace
    the sentinel counts, records, and (under the abort policy) raises on.
    The Trainer crosses this boundary after ``retrace_warmup_epochs``."""
    if not (perf_plane_enabled() and _JAX_MONITORING_INSTALLED):
        return False
    if _STEADY['on']:
        return True
    _STEADY.update(on=True, since=time.time(), retraces=0, note=note)
    try:
        import jax
        jax.config.update('jax_log_compiles', True)
        if not _STEADY['filter_on']:
            for name in _COMPILE_LOGGERS:
                logging.getLogger(name).addFilter(_COMPILE_FILTER)
            _STEADY['filter_on'] = True
    except Exception:
        pass   # sentinel still counts retraces, just without callable names
    gauge('xla_steady_state').set(1)
    record_event('steady_state', 'steady state marked%s'
                 % ((': ' + note) if note else ''), policy=retrace_policy())
    return True


def clear_steady_state():
    """Leave steady state (learner shutdown, or test teardown). The flag is
    process-global, so in-process learners must clear it or a later jit in
    the same process would trip the sentinel."""
    _STEADY.update(on=False, note='', last_compile='')
    gauge('xla_steady_state').set(0)
    try:
        import jax
        if _STEADY['filter_on']:
            for name in _COMPILE_LOGGERS:
                logging.getLogger(name).removeFilter(_COMPILE_FILTER)
            _STEADY['filter_on'] = False
        jax.config.update('jax_log_compiles', False)
    except Exception:
        pass


def steady_state_active() -> bool:
    return bool(_STEADY['on'])


def steady_retrace_count() -> int:
    return int(_STEADY['retraces'])


# Signature-polymorphic helpers (utils/fetch.py's per-signature packed-
# transfer jits, eval-share probes) legitimately compile NEW programs after
# warm-up — once per fresh signature, by design. They declare those scopes
# with expected_compile() and the sentinel books the compile under
# xla_expected_compiles_total instead of treating it as a retrace.
# Thread-local because jit compilation is synchronous on the calling
# thread, so the listener fires on the same thread that opened the scope.
_EXPECTED_COMPILE = threading.local()


@contextmanager
def expected_compile(reason: str = ''):
    """Declare that any XLA compile inside this scope is expected (a known
    signature-polymorphic helper seeing a fresh signature), exempting it
    from the retrace sentinel's count/warn/abort path."""
    depth = getattr(_EXPECTED_COMPILE, 'depth', 0)
    _EXPECTED_COMPILE.depth = depth + 1
    _EXPECTED_COMPILE.reason = reason
    try:
        yield
    finally:
        _EXPECTED_COMPILE.depth = depth


def _in_expected_compile() -> bool:
    return getattr(_EXPECTED_COMPILE, 'depth', 0) > 0


def _note_retrace(event: str):
    """One post-steady-state recompile: count it, flight-record it, warn —
    and under the abort policy raise so the jitted call site fails loudly.
    The raise sits outside the metric try/except on purpose."""
    policy = retrace_policy()
    if policy == 'off':
        return
    if _in_expected_compile():
        try:
            counter('xla_expected_compiles_total').inc()
        except Exception:
            pass
        return
    who = _STEADY['last_compile'] or ('event ' + event.strip('/'))
    try:
        _STEADY['retraces'] += 1
        counter('xla_retraces_total').inc()
        record_event('retrace', 'steady-state XLA retrace: %s' % who,
                     policy=policy, count=_STEADY['retraces'])
        get_logger('retrace').warning(
            'steady-state XLA retrace #%d (%s) — a shape/donation bucket '
            'regression is recompiling the hot program', _STEADY['retraces'],
            who)
    except Exception:
        pass
    if policy == 'abort':
        raise RetraceError(
            'steady-state XLA retrace under HANDYRL_TPU_RETRACE=abort: %s'
            % who)


def _rss_memory() -> Dict[str, int]:
    """CPU fallback when Device.memory_stats() is unavailable: process RSS
    (current), VmHWM (peak), physical RAM (limit) — all from procfs."""
    in_use = peak = limit = 0
    try:
        page = os.sysconf('SC_PAGE_SIZE')
        with open('/proc/self/statm') as fh:
            in_use = int(fh.read().split()[1]) * page
        limit = os.sysconf('SC_PHYS_PAGES') * page
    except Exception:
        pass
    try:
        with open('/proc/self/status') as fh:
            for line in fh:
                if line.startswith('VmHWM:'):
                    peak = int(line.split()[1]) * 1024
                    break
    except Exception:
        try:
            import resource
            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            pass
    return {'bytes_in_use': in_use,
            'peak_bytes_in_use': max(peak, in_use),
            'bytes_limit': limit}


def sample_device_memory(devices=None):
    """Sample per-device memory into the ``device_mem_bytes_*`` gauges.
    Real accelerators report via Device.memory_stats(); backends without it
    (CPU) get one process-RSS row labelled ``process_rss`` — one row, not
    one per CPU "device", since they all share this process's memory."""
    if not perf_plane_enabled():
        return []
    if devices is None:
        try:
            import jax
            devices = jax.local_devices()
        except Exception:
            devices = []
    rows = []
    for dev in devices:
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats:
            label = '%s:%s' % (getattr(dev, 'platform', 'dev'),
                               getattr(dev, 'id', len(rows)))
            row = {'device': label,
                   'bytes_in_use': int(stats.get('bytes_in_use', 0)),
                   'peak_bytes_in_use': int(
                       stats.get('peak_bytes_in_use',
                                 stats.get('bytes_in_use', 0))),
                   'bytes_limit': int(stats.get('bytes_limit', 0))}
            rows.append(row)
        else:
            row = dict(_rss_memory(), device='process_rss')
            rows.append(row)
            break   # every CPU "device" is this same process
    for row in rows:
        dev = row['device']
        gauge('device_mem_bytes_in_use', device=dev).set(row['bytes_in_use'])
        gauge('device_mem_bytes_peak', device=dev).set(
            row['peak_bytes_in_use'])
        gauge('device_mem_bytes_limit', device=dev).set(row['bytes_limit'])
    _PERF_PLANE['last_mem'] = rows
    return rows


def device_memory_utilization(rows=None):
    """Worst-case bytes_in_use/bytes_limit across sampled devices — the
    ``hbm_pressure`` alert input. Only the learner publishes the
    ``device_mem_utilization`` gauge (a ratio must not be summed across
    fleet snapshots the way counters are)."""
    rows = _PERF_PLANE['last_mem'] if rows is None else rows
    util = 0.0
    for row in rows:
        limit = float(row.get('bytes_limit') or 0)
        if limit > 0:
            util = max(util, float(row.get('bytes_in_use', 0)) / limit)
    return util


def utilization_from_stages(stages) -> Optional[float]:
    """Device-utilization proxy from one epoch's ingest-stage seconds:
    host_block / total. Near 1.0 the host spends the epoch waiting on the
    device (device-bound, good); near 0.0 the device is starving behind
    host work (select/decode/assemble/ipc/h2d/dispatch). Accepts plain
    ``{stage: seconds}`` or StageTimer.snapshot's ``{stage: {'s':..}}``."""

    def _sec(val):
        if isinstance(val, dict):
            val = val.get('s', 0.0)
        return float(val or 0.0)

    try:
        total = sum(_sec(stages.get(s)) for s in INGEST_STAGES)
        block = _sec(stages.get('host_block'))
    except Exception:
        return None
    if total <= 0:
        return None
    return block / total


def set_utilization_proxy(value):
    if value is None or not perf_plane_enabled():
        return
    value = max(0.0, min(1.0, float(value)))
    _PERF_PLANE['util'] = value
    gauge('device_utilization_proxy').set(value)


def perf_status() -> Dict[str, Any]:
    """Compiled-performance block for /statusz (rendered by --status)."""
    return {
        'steady_state': bool(_STEADY['on']),
        'retraces': int(_STEADY['retraces']),
        'retrace_policy': retrace_policy(),
        'device_memory': list(_PERF_PLANE['last_mem']),
        'device_mem_utilization': device_memory_utilization(),
        'device_utilization_proxy': _PERF_PLANE['util']}


# ---------------------------------------------------------------------------
# JSONL schema helper (shared by tests and the CI smoke script)

FLEET_KEYS = ('epoch', 'steps', 'episodes', 'time', 'run_id', 'telemetry')


def validate_metrics_line(line: str, fleet: bool = False) -> Dict[str, Any]:
    """Parse one metrics_jsonl line and assert the telemetry schema: the
    base keys always, plus the merged ``fleet_telemetry`` aggregate when
    ``fleet`` (server-mode runs). Raises ValueError on any violation."""
    rec = json.loads(line)
    for key in FLEET_KEYS:
        if key not in rec:
            raise ValueError('metrics line missing %r: %s' % (key, line[:120]))
    tel = rec['telemetry']
    if not isinstance(tel, dict) or 'counters' not in tel:
        raise ValueError('telemetry summary malformed: %r' % (tel,))
    if fleet:
        ft = rec.get('fleet_telemetry')
        if not isinstance(ft, dict) or 'counters' not in ft:
            raise ValueError('fleet_telemetry missing/malformed: %r' % (ft,))
    if 'alerts' in rec:
        ab = rec['alerts']
        if not isinstance(ab, dict) or 'active' not in ab:
            raise ValueError('alerts block malformed: %r' % (ab,))
    return rec


# ---------------------------------------------------------------------------
# operator status view (``main.py --status <host:port>``)


def fetch_statusz(target: str, timeout: float = 5.0) -> Dict[str, Any]:
    """GET http://<target>/statusz and parse the JSON payload."""
    import urllib.request
    with urllib.request.urlopen('http://%s/statusz' % target,
                                timeout=timeout) as resp:
        return json.loads(resp.read().decode('utf-8'))


def render_status(payload: Dict[str, Any]) -> str:
    """Human-readable rendering of one /statusz payload."""
    lines = ['%s pid=%s run=%s' % (payload.get('role', '?'),
                                   payload.get('pid', '?'),
                                   payload.get('run_id', '?'))]
    progress = payload.get('progress')
    if isinstance(progress, dict):
        lines.append('progress: ' + ' '.join(
            '%s=%s' % (k, progress[k]) for k in sorted(progress)))
    alerts = payload.get('alerts')
    if isinstance(alerts, dict):
        active = alerts.get('active') or []
        lines.append('alerts: %s'
                     % (', '.join('FIRING %s' % a for a in active)
                        if active else 'none active'))
        fired = alerts.get('fired') or {}
        if fired:
            lines.append('  fired so far: ' + ', '.join(
                '%s x%d' % (k, fired[k]) for k in sorted(fired)))
    for key in ('fleet_hosts', 'fleet_replicas'):
        states = payload.get(key)
        if isinstance(states, dict) and states:
            lines.append('%s: ' % key.replace('_', ' ') + ', '.join(
                '%s=%s' % (k, states[k]) for k in sorted(states)))
    slo = payload.get('slo')
    if isinstance(slo, dict):
        lines.append('slo: ' + ' '.join(
            '%s=%s' % (k, slo[k]) for k in sorted(slo)))
    perf = payload.get('perf')
    if isinstance(perf, dict):
        bits = ['steady' if perf.get('steady_state') else 'warming',
                'retraces=%s' % perf.get('retraces', 0),
                'policy=%s' % perf.get('retrace_policy', '?')]
        util = perf.get('device_utilization_proxy')
        if util is not None:
            bits.append('device_util=%.0f%%' % (float(util) * 100.0))
        mem_util = perf.get('device_mem_utilization')
        if mem_util:
            bits.append('mem_util=%.0f%%' % (float(mem_util) * 100.0))
        lines.append('perf: ' + ' '.join(bits))
        for row in perf.get('device_memory') or []:
            limit = row.get('bytes_limit') or 0
            lines.append('  mem %s: %.0f MiB in use (peak %.0f) of %s'
                         % (row.get('device', '?'),
                            row.get('bytes_in_use', 0) / 2**20,
                            row.get('peak_bytes_in_use', 0) / 2**20,
                            ('%.0f MiB' % (limit / 2**20)) if limit
                            else 'unknown'))
    sessions = payload.get('sessions')
    if isinstance(sessions, list) and sessions:
        lines.append('sessions: %d active' % len(sessions))
        lines.append('  %-12s %-14s %6s %9s %9s %-8s'
                     % ('sid', 'client', 'plies', 'version', 'ply_p99',
                        'replica'))
        for s in sessions:
            p99 = s.get('ply_p99_ms')
            lines.append('  %-12s %-14s %6s %9s %9s %-8s'
                         % (s.get('sid', '?'), s.get('client', '?'),
                            s.get('plies', 0), s.get('version') or '-',
                            ('%.1fms' % p99) if p99 is not None else '-',
                            s.get('replica') or '-'))
    requests = payload.get('requests')
    if isinstance(requests, list) and requests:
        lines.append('requests:')
        lines.append('  %-10s %8s %9s %9s %9s %9s %s'
                     % ('replica', 'inflight', 'p50', 'p99', 'received',
                        'answered', 'state'))
        for r in requests:
            lines.append('  %-10s %8s %8.1fms %8.1fms %9s %9s %s'
                         % (r.get('replica', '?'), r.get('inflight', 0),
                            float(r.get('p50_ms') or 0.0),
                            float(r.get('p99_ms') or 0.0),
                            r.get('received', 0), r.get('answered', 0),
                            'draining' if r.get('draining') else 'serving'))
    rec = payload.get('recorder')
    if isinstance(rec, dict):
        lines.append('recorder: %s/%s events (%s dropped), %d dump(s)'
                     % (rec.get('events', 0), rec.get('capacity', 0),
                        rec.get('dropped', 0), len(rec.get('dumps') or [])))
    return '\n'.join(lines)


def status_main(args: Optional[Dict[str, Any]], argv: Sequence[str]):
    """``main.py --status <host:port>``: fetch a live /statusz (the
    learner's telemetry_port or a serving metrics_port) and render it."""
    rest = [a for a in argv if not a.startswith('--')]
    target = rest[0] if rest else ''
    if not target:
        port = int((args or {}).get('telemetry_port') or 0)
        if port:
            target = 'localhost:%d' % port
    if not target:
        print('usage: main.py --status <host:port> [--json]')
        raise SystemExit(1)
    if ':' not in target:
        target = 'localhost:' + target
    try:
        payload = fetch_statusz(target)
    except Exception as exc:
        print('status fetch from %s failed: %s' % (target, exc))
        raise SystemExit(1)
    if '--json' in argv:
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print(render_status(payload))
    return payload
