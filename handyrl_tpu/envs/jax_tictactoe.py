"""Pure-JAX vectorized TicTacToe: the whole environment as jittable array
functions.

The host-side envs (envs/tictactoe.py) mirror the reference's Python-object
protocol; this module is the fully TPU-resident counterpart used by the
device rollout engine (device_generation.py): N boards advance as one
program — reset, legal mask, win detection, observation encoding and
auto-reset are all jnp ops, so self-play stepping never leaves the chip.

State pytree (all leaves have leading env axis N):
  boards  (N, 9)  int8   +1 black / -1 white / 0 empty
  side    (N,)    int8   side to move (+1/-1)
  winner  (N,)    int8   +1/-1 when decided, 0 otherwise
  moves   (N,)    int8   plies played
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .tictactoe import WIN_LINES

N_ACTIONS = 9
MAX_STEPS = 9
NUM_PLAYERS = 2
# deterministic given the action sequence: device-actor records can replay
# byte-identically through the host sampling contract (generation.py)
RNG_COMPAT = 'strict'


class State(NamedTuple):
    boards: jnp.ndarray
    side: jnp.ndarray
    winner: jnp.ndarray
    moves: jnp.ndarray


def init_state(n: int) -> State:
    return State(
        boards=jnp.zeros((n, 9), jnp.int8),
        side=jnp.ones((n,), jnp.int8),
        winner=jnp.zeros((n,), jnp.int8),
        moves=jnp.zeros((n,), jnp.int8),
    )


def legal_mask(state: State) -> jnp.ndarray:
    """(N, 9) float 1 = legal."""
    return (state.boards == 0).astype(jnp.float32)


def terminal(state: State) -> jnp.ndarray:
    return (state.winner != 0) | (state.moves >= MAX_STEPS)


def turn(state: State) -> jnp.ndarray:
    """Acting player index (0/1) per env."""
    return (state.moves % 2).astype(jnp.int32)


def observe(state: State) -> jnp.ndarray:
    """Side-to-move view planes (N, 3, 3, 3): [const 1, mine, theirs]."""
    mine = (state.boards == state.side[:, None]).astype(jnp.float32)
    theirs = (state.boards == -state.side[:, None]).astype(jnp.float32)
    ones = jnp.ones_like(mine)
    planes = jnp.stack([ones, mine, theirs], axis=1)       # (N, 3, 9)
    return planes.reshape(-1, 3, 3, 3)


def step(state: State, actions: jnp.ndarray) -> State:
    """Apply one action per env (envs already terminal are left unchanged by
    the caller via auto-reset)."""
    n = state.boards.shape[0]
    boards = state.boards.at[jnp.arange(n), actions].set(state.side)
    line_sums = boards[:, WIN_LINES].sum(axis=2)           # (N, 8)
    won = (line_sums == 3 * state.side[:, None].astype(jnp.int32)).any(axis=1)
    winner = jnp.where(won & (state.winner == 0), state.side, state.winner)
    return State(boards=boards, side=-state.side,
                 winner=winner.astype(jnp.int8),
                 moves=state.moves + 1)


def outcome(state: State) -> jnp.ndarray:
    """(N, 2) outcome per player seat (player 0 is black)."""
    w = state.winner.astype(jnp.float32)
    return jnp.stack([w, -w], axis=1)


def auto_reset(state: State, done: jnp.ndarray) -> State:
    """Replace finished envs with fresh boards."""
    fresh = init_state(state.boards.shape[0])
    pick = lambda a, b: jnp.where(done.reshape((-1,) + (1,) * (a.ndim - 1)), a, b)
    return State(*(pick(f, s) for f, s in zip(fresh, state)))
