"""Geister: 2-player imperfect-information board game.

Behavior parity with the reference game (`/root/reference/handyrl/envs/
geister.py:170-541`): 6x6 board, each side secretly assigns 4 blue (good) and
4 red (bad) ghosts to 8 fixed home squares (70 possible layouts, action ids
144..213), then alternates single-square orthogonal moves (action ids
0..143 = direction*36 + from-square, always encoded from the mover's own
rotated perspective). Capturing all of the opponent's blues or losing all
your reds loses for them; a blue ghost may escape through the opponent's two
corner goal cells; 200 plies is a draw. Per-step reward -0.01 for both
players. Observations hide the opponent's piece types (the imperfect
information) and are rotated 180 degrees for the second player.

The delta-sync protocol ('set' layout or -1 for the hidden opponent layout,
'move' strings, 'captured' type disclosure to the capturing player) matches
the reference so network battles and the consistency oracle carry over; a
mirror env assigns random types to unseen opponent pieces and corrects
squares when captures reveal them.
"""

from __future__ import annotations

import itertools
import random
from typing import Dict, List, Optional

import numpy as np

from ..environment import BaseEnvironment

ROWS, COLS = 'ABCDEF', '123456'
BLUE, RED = 0, 1
TYPE_CHARS = 'BR'
GLYPHS = {-1: '_', 0: 'B', 1: 'R', 2: 'b', 3: 'r', 4: '*'}

# orthogonal step offsets, index = action direction for the BLACK perspective
STEPS = np.array([(-1, 0), (0, -1), (0, 1), (1, 0)], dtype=np.int32)

# home squares per color, in layout-slot order
HOME_SQUARES = [
    ['B2', 'C2', 'D2', 'E2', 'B1', 'C1', 'D1', 'E1'],   # first player (black)
    ['E5', 'D5', 'C5', 'B5', 'E6', 'D6', 'C6', 'B6'],   # second player (white)
]

# goal (escape) cells just off the board, per color
GOALS = np.array([[(-1, 5), (6, 5)], [(-1, 0), (6, 0)]], dtype=np.int32)

# the 70 ways to pick which 4 of the 8 home slots hold blue ghosts
LAYOUTS = list(itertools.combinations(range(8), 4))

N_MOVE_ACTIONS = 4 * 36
N_SET_ACTIONS = len(LAYOUTS)


def piece_of(color: int, ptype: int) -> int:
    return color * 2 + ptype


def color_of(piece: int) -> int:
    return -1 if piece < 0 else piece // 2


def type_of(piece: int) -> int:
    return -1 if piece < 0 else piece % 2


class Environment(BaseEnvironment):

    def __init__(self, args: Optional[dict] = None):
        super().__init__(args)
        self.args = args or {}
        self.reset()

    def reset(self, args: Optional[dict] = None):
        self.board = np.full((6, 6), -1, dtype=np.int32)
        self.color = 0                   # 0 = first player (black), to move
        self.turn_count = -2             # two setup plies before ply 0
        self.win_color: Optional[int] = None   # 0/1 winner, 2 draw
        self.counts = np.zeros(4, dtype=np.int32)      # alive per piece kind
        # per piece-slot (color*8+slot): current square or (-1,-1) if gone
        self.slot_pos = np.full((16, 2), -1, dtype=np.int32)
        # board -> slot index for O(1) capture bookkeeping
        self.slot_at = np.full((6, 6), -1, dtype=np.int32)
        self.moves: List[int] = []
        self.captured_type: Optional[int] = None
        self.layouts: Dict[int, int] = {}

    # -- geometry helpers --------------------------------------------------
    @staticmethod
    def _onboard(pos) -> bool:
        return 0 <= pos[0] < 6 and 0 <= pos[1] < 6

    @staticmethod
    def _rot(pos):
        return np.array((5 - pos[0], 5 - pos[1]), dtype=np.int32)

    def _is_goal(self, color: int, pos) -> bool:
        return any(g[0] == pos[0] and g[1] == pos[1] for g in GOALS[color])

    # -- square <-> string -------------------------------------------------
    @staticmethod
    def _sq2str(pos) -> str:
        if 0 <= pos[0] < 6 and 0 <= pos[1] < 6:
            return ROWS[pos[0]] + COLS[pos[1]]
        return '**'

    @staticmethod
    def _str2sq(s: str):
        if s == '**':
            return None
        return np.array((ROWS.find(s[0]), COLS.find(s[1])), dtype=np.int32)

    # -- action codec (mover-perspective encoding) ------------------------
    def _encode_move(self, pos_from, direction: int, color: int) -> int:
        if color == 1:
            pos_from = self._rot(pos_from)
            direction = 3 - direction
        return direction * 36 + pos_from[0] * 6 + pos_from[1]

    def _move_from(self, action: int, color: int):
        sq = action % 36
        pos = np.array((sq // 6, sq % 6), dtype=np.int32)
        return self._rot(pos) if color == 1 else pos

    def _move_dir(self, action: int, color: int) -> int:
        d = action // 36
        return 3 - d if color == 1 else d

    def _move_to(self, action: int, color: int):
        return self._move_from(action, color) + STEPS[self._move_dir(action, color)]

    def action2str(self, a: int, player: Optional[int] = None) -> str:
        if a >= N_MOVE_ACTIONS:
            return 's%d' % (a - N_MOVE_ACTIONS)
        c = player
        return (self._sq2str(self._move_from(a, c))
                + self._sq2str(self._move_to(a, c)))

    def str2action(self, s: str, player: Optional[int] = None) -> int:
        if s[0] == 's':
            return N_MOVE_ACTIONS + int(s[1:])
        c = player
        pos_from = self._str2sq(s[:2])
        pos_to = self._str2sq(s[2:])
        if pos_to is None:
            # an escape move: find the adjacent goal cell
            for g in GOALS[c]:
                if int(((pos_from - g) ** 2).sum()) == 1:
                    diff = g - pos_from
                    break
        else:
            diff = pos_to - pos_from
        direction = next(d for d, dd in enumerate(STEPS)
                         if dd[0] == diff[0] and dd[1] == diff[1])
        return self._encode_move(pos_from, direction, c)

    # -- piece bookkeeping -------------------------------------------------
    def _place(self, piece: int, pos, slot: int):
        self.board[pos[0], pos[1]] = piece
        self.slot_pos[slot] = pos
        self.slot_at[pos[0], pos[1]] = slot
        self.counts[piece] += 1

    def _remove(self, pos):
        piece = self.board[pos[0], pos[1]]
        slot = self.slot_at[pos[0], pos[1]]
        self.board[pos[0], pos[1]] = -1
        self.slot_at[pos[0], pos[1]] = -1
        self.slot_pos[slot] = (-1, -1)
        self.counts[piece] -= 1
        return piece

    def _relocate(self, pos_from, pos_to):
        piece = self.board[pos_from[0], pos_from[1]]
        slot = self.slot_at[pos_from[0], pos_from[1]]
        self.board[pos_from[0], pos_from[1]] = -1
        self.slot_at[pos_from[0], pos_from[1]] = -1
        self.board[pos_to[0], pos_to[1]] = piece
        self.slot_at[pos_to[0], pos_to[1]] = slot
        self.slot_pos[slot] = pos_to

    # -- transitions -------------------------------------------------------
    def _apply_layout(self, layout: int):
        self.layouts[self.color] = layout
        if layout < 0:
            layout = random.randrange(N_SET_ACTIONS)   # hidden opponent setup
        blue_slots = set(LAYOUTS[layout])
        for slot in range(8):
            ptype = BLUE if slot in blue_slots else RED
            pos = self._str2sq(HOME_SQUARES[self.color][slot])
            self._place(piece_of(self.color, ptype), pos, self.color * 8 + slot)
        self.color = 1 - self.color
        self.turn_count += 1

    def play(self, action: int, player: Optional[int] = None):
        if self.turn_count < 0:
            return self._apply_layout(action - N_MOVE_ACTIONS)

        pos_from = self._move_from(action, self.color)
        pos_to = self._move_to(action, self.color)
        self.captured_type = None

        if not self._onboard(pos_to):
            # blue ghost escapes: mover wins
            self._remove(pos_from)
            self.win_color = self.color
        else:
            target = self.board[pos_to[0], pos_to[1]]
            if target != -1:
                captured = self._remove(pos_to)
                self.captured_type = type_of(captured)
                if self.counts[captured] == 0:
                    if type_of(captured) == BLUE:
                        # took every opponent blue: mover wins
                        self.win_color = self.color
                    else:
                        # took every opponent red: mover loses
                        self.win_color = 1 - self.color
            self._relocate(pos_from, pos_to)

        self.color = 1 - self.color
        self.turn_count += 1
        self.moves.append(action)

        if self.turn_count >= 200 and self.win_color is None:
            self.win_color = 2   # draw

    # -- protocol ----------------------------------------------------------
    def turn(self) -> int:
        return self.players()[self.turn_count % 2]

    def terminal(self) -> bool:
        return self.win_color is not None

    def reward(self) -> Dict[int, float]:
        return {p: -0.01 for p in self.players()}

    def outcome(self) -> Dict[int, float]:
        scores = [0.0, 0.0]
        if self.win_color == 0:
            scores = [1.0, -1.0]
        elif self.win_color == 1:
            scores = [-1.0, 1.0]
        return {p: scores[i] for i, p in enumerate(self.players())}

    def legal_actions(self, player: Optional[int] = None) -> List[int]:
        if self.turn_count < 0:
            return [N_MOVE_ACTIONS + i for i in range(N_SET_ACTIONS)]
        actions = []
        c = self.color
        for slot in range(c * 8, (c + 1) * 8):
            pos = self.slot_pos[slot]
            if pos[0] < 0:
                continue
            ptype = type_of(self.board[pos[0], pos[1]])
            for d in range(4):
                to = pos + STEPS[d]
                if self._onboard(to):
                    if color_of(self.board[to[0], to[1]]) == c:
                        continue   # own piece in the way
                elif not (ptype == BLUE and self._is_goal(c, to)):
                    continue       # only blues may escape, only via goals
                actions.append(self._encode_move(pos, d, c))
        return actions

    def players(self) -> List[int]:
        return [0, 1]

    # -- delta sync (network battle / mirror envs) ------------------------
    def diff_info(self, player: Optional[int] = None):
        color = player
        mover = (self.turn_count - 1) % 2
        info: Dict[str, object] = {}
        if not self.moves:
            if self.turn_count > -2:
                info['set'] = self.layouts[mover] if color == mover else -1
        else:
            info['move'] = self.action2str(self.moves[-1], mover)
            if color == mover and self.captured_type is not None:
                info['captured'] = TYPE_CHARS[self.captured_type]
        return info

    def update(self, info, reset: bool):
        if reset:
            self.reset(info if isinstance(info, dict) else None)
        elif 'set' in info:
            self._apply_layout(info['set'])
        elif 'move' in info:
            action = self.str2action(info['move'], self.color)
            if 'captured' in info:
                # the capture reveals the true type: fix the square first
                pos_to = self._move_to(action, self.color)
                t = TYPE_CHARS.index(info['captured'])
                wrong = self.board[pos_to[0], pos_to[1]]
                actual = piece_of(1 - self.color, t)
                self.counts[wrong] -= 1
                self.counts[actual] += 1
                self.board[pos_to[0], pos_to[1]] = actual
            self.play(action)

    # -- observation -------------------------------------------------------
    def observation(self, player: Optional[int] = None):
        """Dict obs {scalar(18), board(7,6,6)} from the viewer's own
        perspective; opponent piece types are hidden unless player is None
        (the omniscient view). Second player sees the board rotated 180."""
        turn_view = player is None or player == self.turn()
        color = self.color if turn_view else 1 - self.color
        opp = 1 - color

        n_my_blue = self.counts[piece_of(color, BLUE)]
        n_my_red = self.counts[piece_of(color, RED)]
        n_op_blue = self.counts[piece_of(opp, BLUE)]
        n_op_red = self.counts[piece_of(opp, RED)]

        scalar = np.array([
            1 if color == 0 else 0,
            1 if turn_view else 0,
            *[1 if n_my_blue == i else 0 for i in range(1, 5)],
            *[1 if n_my_red == i else 0 for i in range(1, 5)],
            *[1 if n_op_blue == i else 0 for i in range(1, 5)],
            *[1 if n_op_red == i else 0 for i in range(1, 5)],
        ], dtype=np.float32)

        my_blue = self.board == piece_of(color, BLUE)
        my_red = self.board == piece_of(color, RED)
        op_blue = self.board == piece_of(opp, BLUE)
        op_red = self.board == piece_of(opp, RED)
        hidden = player is not None
        zeros = np.zeros_like(self.board, dtype=bool)

        planes = np.stack([
            np.ones((6, 6)),
            my_blue + my_red,
            op_blue + op_red,
            my_blue,
            my_red,
            zeros if hidden else op_blue,
            zeros if hidden else op_red,
        ]).astype(np.float32)

        if color == 1:
            planes = np.rot90(planes, k=2, axes=(1, 2))
        return {'scalar': scalar, 'board': planes}

    def net(self):
        from ..models.geister import GeisterNet
        # env_args: {'norm_kind': 'batch'} surfaces the round-4 norm
        # investigation knob (BENCHMARKS.md Geister quality-gap section)
        # without a source edit
        return GeisterNet(norm_kind=self.args.get('norm_kind', 'group'),
                          policy_head=self.args.get('policy_head', 'dense'),
                          init_kind=self.args.get('init_kind', 'flax'))

    def __str__(self) -> str:
        def glyph(piece):
            if piece == -1:
                return GLYPHS[-1]
            if self.layouts.get(color_of(piece), 0) < 0:
                return GLYPHS[4]
            return GLYPHS[piece]

        lines = ['  ' + ' '.join(COLS)]
        for i in range(6):
            lines.append(ROWS[i] + ' '
                         + ' '.join(glyph(int(self.board[i, j])) for j in range(6)))
        lines.append('remained = B:%d R:%d b:%d r:%d' % tuple(self.counts))
        lines.append('ply = %s to-move = %s'
                     % (str(self.turn_count).ljust(3), 'BW'[self.color]))
        return '\n'.join(lines)


if __name__ == '__main__':
    e = Environment()
    for _ in range(10):
        e.reset()
        while not e.terminal():
            e.play(random.choice(e.legal_actions()))
        print(e)
        print(e.outcome())
