"""Pure-JAX vectorized Geister (device-resident twin of envs/geister.py).

N games advance as one program. The board is a flat (N, 36) piece-code array
(-1 empty, else color*2 + type with type 0=blue, 1=red); the setup phase is
part of the action space (ids 144..213 pick one of the 70 blue layouts) so
the policy drives it like any other move; move decode/encode uses
precomputed per-color lookup tables (actions are always encoded from the
mover's rotated perspective, matching the host env's codec).

Observation = the acting player's view: 18 scalars + 7 board planes with
opponent piece types hidden and the second player's board rotated 180
degrees — identical semantics to the host env's ``observation`` (the
imperfect-information surface).
"""

from __future__ import annotations

import itertools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

NUM_PLAYERS = 2
BOARD = 36
N_MOVE = 4 * BOARD          # 144
N_SET = 70
N_ACTIONS = N_MOVE + N_SET  # 214
MAX_PLIES = 200
SIMULTANEOUS = False
# the host env hides piece colors behind its own rng (secret setup); device
# records cannot replay through the host sampling contract byte-identically
RNG_COMPAT = 'device'

BLUE, RED = 0, 1

# ---- precomputed tables (numpy, at import) -------------------------------

_STEPS = np.array([(-1, 0), (0, -1), (0, 1), (1, 0)], np.int32)
_GOALS = np.array([[(-1, 5), (6, 5)], [(-1, 0), (6, 0)]], np.int32)
_LAYOUTS = np.array(list(itertools.combinations(range(8), 4)), np.int32)

# home squares as flat cells, layout-slot order (matches the host env)
def _sq(s):
    return 'ABCDEF'.find(s[0]) * 6 + '123456'.find(s[1])

_HOME = np.array([
    [_sq(s) for s in ['B2', 'C2', 'D2', 'E2', 'B1', 'C1', 'D1', 'E1']],
    [_sq(s) for s in ['E5', 'D5', 'C5', 'B5', 'E6', 'D6', 'C6', 'B6']],
], np.int32)

# layout -> per-slot piece type for each color: (70, 8)
_LAYOUT_TYPES = np.ones((N_SET, 8), np.int32)
for _i, _combo in enumerate(_LAYOUTS):
    _LAYOUT_TYPES[_i, _combo] = 0          # chosen slots are blue

# move decode per color: from-cell, to-cell (-1 = offboard), goal flag
_MOVE_FROM = np.zeros((2, N_MOVE), np.int32)
_MOVE_TO = np.full((2, N_MOVE), -1, np.int32)
_MOVE_GOAL = np.zeros((2, N_MOVE), bool)
for _c in range(2):
    for _a in range(N_MOVE):
        d, sq36 = _a // BOARD, _a % BOARD
        x, y = sq36 // 6, sq36 % 6
        if _c == 1:
            x, y = 5 - x, 5 - y
            d = 3 - d
        tx, ty = x + _STEPS[d][0], y + _STEPS[d][1]
        _MOVE_FROM[_c, _a] = x * 6 + y
        if 0 <= tx < 6 and 0 <= ty < 6:
            _MOVE_TO[_c, _a] = tx * 6 + ty
        else:
            _MOVE_GOAL[_c, _a] = any(
                tx == g[0] and ty == g[1] for g in _GOALS[_c])

MOVE_FROM = jnp.asarray(_MOVE_FROM)
MOVE_TO = jnp.asarray(_MOVE_TO)
MOVE_GOAL = jnp.asarray(_MOVE_GOAL)
HOME = jnp.asarray(_HOME)
LAYOUT_TYPES = jnp.asarray(_LAYOUT_TYPES)
ROT_PERM = jnp.asarray(np.arange(BOARD)[::-1].copy())


class State(NamedTuple):
    board: jnp.ndarray       # (N, 36) int8: -1 empty, else color*2+type
    color: jnp.ndarray       # (N,) int8 side to move
    plies: jnp.ndarray       # (N,) int32, starts at -2 (setup phase)
    win: jnp.ndarray         # (N,) int8: -1 none, 0/1 winner, 2 draw
    counts: jnp.ndarray      # (N, 4) int32 alive per piece code


def init_state(n: int, seed: int = 0) -> State:
    return State(
        board=jnp.full((n, BOARD), -1, jnp.int8),
        color=jnp.zeros((n,), jnp.int8),
        plies=jnp.full((n,), -2, jnp.int32),
        win=jnp.full((n,), -1, jnp.int8),
        counts=jnp.zeros((n, 4), jnp.int32),
    )


def turn(state: State) -> jnp.ndarray:
    return state.color.astype(jnp.int32)


def terminal(state: State) -> jnp.ndarray:
    return state.win >= 0


def outcome(state: State) -> jnp.ndarray:
    """(N, 2): +1/-1 for a win, 0 for draw/unfinished."""
    w = state.win
    first = jnp.where(w == 0, 1.0, jnp.where(w == 1, -1.0, 0.0))
    return jnp.stack([first, -first], axis=1)


def rewards(state: State) -> jnp.ndarray:
    """(N, 2) per-ply rewards: -0.01 to both players every ply (the host
    env's ply-cost shaping, envs/geister.py reward())."""
    n = state.board.shape[0]
    return jnp.full((n, NUM_PLAYERS), -0.01, jnp.float32)


def legal_mask(state: State) -> jnp.ndarray:
    """(N, 214) float 1 = legal for the side to move."""
    n = state.board.shape[0]
    setup = state.plies < 0

    c = state.color.astype(jnp.int32)
    piece = state.board.astype(jnp.int32)
    own = (piece >= 0) & (piece // 2 == c[:, None])            # (N, 36)
    own_from = jnp.take_along_axis(own, MOVE_FROM[c], axis=1)  # (N, 144)
    to = MOVE_TO[c]                                            # (N, 144)
    to_piece = jnp.take_along_axis(piece, jnp.maximum(to, 0), axis=1)
    to_own = (to_piece >= 0) & (to_piece // 2 == c[:, None])
    onboard_ok = (to >= 0) & ~to_own
    from_type = jnp.take_along_axis(piece, MOVE_FROM[c], axis=1) % 2
    goal_ok = (to < 0) & MOVE_GOAL[c] & (from_type == BLUE)
    move_legal = own_from & (onboard_ok | goal_ok)

    mask = jnp.concatenate([
        jnp.where(setup[:, None], False, move_legal),
        jnp.broadcast_to(setup[:, None], (n, N_SET)),
    ], axis=1)
    return mask.astype(jnp.float32)


def step(state: State, actions: jnp.ndarray) -> State:
    n = state.board.shape[0]
    c = state.color.astype(jnp.int32)
    piece_self_base = c * 2
    setup = state.plies < 0

    # ---- setup branch: place 8 pieces per the chosen layout --------------
    layout = jnp.clip(actions - N_MOVE, 0, N_SET - 1)
    types = LAYOUT_TYPES[layout]                              # (N, 8)
    home = HOME[c]                                            # (N, 8)
    set_board = state.board
    set_pieces = (piece_self_base[:, None] + types).astype(jnp.int8)
    set_board = set_board.at[jnp.arange(n)[:, None], home].set(
        jnp.where(setup[:, None], set_pieces,
                  jnp.take_along_axis(state.board, home, axis=1)))
    # a setup always places 4 blue + 4 red for the mover
    setup_add = (jax.nn.one_hot(piece_self_base, 4, dtype=jnp.int32)
                 + jax.nn.one_hot(piece_self_base + 1, 4, dtype=jnp.int32)) * 4
    set_counts = state.counts + jnp.where(setup[:, None], setup_add, 0)

    # ---- move branch -----------------------------------------------------
    a = jnp.clip(actions, 0, N_MOVE - 1)
    frm = MOVE_FROM[c, a]
    to = MOVE_TO[c, a]
    is_goal = MOVE_GOAL[c, a] & (to < 0)
    moving = jnp.take_along_axis(state.board, frm[:, None], axis=1)[:, 0]
    target = jnp.take_along_axis(
        state.board, jnp.maximum(to, 0)[:, None], axis=1)[:, 0]
    captures = (~setup) & (to >= 0) & (target >= 0)
    cap_code = jnp.clip(target, 0, 3).astype(jnp.int32)

    move_board = state.board
    move_board = move_board.at[jnp.arange(n), frm].set(
        jnp.where(setup, moving, -1).astype(jnp.int8))
    # place mover on destination (only when staying on board)
    dest = jnp.maximum(to, 0)
    new_dest = jnp.where((~setup) & (to >= 0), moving,
                         jnp.take_along_axis(move_board, dest[:, None],
                                             axis=1)[:, 0])
    move_board = move_board.at[jnp.arange(n), dest].set(
        new_dest.astype(jnp.int8))

    move_counts = set_counts - jnp.where(
        captures[:, None],
        jax.nn.one_hot(cap_code, 4, dtype=jnp.int32), 0)
    # a goal escape removes the escaping piece from the board counts
    escape = (~setup) & is_goal
    move_counts = move_counts - jnp.where(
        escape[:, None],
        jax.nn.one_hot(jnp.clip(moving, 0, 3), 4, dtype=jnp.int32), 0)

    board = jnp.where(setup[:, None], set_board, move_board)
    counts = jnp.where(setup[:, None], set_counts, move_counts)

    # ---- wins ------------------------------------------------------------
    opp = 1 - c
    cap_all_blue = captures & (jnp.take_along_axis(
        counts, (opp * 2 + BLUE)[:, None], axis=1)[:, 0] == 0) \
        & (cap_code % 2 == BLUE)
    cap_all_red = captures & (jnp.take_along_axis(
        counts, (opp * 2 + RED)[:, None], axis=1)[:, 0] == 0) \
        & (cap_code % 2 == RED)
    plies = state.plies + 1
    win = state.win
    win = jnp.where((~setup) & is_goal, c.astype(jnp.int8), win)
    win = jnp.where(cap_all_blue & (win < 0), c.astype(jnp.int8), win)
    win = jnp.where(cap_all_red & (win < 0), opp.astype(jnp.int8), win)
    win = jnp.where((plies >= MAX_PLIES) & (win < 0), jnp.int8(2), win)

    return State(board=board, color=(1 - state.color).astype(jnp.int8),
                 plies=plies, win=win, counts=counts)


def observe(state: State) -> jnp.ndarray:
    """Acting player's view as a dict-free stack: this device twin returns
    {'scalar': (N, 18), 'board': (N, 7, 6, 6)} to match GeisterNet's input."""
    return observe_as(state, state.color.astype(jnp.int32))


def observe_as(state: State, viewer: jnp.ndarray) -> jnp.ndarray:
    """View for an arbitrary (N,) viewer seat (host observation(player),
    geister.py:302-340): board from the viewer's perspective, opponent
    piece types hidden, turn flag set when the viewer is to move."""
    c = viewer.astype(jnp.int32)
    opp = 1 - c
    piece = state.board.astype(jnp.int32)
    turn_view = (state.color.astype(jnp.int32) == c)

    def cnt(code):
        return jnp.take_along_axis(state.counts, code[:, None], axis=1)[:, 0]

    n_my_b, n_my_r = cnt(c * 2 + BLUE), cnt(c * 2 + RED)
    n_op_b, n_op_r = cnt(opp * 2 + BLUE), cnt(opp * 2 + RED)

    def onehot4(v):
        return jax.nn.one_hot(jnp.clip(v - 1, 0, 3), 4, dtype=jnp.float32) \
            * (v > 0)[:, None]

    scalar = jnp.concatenate([
        (c == 0).astype(jnp.float32)[:, None],
        turn_view.astype(jnp.float32)[:, None],
        onehot4(n_my_b), onehot4(n_my_r), onehot4(n_op_b), onehot4(n_op_r),
    ], axis=1)

    my_b = (piece == (c * 2 + BLUE)[:, None]).astype(jnp.float32)
    my_r = (piece == (c * 2 + RED)[:, None]).astype(jnp.float32)
    op_any = ((piece >= 0) & (piece // 2 == opp[:, None])).astype(jnp.float32)
    zeros = jnp.zeros_like(my_b)
    planes = jnp.stack([
        jnp.ones_like(my_b), my_b + my_r, op_any, my_b, my_r, zeros, zeros,
    ], axis=1)                                          # (N, 7, 36)
    # rotate 180 for the second player
    rotated = planes[:, :, ROT_PERM]
    planes = jnp.where((c == 1)[:, None, None], rotated, planes)
    board_planes = planes.reshape(-1, 7, 6, 6)
    return {'scalar': scalar, 'board': board_planes}


def auto_reset(state: State, done: jnp.ndarray) -> State:
    fresh = init_state(state.board.shape[0])
    pick = lambda f, s: jnp.where(done.reshape((-1,) + (1,) * (s.ndim - 1)), f, s)
    return State(*(pick(f, s) for f, s in zip(fresh, state)))
