"""ConnectX: kaggle's Connect Four on the standard 6x7 board.

The kaggle competition wraps ``kaggle_environments.make("connectx")``;
that package is not available here, so this module implements the default
configuration natively (rows=6, columns=7, inarow=4) with the framework's
training surface:

  * turn-based perfect information — actions 0..6 drop a checker into a
    column, full columns are illegal; four in a row horizontally,
    vertically or diagonally wins; a full board with no line is a draw;
  * observations are 3 planes (6, 7) from the side-to-move's view
    ([is-my-turn-view, my checkers, opponent checkers]), the same codec
    TicTacToe uses, so the shared conv trunk applies unchanged;
  * ``rule_based_action`` is the classic one-ply heuristic the kaggle
    "negamax-lite" starter agents share: win now if a drop wins, block
    the opponent's immediate win otherwise, else prefer the center
    column (ties broken center-out, deterministically) — a real (if
    shallow) anchor for league rating matches;
  * the string codec is the column number, so network-battle mirrors
    reconstruct the board from one character per ply.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

import numpy as np

from ...environment import BaseEnvironment

ROWS, COLS = 6, 7
IN_A_ROW = 4
# center-out column preference of the heuristic agent (and a decent
# human-prior ordering for tie-breaks): 3, then 2/4, then 1/5, then 0/6
CENTER_ORDER = [3, 2, 4, 1, 5, 0, 6]
GLYPH = {0: '.', 1: 'O', -1: 'X'}


def _win_lines():
    """Every 4-cell line on the board as an (N, 4) array of flat indices."""
    lines = []
    for r in range(ROWS):
        for c in range(COLS):
            for dr, dc in ((0, 1), (1, 0), (1, 1), (1, -1)):
                rr, cc = r + 3 * dr, c + 3 * dc
                if 0 <= rr < ROWS and 0 <= cc < COLS:
                    lines.append([(r + i * dr) * COLS + (c + i * dc)
                                  for i in range(IN_A_ROW)])
    return np.array(lines, dtype=np.int64)


WIN_LINES = _win_lines()


class Environment(BaseEnvironment):
    FIRST, SECOND = 1, -1

    def __init__(self, args: Optional[dict] = None):
        super().__init__(args)
        self.args = args or {}
        self.rng = random.Random(self.args.get('id', 0))
        self.reset()

    def reset(self, args: Optional[dict] = None):
        # cells: flat length-42 vector, +1 first player / -1 second / 0 empty
        self.cells = np.zeros(ROWS * COLS, dtype=np.int8)
        self.side = self.FIRST
        self.winner = 0
        self.moves: List[int] = []

    # -- transitions ------------------------------------------------------
    def _drop_row(self, col: int) -> int:
        """Lowest empty row in ``col`` (-1 when the column is full)."""
        board = self.cells.reshape(ROWS, COLS)
        for r in range(ROWS - 1, -1, -1):
            if board[r, col] == 0:
                return r
        return -1

    def play(self, action: int, player: Optional[int] = None):
        row = self._drop_row(action)
        self.cells[row * COLS + action] = self.side
        line_sums = self.cells[WIN_LINES].sum(axis=1)
        if (line_sums == IN_A_ROW * self.side).any():
            self.winner = self.side
        self.side = -self.side
        self.moves.append(action)

    def turn(self) -> int:
        return len(self.moves) % 2

    def terminal(self) -> bool:
        return self.winner != 0 or len(self.moves) == ROWS * COLS

    def outcome(self) -> Dict[int, float]:
        score = float(self.winner)
        return {0: score, 1: -score}

    def legal_actions(self, player: Optional[int] = None) -> List[int]:
        board = self.cells.reshape(ROWS, COLS)
        return [c for c in range(COLS) if board[0, c] == 0]

    def players(self) -> List[int]:
        return [0, 1]

    # -- observation ------------------------------------------------------
    def observation(self, player: Optional[int] = None) -> np.ndarray:
        """Planes: [is-my-turn-view, my checkers, opponent checkers],
        shape (3, 6, 7) — TicTacToe's codec on the bigger board."""
        turn_view = player is None or player == self.turn()
        me = self.side if turn_view else -self.side
        board = self.cells.reshape(ROWS, COLS)
        return np.stack([
            np.full((ROWS, COLS), 1.0 if turn_view else 0.0),
            (board == me).astype(np.float32),
            (board == -me).astype(np.float32),
        ]).astype(np.float32)

    # -- rule-based opponent ----------------------------------------------
    def rule_based_action(self, player: int, key=None) -> int:
        """One-ply tactical heuristic: play the winning drop if one
        exists, else block the opponent's winning drop, else the first
        legal column center-out — deterministic, so rating matches
        against it are reproducible."""
        legal = self.legal_actions()

        def wins(col: int, side: int) -> bool:
            row = self._drop_row(col)
            idx = row * COLS + col
            self.cells[idx] = side
            won = bool((self.cells[WIN_LINES].sum(axis=1)
                        == IN_A_ROW * side).any())
            self.cells[idx] = 0
            return won

        for side in (self.side, -self.side):   # my win first, then block
            for col in legal:
                if wins(col, side):
                    return col
        for col in CENTER_ORDER:
            if col in legal:
                return col
        return legal[0]

    # -- string codec ------------------------------------------------------
    def action2str(self, a: int, player: Optional[int] = None) -> str:
        return str(a)

    def str2action(self, s: str, player: Optional[int] = None) -> int:
        return int(s)

    def diff_info(self, player: Optional[int] = None) -> str:
        return self.action2str(self.moves[-1]) if self.moves else ''

    def update(self, info: str, reset: bool):
        if reset:
            self.reset()
        else:
            self.play(self.str2action(info))

    def __str__(self) -> str:
        board = self.cells.reshape(ROWS, COLS)
        lines = [' '.join(str(c) for c in range(COLS))]
        for r in range(ROWS):
            lines.append(' '.join(GLYPH[int(v)] for v in board[r]))
        lines.append('record = ' + ' '.join(str(a) for a in self.moves))
        return '\n'.join(lines)

    # -- model hook --------------------------------------------------------
    def net(self):
        from ...models.connect_four import ConnectFourNet
        return ConnectFourNet()


if __name__ == '__main__':
    e = Environment()
    for _ in range(5):
        e.reset()
        while not e.terminal():
            e.play(random.choice(e.legal_actions()))
        print(e)
        print(e.outcome())
