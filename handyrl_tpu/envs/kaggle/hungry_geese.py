"""Hungry Geese: 4-player simultaneous survival game on a 7x11 torus.

The reference wraps ``kaggle_environments.make("hungry_geese")``
(hungry_geese.py:60-231); that package is not available here, so this module
implements the game natively with the same rules and the same training
surface:

  * geese move N/S/W/E each step on a wrapping 7x11 grid; reversing onto
    your own neck is death; eating food grows the goose; every 40 steps
    every goose loses a tail cell (starvation at length 0); colliding with
    any goose body, or head-to-head, is death; the game ends when at most
    one goose survives or after 200 steps;
  * per-goose score = survival steps dominating, then length (the kaggle
    reward formula's ordering), and the outcome is the pairwise-rank score
    in {-1, -1/3, +1/3, +1} exactly as the reference computes it
    (hungry_geese.py:168-180);
  * observations are the same 17x7x11 planes (heads, tails, bodies,
    previous heads — all rotated so the observing player is channel 0 — and
    food), built from the last two board states (hungry_geese.py:202-231);
  * ``rule_based_action`` is a behavioral port of kaggle's GreedyAgent —
    the same opponent the reference delegates to — so win rates "vs
    rulebase" are comparable to the reference's (see the decision rules in
    the method docstring and the agreement test in
    tests/test_greedy_agent.py).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

import numpy as np

from ...environment import BaseEnvironment

R, C = 7, 11
N_CELLS = R * C
ACTIONS = ['NORTH', 'SOUTH', 'WEST', 'EAST']
DELTAS = [(-1, 0), (1, 0), (0, -1), (0, 1)]
OPPOSITE = {0: 1, 1: 0, 2: 3, 3: 2}
# kaggle's Action enum iterates NORTH, EAST, SOUTH, WEST — the GreedyAgent's
# candidate scan (and thus its tie-breaking) follows that order
GREEDY_ACTION_ORDER = [0, 3, 1, 2]
HUNGER_RATE = 40
MAX_STEPS = 200
N_FOOD = 2
MAX_LEN_SCORE = N_CELLS + 1     # score base so survival dominates length


def _move(cell: int, action: int) -> int:
    x, y = divmod(cell, C)
    dx, dy = DELTAS[action]
    return ((x + dx) % R) * C + (y + dy) % C


class Environment(BaseEnvironment):
    NUM_AGENTS = 4

    def __init__(self, args: Optional[dict] = None):
        super().__init__(args)
        self.args = args or {}
        self.rng = random.Random(self.args.get('id', 0))
        self.reset()

    def reset(self, args: Optional[dict] = None):
        cells = self.rng.sample(range(N_CELLS), self.NUM_AGENTS + N_FOOD)
        self.geese: List[List[int]] = [[c] for c in cells[:self.NUM_AGENTS]]
        self.food: List[int] = cells[self.NUM_AGENTS:]
        self.alive: List[bool] = [True] * self.NUM_AGENTS
        self.scores: List[float] = [0.0] * self.NUM_AGENTS
        self.last_actions: Dict[int, int] = {}
        self.prev_geese: List[List[int]] = [list(g) for g in self.geese]
        self.step_count = 0
        self._update_scores()

    # -- helpers -----------------------------------------------------------
    def _update_scores(self):
        for p in range(self.NUM_AGENTS):
            if self.alive[p]:
                self.scores[p] = ((self.step_count + 1) * MAX_LEN_SCORE
                                  + len(self.geese[p]))

    def _spawn_food(self):
        occupied = set(self.food)
        for g in self.geese:
            occupied.update(g)
        free = [c for c in range(N_CELLS) if c not in occupied]
        while len(self.food) < N_FOOD and free:
            cell = self.rng.choice(free)
            free.remove(cell)
            self.food.append(cell)

    # -- transitions -------------------------------------------------------
    def step(self, actions: Dict[int, Optional[int]]):
        """Canonical kaggle resolution order (see the rules-source note in
        docs/geese_rules.md): per agent — reversal death (unconditional, even
        at length 1), move + eat-or-pop-tail, SELF-collision against the
        remaining own cells (old head still present, popped tail absent, new
        head not yet inserted), head insert, hunger pop + starvation death —
        then ONE simultaneous cross-goose pass: a histogram over every cell
        of every surviving goose kills any goose whose head cell counts > 1.
        Geese emptied in the per-agent phase (reversed / self-collided /
        starved) contribute nothing to the histogram, so their vacated cells
        are safe to enter the same step."""
        self.prev_geese = [list(g) for g in self.geese]
        self.step_count += 1
        acted: Dict[int, int] = {}
        hungry = self.step_count % HUNGER_RATE == 0

        # per-agent phase
        for p in range(self.NUM_AGENTS):
            if not self.alive[p]:
                continue
            action = actions.get(p)
            action = 0 if action is None else int(action)
            acted[p] = action
            goose = self.geese[p]
            if (p in self.last_actions
                    and action == OPPOSITE[self.last_actions[p]]):
                self.alive[p] = False      # reversal: dies at ANY length
                self.geese[p] = []
                continue
            head = _move(goose[0], action)
            if head in self.food:
                self.food.remove(head)     # grow: keep the tail
            else:
                goose.pop()
            if head in goose:              # self collision (pre-insert)
                self.alive[p] = False
                self.geese[p] = []
                continue
            goose.insert(0, head)
            if hungry:
                goose.pop()
                if not goose:
                    self.alive[p] = False  # starved

        # simultaneous cross-goose collisions
        count: Dict[int, int] = {}
        for p in range(self.NUM_AGENTS):
            for cell in self.geese[p]:
                count[cell] = count.get(cell, 0) + 1
        for p in range(self.NUM_AGENTS):
            if not self.alive[p] or not self.geese[p]:
                continue
            if count[self.geese[p][0]] > 1:
                self.alive[p] = False
                self.geese[p] = []

        for p, a in acted.items():
            self.last_actions[p] = a
        self._spawn_food()
        self._update_scores()

    # -- protocol ----------------------------------------------------------
    def turns(self) -> List[int]:
        return [p for p in self.players() if self.alive[p]]

    def terminal(self) -> bool:
        return sum(self.alive) <= 1 or self.step_count >= MAX_STEPS

    def outcome(self) -> Dict[int, float]:
        """Pairwise-rank score: +1/(N-1) per beaten opponent, -1/(N-1) per
        opponent that beat you."""
        outcomes = {p: 0.0 for p in self.players()}
        for p in self.players():
            for q in self.players():
                if p == q:
                    continue
                if self.scores[p] > self.scores[q]:
                    outcomes[p] += 1 / (self.NUM_AGENTS - 1)
                elif self.scores[p] < self.scores[q]:
                    outcomes[p] -= 1 / (self.NUM_AGENTS - 1)
        return outcomes

    def legal_actions(self, player: Optional[int] = None) -> List[int]:
        return list(range(len(ACTIONS)))

    def players(self) -> List[int]:
        return list(range(self.NUM_AGENTS))

    def action2str(self, a: int, player: Optional[int] = None) -> str:
        return ACTIONS[a]

    def str2action(self, s: str, player: Optional[int] = None) -> int:
        return ACTIONS.index(s)

    # -- delta sync --------------------------------------------------------
    def diff_info(self, player: Optional[int] = None):
        return {
            'geese': [list(g) for g in self.geese],
            'prev_geese': [list(g) for g in self.prev_geese],
            'food': list(self.food),
            'alive': list(self.alive),
            'scores': list(self.scores),
            'last_actions': dict(self.last_actions),
            'step': self.step_count,
        }

    def update(self, info, reset: bool):
        self.geese = [list(g) for g in info['geese']]
        self.prev_geese = [list(g) for g in info['prev_geese']]
        self.food = list(info['food'])
        self.alive = list(info['alive'])
        self.scores = list(info['scores'])
        self.last_actions = dict(info['last_actions'])
        self.step_count = info['step']

    # -- observation -------------------------------------------------------
    def observation(self, player: Optional[int] = None) -> np.ndarray:
        if player is None:
            player = 0
        b = np.zeros((self.NUM_AGENTS * 4 + 1, N_CELLS), dtype=np.float32)
        for p, goose in enumerate(self.geese):
            ch = (p - player) % self.NUM_AGENTS
            for cell in goose[:1]:
                b[0 + ch, cell] = 1
            for cell in goose[-1:]:
                b[4 + ch, cell] = 1
            for cell in goose:
                b[8 + ch, cell] = 1
        for p, goose in enumerate(self.prev_geese):
            ch = (p - player) % self.NUM_AGENTS
            for cell in goose[:1]:
                b[12 + ch, cell] = 1
        for cell in self.food:
            b[16, cell] = 1
        return b.reshape(-1, R, C)

    # -- rule-based opponent ----------------------------------------------
    def rule_based_action(self, player: int, key=None) -> int:
        """Behavioral port of kaggle_environments' GreedyAgent, which the
        reference delegates to (reference hungry_geese.py:189-197).

        Decision rules, in the kaggle agent's own terms: a candidate move
        may not land on a cell adjacent to any opponent head, on any
        non-tail goose cell (a tail vacates this turn and IS steppable), on
        the tail of an opponent whose head is adjacent to food (about to
        eat and keep that tail), and may not reverse the player's last
        action. Among candidates it picks the minimum
        *non-wrapped* Manhattan distance to the nearest food (the kaggle
        agent does not wrap its distance metric), ties broken in its
        Action-enum iteration order NORTH, EAST, SOUTH, WEST. If no
        candidate survives, it plays uniformly at random over all four
        actions (even a fatal one)."""
        goose = self.geese[player]
        if not goose:
            return 0
        head = goose[0]

        opponents = [g for p, g in enumerate(self.geese) if p != player and g]
        head_adjacent = {_move(g[0], a) for g in opponents for a in range(4)}
        # kaggle's bodies EXCLUDE tails (goose[0:-1] — a tail cell vacates
        # this turn), then add back the tails of opponents about to eat
        bodies = {cell for g in self.geese for cell in g[:-1]}
        eating_tails = {g[-1] for g in opponents
                        if any(_move(g[0], a) in self.food for a in range(4))}
        last = self.last_actions.get(player)
        banned = OPPOSITE[last] if last is not None else None

        def food_steps(cell: int) -> int:
            x, y = divmod(cell, C)
            return min((abs(x - fx) + abs(y - fy)
                        for f in self.food for fx, fy in [divmod(f, C)]),
                       default=0)

        best = None
        for a in GREEDY_ACTION_ORDER:
            to = _move(head, a)
            if (a == banned or to in head_adjacent or to in bodies
                    or to in eating_tails):
                continue
            d = food_steps(to)
            if best is None or d < best[0]:
                best = (d, a)
        if best is None:
            return self.rng.randrange(4)
        return best[1]

    def net(self):
        # env_args {'norm_kind': 'batch'} selects full BatchNorm in the
        # stem + all blocks (reference TorusConv2d's nn.BatchNorm2d,
        # hungry_geese.py:23-35,43-44) — the round-5 norm A/B knob
        from ...models.geese import GeeseNet, GeeseNetLSTM
        if self.args.get('net_kind', 'conv') == 'lstm':
            # the LSTM-era baseline configuration (BASELINE.md row 4):
            # torus-conv stem + ConvLSTM core carrying state across plies
            return GeeseNetLSTM(norm_kind=self.args.get('norm_kind', 'group'),
                                torus_impl=self.args.get('torus_impl', 'pad'))
        return GeeseNet(norm_kind=self.args.get('norm_kind', 'group'),
                        torus_impl=self.args.get('torus_impl', 'pad'))

    def __str__(self) -> str:
        grid = [['.'] * C for _ in range(R)]
        for cell in self.food:
            x, y = divmod(cell, C)
            grid[x][y] = 'f'
        for p, goose in enumerate(self.geese):
            for i, cell in enumerate(goose):
                x, y = divmod(cell, C)
                grid[x][y] = str(p) if i == 0 else 'abcd'[p]
        lines = ['step %d  alive %s' % (self.step_count, self.alive)]
        lines += [''.join(row) for row in grid]
        lines.append(' '.join(str(len(g) or '-') for g in self.geese))
        return '\n'.join(lines)


if __name__ == '__main__':
    e = Environment()
    for _ in range(10):
        e.reset()
        while not e.terminal():
            e.step({p: random.choice(e.legal_actions(p)) for p in e.turns()})
        print(e)
        print(e.outcome())
