"""Tic-Tac-Toe environment.

Feature parity with the reference game (`/root/reference/handyrl/envs/
tictactoe.py:72-168`): 2-player turn-based perfect-information play on a 3x3
board, actions 0..8, observation planes (3,3,3) from the side-to-move's view,
string moves like "A1", delta sync via last move. The implementation is
rewritten around precomputed winning lines instead of per-move row/col/diag
sums.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

import numpy as np

from ..environment import BaseEnvironment

# The eight winning triplets of board cells (rows, columns, diagonals).
WIN_LINES = np.array([
    [0, 1, 2], [3, 4, 5], [6, 7, 8],   # rows
    [0, 3, 6], [1, 4, 7], [2, 5, 8],   # columns
    [0, 4, 8], [2, 4, 6],              # diagonals
], dtype=np.int64)

COLS = 'ABC'
ROWS = '123'
GLYPH = {0: '_', 1: 'O', -1: 'X'}


class Environment(BaseEnvironment):
    BLACK, WHITE = 1, -1

    def __init__(self, args: Optional[dict] = None):
        super().__init__(args)
        self.reset()

    def reset(self, args: Optional[dict] = None):
        # cells: flat length-9 vector, +1 black / -1 white / 0 empty
        self.cells = np.zeros(9, dtype=np.int8)
        self.side = self.BLACK
        self.winner = 0
        self.moves: List[int] = []

    # -- transitions ------------------------------------------------------
    def play(self, action: int, player: Optional[int] = None):
        self.cells[action] = self.side
        line_sums = self.cells[WIN_LINES].sum(axis=1)
        if (line_sums == 3 * self.side).any():
            self.winner = self.side
        self.side = -self.side
        self.moves.append(action)

    def turn(self) -> int:
        return len(self.moves) % 2

    def terminal(self) -> bool:
        return self.winner != 0 or len(self.moves) == 9

    def outcome(self) -> Dict[int, float]:
        score = float(self.winner)
        return {0: score, 1: -score}

    def legal_actions(self, player: Optional[int] = None) -> List[int]:
        return np.flatnonzero(self.cells == 0).tolist()

    def players(self) -> List[int]:
        return [0, 1]

    def reward(self) -> Dict[int, float]:
        return {}

    # -- observation ------------------------------------------------------
    def observation(self, player: Optional[int] = None) -> np.ndarray:
        """Planes: [is-my-turn-view, my stones, opponent stones], (3, 3, 3)."""
        turn_view = player is None or player == self.turn()
        me = self.side if turn_view else -self.side
        board = self.cells.reshape(3, 3)
        return np.stack([
            np.full((3, 3), 1.0 if turn_view else 0.0),
            (board == me).astype(np.float32),
            (board == -me).astype(np.float32),
        ]).astype(np.float32)

    # -- string codec ------------------------------------------------------
    def action2str(self, a: int, player: Optional[int] = None) -> str:
        return COLS[a // 3] + ROWS[a % 3]

    def str2action(self, s: str, player: Optional[int] = None) -> int:
        return COLS.index(s[0]) * 3 + ROWS.index(s[1])

    def diff_info(self, player: Optional[int] = None) -> str:
        return self.action2str(self.moves[-1]) if self.moves else ''

    def update(self, info: str, reset: bool):
        if reset:
            self.reset()
        else:
            self.play(self.str2action(info))

    def __str__(self) -> str:
        board = self.cells.reshape(3, 3)
        lines = ['  ' + ' '.join(ROWS)]
        for i in range(3):
            lines.append(COLS[i] + ' ' + ' '.join(GLYPH[int(v)] for v in board[i]))
        lines.append('record = ' + ' '.join(self.action2str(a) for a in self.moves))
        return '\n'.join(lines)

    # -- model hook --------------------------------------------------------
    def net(self):
        from ..models.tictactoe import SimpleConv2dModel
        return SimpleConv2dModel()


if __name__ == '__main__':
    e = Environment()
    for _ in range(10):
        e.reset()
        while not e.terminal():
            e.play(random.choice(e.legal_actions()))
        print(e)
        print(e.outcome())
