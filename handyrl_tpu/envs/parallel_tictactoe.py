"""Simultaneous-move Tic-Tac-Toe variant.

Exercises the simultaneous-transition path (both players act each step, the
environment applies one of the submitted actions at random), mirroring the
reference variant (`/root/reference/handyrl/envs/parallel_tictactoe.py`).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

import numpy as np

from .tictactoe import Environment as TicTacToe, WIN_LINES, COLS, ROWS, GLYPH


class Environment(TicTacToe):

    def step(self, actions: Dict[int, Optional[int]]):
        player = random.choice(list(actions.keys()))
        self._apply(actions[player], player)

    def _apply(self, action: int, player: int):
        color = [self.BLACK, self.WHITE][player]
        self.cells[action] = color
        line_sums = self.cells[WIN_LINES].sum(axis=1)
        if (line_sums == 3 * color).any():
            self.winner = color
        self.moves.append((color, action))

    def turn(self):
        raise NotImplementedError()

    def turns(self) -> List[int]:
        return self.players()

    def terminal(self) -> bool:
        # a cell may be overwritten, so the game also ends when the board fills
        return self.winner != 0 or not (self.cells == 0).any()

    def diff_info(self, player: Optional[int] = None) -> str:
        if not self.moves:
            return ''
        color, action = self.moves[-1]
        return self.action2str(action) + ':' + GLYPH[color]

    def update(self, info: str, reset: bool):
        if reset:
            self.reset()
        else:
            move, glyph = info.split(':')
            self._apply(self.str2action(move), 'OX'.index(glyph))

    def observation(self, player: Optional[int] = None) -> np.ndarray:
        # simultaneous game: every player observes from their own color
        me = self.BLACK if (player is None or player == 0) else self.WHITE
        board = self.cells.reshape(3, 3)
        return np.stack([
            np.ones((3, 3)),
            (board == me).astype(np.float32),
            (board == -me).astype(np.float32),
        ]).astype(np.float32)

    def __str__(self) -> str:
        board = self.cells.reshape(3, 3)
        lines = ['  ' + ' '.join(ROWS)]
        for i in range(3):
            lines.append(COLS[i] + ' ' + ' '.join(GLYPH[int(v)] for v in board[i]))
        return '\n'.join(lines)


if __name__ == '__main__':
    e = Environment()
    for _ in range(10):
        e.reset()
        while not e.terminal():
            e.step({p: random.choice(e.legal_actions(p)) for p in e.turns()})
        print(e)
        print(e.outcome())
