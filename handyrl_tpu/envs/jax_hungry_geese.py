"""Pure-JAX vectorized Hungry Geese: the flagship env as jittable array
functions (device-resident twin of envs/kaggle/hungry_geese.py).

N games of 4 geese advance as one program. Bodies are fixed-size ordered
cell buffers (head at index 0) with explicit lengths; movement is a shift,
growth/starvation are length edits, collisions are scatter-counts on the
7x11 board, and food respawn is a categorical draw over empty cells — no
data-dependent shapes anywhere.

Simultaneous-move protocol for device_generation.DeviceGenerator:
``SIMULTANEOUS = True``, ``observe`` returns per-player planes
(N, P, 17, 7, 11), ``step`` consumes (N, P) actions, ``acting`` gives the
per-player act mask.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

R, C = 7, 11
N_CELLS = R * C
NUM_PLAYERS = 4
N_ACTIONS = 4
MAX_LEN = N_CELLS
HUNGER_RATE = 40
MAX_STEPS = 200
N_FOOD = 2
MAX_LEN_SCORE = N_CELLS + 1
SIMULTANEOUS = True
# food spawns draw from the env's own device rng; host replay cannot
# reproduce them, so device-actor records are record_version-stamped
RNG_COMPAT = 'device'

# NORTH, SOUTH, WEST, EAST — row/col deltas and the opposite-action table
DROW = jnp.array([-1, 1, 0, 0], jnp.int32)
DCOL = jnp.array([0, 0, -1, 1], jnp.int32)
OPPOSITE = jnp.array([1, 0, 3, 2], jnp.int32)


class State(NamedTuple):
    cells: jnp.ndarray       # (N, P, MAX_LEN) ordered cell ids, head first
    length: jnp.ndarray      # (N, P) int32; 0 = gone
    alive: jnp.ndarray       # (N, P) bool
    food: jnp.ndarray        # (N, N_FOOD) int32 cell ids
    last_action: jnp.ndarray  # (N, P) int32; -1 = none yet
    prev_heads: jnp.ndarray  # (N, P) int32; -1 = none
    steps: jnp.ndarray       # (N,) int32
    scores: jnp.ndarray      # (N, P) float32
    key: jnp.ndarray         # (N, 2) per-env PRNG keys (uint32)


def _move_cells(cells: jnp.ndarray, actions: jnp.ndarray) -> jnp.ndarray:
    r, c = cells // C, cells % C
    return ((r + DROW[actions]) % R) * C + (c + DCOL[actions]) % C


def _spawn(key, occupied_mask):
    """Sample one cell uniformly from unoccupied cells. occupied_mask (77,)."""
    logits = jnp.where(occupied_mask, -jnp.inf, 0.0)
    return jax.random.categorical(key, logits)


def init_state(n: int, seed: int = 0) -> State:
    keys = jax.random.split(jax.random.PRNGKey(seed), n)

    def init_one(key):
        picks = jax.random.choice(key, N_CELLS, (NUM_PLAYERS + N_FOOD,),
                                  replace=False)
        cells = jnp.full((NUM_PLAYERS, MAX_LEN), -1, jnp.int32)
        cells = cells.at[:, 0].set(picks[:NUM_PLAYERS].astype(jnp.int32))
        return cells, picks[NUM_PLAYERS:].astype(jnp.int32)

    cells, food = jax.vmap(init_one)(keys)
    n_arr = jnp.arange(n)
    del n_arr
    state = State(
        cells=cells,
        length=jnp.ones((n, NUM_PLAYERS), jnp.int32),
        alive=jnp.ones((n, NUM_PLAYERS), bool),
        food=food,
        last_action=jnp.full((n, NUM_PLAYERS), -1, jnp.int32),
        prev_heads=jnp.full((n, NUM_PLAYERS), -1, jnp.int32),
        steps=jnp.zeros((n,), jnp.int32),
        scores=jnp.zeros((n, NUM_PLAYERS), jnp.float32),
        key=jax.vmap(jax.random.fold_in)(keys, jnp.arange(n)),
    )
    return state._replace(scores=_scores(state))


def _scores(state: State) -> jnp.ndarray:
    live_score = ((state.steps[:, None] + 1) * MAX_LEN_SCORE
                  + state.length).astype(jnp.float32)
    return jnp.where(state.alive, live_score, state.scores)


def acting(state: State) -> jnp.ndarray:
    """(N, P) bool: which players submit actions this step."""
    return state.alive


def terminal(state: State) -> jnp.ndarray:
    return (state.alive.sum(axis=1) <= 1) | (state.steps >= MAX_STEPS)


def legal_mask(state: State) -> jnp.ndarray:
    """(N, P, A) — all actions submittable (reference parity)."""
    n = state.cells.shape[0]
    return jnp.ones((n, NUM_PLAYERS, N_ACTIONS), jnp.float32)


def outcome(state: State) -> jnp.ndarray:
    """Pairwise-rank score in {-1..1}, (N, P)."""
    s = state.scores
    beats = (s[:, :, None] > s[:, None, :]).sum(axis=2).astype(jnp.float32)
    loses = (s[:, :, None] < s[:, None, :]).sum(axis=2).astype(jnp.float32)
    return (beats - loses) / (NUM_PLAYERS - 1)


def _body_occupancy(cells, length, alive, include_heads):
    """Scatter-count occupied cells -> (N, 77) counts."""
    idx = jnp.arange(MAX_LEN)[None, None, :]
    start = 0 if include_heads else 1
    valid = (idx >= start) & (idx < length[..., None]) & alive[..., None]
    flat = jnp.where(valid, cells, N_CELLS)   # out-of-range bucket
    one_hot = jax.nn.one_hot(flat, N_CELLS + 1, dtype=jnp.float32)
    return one_hot.sum(axis=(1, 2))[:, :N_CELLS]


def step(state: State, actions: jnp.ndarray) -> State:
    """Apply (N, P) actions; dead players' actions are ignored.

    Canonical kaggle resolution order (docs/geese_rules.md), vectorized:
    reversal death (unconditional, even at length 1) -> move + eat ->
    SELF-collision against the remaining own cells (popped tail excluded,
    new head excluded) -> hunger pop / starvation -> ONE simultaneous
    cross-goose occupancy pass killing any head whose cell counts > 1.
    Geese emptied before the occupancy pass contribute nothing to it."""
    prev_heads = jnp.where(state.alive, state.cells[:, :, 0], -1)

    # 1. reversal deaths: canonical has NO length guard
    reversed_ = (state.last_action >= 0) & \
        (actions == OPPOSITE[jnp.clip(state.last_action, 0, 3)])
    alive = state.alive & ~reversed_

    # 2. move heads, eat
    heads = state.cells[:, :, 0]
    new_heads = _move_cells(heads, actions)
    ate = (new_heads[:, :, None] == state.food[:, None, :]).any(axis=2) & alive
    cells = jnp.concatenate([new_heads[:, :, None], state.cells[:, :, :-1]],
                            axis=2)
    length = state.length + ate.astype(jnp.int32)

    # 3. self-collision BEFORE hunger: new buffer indices 1..length-1 hold
    # exactly the canonical post-pop pre-insert goose (old head kept, old
    # tail dropped unless it ate)
    idx = jnp.arange(MAX_LEN)[None, None, :]
    own_valid = (idx >= 1) & (idx < length[..., None])
    self_hit = ((cells == new_heads[..., None]) & own_valid).any(axis=2) \
        & alive
    alive = alive & ~self_hit

    # 4. starvation every HUNGER_RATE steps
    steps = state.steps + 1
    starve = (steps % HUNGER_RATE == 0)
    length = length - (starve[:, None] & alive).astype(jnp.int32)
    alive = alive & (length > 0)

    # 5. simultaneous cross-goose pass: occupancy over every cell (heads
    # included) of every surviving goose; head cell count > 1 kills
    occ = _body_occupancy(cells, length, alive, include_heads=True)
    head_cell = cells[:, :, 0]
    collided = alive & \
        (jnp.take_along_axis(occ, head_cell, axis=1) > 1)
    alive = alive & ~collided

    length = jnp.where(alive, length, 0)

    # freeze scores of the newly dead at their pre-death value; update alive
    dead_now = state.alive & ~alive
    frozen = jnp.where(dead_now, state.scores, 0.0)
    live_score = ((steps[:, None] + 1) * MAX_LEN_SCORE + length).astype(jnp.float32)
    scores = jnp.where(alive, live_score,
                       jnp.where(dead_now, frozen, state.scores))

    # 5. food respawn for eaten slots (uniform over empty cells)
    occupied = _body_occupancy(cells, length, alive, include_heads=True) > 0
    # slot f was eaten if any goose that ate has its new head on that cell
    food_eaten = ((state.food[:, None, :] == new_heads[:, :, None])
                  & ate[:, :, None]).any(axis=1)            # (N, N_FOOD)

    def respawn_env(key, food, eaten, occ):
        def one(i, carry):
            key, food = carry
            key, sub = jax.random.split(key)
            occ_now = occ | jax.nn.one_hot(food, N_CELLS, dtype=bool).any(axis=0)
            new_cell = _spawn(sub, occ_now)
            food = food.at[i].set(jnp.where(eaten[i], new_cell, food[i]))
            return key, food
        key, food = jax.lax.fori_loop(0, N_FOOD, one, (key, food))
        return key, food

    key, food = jax.vmap(respawn_env)(state.key, state.food, food_eaten,
                                      occupied)

    last_action = jnp.where(state.alive, actions, state.last_action)

    return State(cells=cells, length=length, alive=alive, food=food,
                 last_action=last_action, prev_heads=prev_heads,
                 steps=steps, scores=scores, key=key)


GREEDY_ORDER = jnp.array([0, 3, 1, 2], jnp.int32)   # kaggle Action order


def greedy_action(state: State, key) -> jnp.ndarray:
    """Vectorized GreedyAgent (N, P): the kaggle rulebase opponent the
    reference delegates to, same decision rules as the host port
    (envs/kaggle/hungry_geese.py rule_based_action): candidates may not
    reverse, land on a cell adjacent to an opponent head, on any non-tail
    goose cell, or on the tail of an opponent about to eat; among
    candidates, minimum NON-wrapped Manhattan distance to the nearest
    food, ties in kaggle Action order NORTH, EAST, SOUTH, WEST; no
    candidate -> uniform random over all four actions."""
    N = state.cells.shape[0]
    heads = state.cells[:, :, 0]                             # (N, P)
    idx = jnp.arange(MAX_LEN)[None, None, :]

    # move targets for every (player, action)
    targets = _move_cells(heads[:, :, None],
                          jnp.arange(4)[None, None, :])      # (N, P, 4)

    # bodies of ALL geese excluding each goose's tail cell
    body_valid = (idx < (state.length - 1)[..., None]) & state.alive[..., None]
    body_flat = jnp.where(body_valid, state.cells, N_CELLS)
    bodies = jax.nn.one_hot(body_flat, N_CELLS + 1,
                            dtype=bool).any(axis=(1, 2))[:, :N_CELLS]  # (N,77)

    # per-source adjacency of each goose's head (only alive geese) — the
    # same four neighbor cells as the move targets above
    head_adj = jnp.where(state.alive[..., None], targets, N_CELLS)
    adj_src = jax.nn.one_hot(head_adj, N_CELLS + 1,
                             dtype=bool).any(axis=2)[..., :N_CELLS]  # (N,P,77)
    # viewer p bans cells adjacent to OPPONENT heads only
    others_adj = jnp.stack(
        [(adj_src[:, [q for q in range(NUM_PLAYERS) if q != p]]).any(axis=1)
         for p in range(NUM_PLAYERS)], axis=1)               # (N, P, 77)

    # tails of geese about to eat (head adjacent to food)
    food_mask = jax.nn.one_hot(state.food, N_CELLS,
                               dtype=bool).any(axis=1)       # (N, 77)
    eats_next = (adj_src & food_mask[:, None, :]).any(axis=2)  # (N, P)
    tail_ix = jnp.clip(state.length - 1, 0, MAX_LEN - 1)
    tails = jnp.take_along_axis(state.cells, tail_ix[..., None],
                                axis=2)[..., 0]              # (N, P)
    tails = jnp.where(state.alive & eats_next, tails, N_CELLS)
    tail_src = jax.nn.one_hot(tails, N_CELLS + 1,
                              dtype=bool)[..., :N_CELLS]     # (N, P, 77)
    others_eating_tails = jnp.stack(
        [(tail_src[:, [q for q in range(NUM_PLAYERS) if q != p]]).any(axis=1)
         for p in range(NUM_PLAYERS)], axis=1)               # (N, P, 77)

    banned_mask = others_adj | others_eating_tails | bodies[:, None, :]
    hit = jnp.take_along_axis(
        banned_mask.reshape(N * NUM_PLAYERS, N_CELLS),
        targets.reshape(N * NUM_PLAYERS, 4), axis=1
    ).reshape(N, NUM_PLAYERS, 4)
    reverse = (state.last_action[..., None] >= 0) & (
        jnp.arange(4)[None, None, :]
        == OPPOSITE[jnp.clip(state.last_action, 0, 3)][..., None])
    allowed = ~(hit | reverse)                               # (N, P, 4)

    # NON-wrapped Manhattan distance to nearest food from each target
    tr, tc = targets // C, targets % C                       # (N, P, 4)
    fr, fc = state.food // C, state.food % C                 # (N, F)
    dist = (jnp.abs(tr[..., None] - fr[:, None, None, :])
            + jnp.abs(tc[..., None] - fc[:, None, None, :])).min(axis=-1)

    # min dist among allowed, ties in GREEDY_ORDER; the rank term is < 1
    # so it never outweighs a distance difference
    rank = jnp.argsort(GREEDY_ORDER)                         # action -> rank
    score = jnp.where(allowed, dist.astype(jnp.float32)
                      + rank[None, None, :].astype(jnp.float32) / 8.0,
                      jnp.inf)
    best = jnp.argmin(score, axis=-1).astype(jnp.int32)      # (N, P)
    fallback = jax.random.randint(key, (N, NUM_PLAYERS), 0, 4, jnp.int32)
    return jnp.where(allowed.any(axis=-1), best, fallback)


def observe(state: State) -> jnp.ndarray:
    """Per-player observation planes (N, P, 17, 7, 11), channel layout and
    relative player rotation exactly as the host env (hungry_geese.py
    observation): heads, tails, bodies, previous heads, food."""
    n = state.cells.shape[0]
    idx = jnp.arange(MAX_LEN)[None, None, :]
    valid = (idx < state.length[..., None]) & state.alive[..., None]
    flat = jnp.where(valid, state.cells, N_CELLS)
    body_planes = jax.nn.one_hot(flat, N_CELLS + 1,
                                 dtype=jnp.float32).sum(axis=2)[..., :N_CELLS]
    body_planes = jnp.minimum(body_planes, 1.0)            # (N, P, 77)

    head = jnp.where(state.alive, state.cells[:, :, 0], N_CELLS)
    head_planes = jax.nn.one_hot(head, N_CELLS + 1,
                                 dtype=jnp.float32)[..., :N_CELLS]
    tail_idx = jnp.clip(state.length - 1, 0, MAX_LEN - 1)
    tail = jnp.take_along_axis(state.cells, tail_idx[..., None], axis=2)[..., 0]
    tail = jnp.where(state.alive, tail, N_CELLS)
    tail_planes = jax.nn.one_hot(tail, N_CELLS + 1,
                                 dtype=jnp.float32)[..., :N_CELLS]
    prev = jnp.where(state.prev_heads >= 0, state.prev_heads, N_CELLS)
    prev_planes = jax.nn.one_hot(prev, N_CELLS + 1,
                                 dtype=jnp.float32)[..., :N_CELLS]
    food_plane = jax.nn.one_hot(state.food, N_CELLS,
                                dtype=jnp.float32).sum(axis=1)  # (N, 77)

    # relative rotation: viewer p sees goose q in channel (q - p) % P
    def planes_for(viewer):
        order = (jnp.arange(NUM_PLAYERS) + viewer) % NUM_PLAYERS
        return jnp.concatenate([
            head_planes[:, order], tail_planes[:, order],
            body_planes[:, order], prev_planes[:, order],
            food_plane[:, None, :],
        ], axis=1)                                          # (N, 17, 77)

    obs = jnp.stack([planes_for(p) for p in range(NUM_PLAYERS)], axis=1)
    return obs.reshape(n, NUM_PLAYERS, 17, R, C)


def auto_reset(state: State, done: jnp.ndarray) -> State:
    n = state.cells.shape[0]
    keys = jax.vmap(lambda k: jax.random.split(k)[0])(state.key)

    def fresh_one(key):
        picks = jax.random.choice(key, N_CELLS, (NUM_PLAYERS + N_FOOD,),
                                  replace=False)
        cells = jnp.full((NUM_PLAYERS, MAX_LEN), -1, jnp.int32)
        cells = cells.at[:, 0].set(picks[:NUM_PLAYERS].astype(jnp.int32))
        return cells, picks[NUM_PLAYERS:].astype(jnp.int32)

    f_cells, f_food = jax.vmap(fresh_one)(keys)
    ones = jnp.ones((n, NUM_PLAYERS), jnp.int32)
    f_scores = (1 * MAX_LEN_SCORE + ones).astype(jnp.float32)

    def pick(fresh, cur):
        return jnp.where(done.reshape((-1,) + (1,) * (cur.ndim - 1)), fresh, cur)

    return State(
        cells=pick(f_cells, state.cells),
        length=pick(ones, state.length),
        alive=pick(jnp.ones((n, NUM_PLAYERS), bool), state.alive),
        food=pick(f_food, state.food),
        last_action=pick(jnp.full((n, NUM_PLAYERS), -1, jnp.int32),
                         state.last_action),
        prev_heads=pick(jnp.full((n, NUM_PLAYERS), -1, jnp.int32),
                        state.prev_heads),
        steps=pick(jnp.zeros((n,), jnp.int32), state.steps),
        scores=pick(f_scores, state.scores),
        key=keys,
    )
