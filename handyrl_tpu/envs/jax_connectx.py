"""Pure-JAX vectorized ConnectX: kaggle's Connect Four as jittable array
functions.

The host env (envs/kaggle/connectx.py) implements the default kaggle
configuration (rows=6, columns=7, inarow=4) in Python; this module is its
fully device-resident twin for the fused rollout engines
(device_generation.py): N boards advance as one program — the drop-to-
lowest-empty transition, win detection over the precomputed 4-cell lines,
the TicTacToe-style observation codec and auto-reset are all jnp ops.

State pytree (all leaves have leading env axis N):
  boards  (N, 42) int8   +1 first player / -1 second / 0 empty (row-major)
  side    (N,)    int8   side to move (+1/-1)
  winner  (N,)    int8   +1/-1 when decided, 0 otherwise
  moves   (N,)    int8   plies played (<= 42)

``greedy_action`` vectorizes the host ``rule_based_action`` heuristic
exactly (win now, else block, else center-out) so 'rulebase' league seats
run inside the compiled ply.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .kaggle.connectx import CENTER_ORDER, COLS, IN_A_ROW, ROWS, WIN_LINES

N_ACTIONS = COLS
MAX_STEPS = ROWS * COLS
NUM_PLAYERS = 2
# the env is deterministic given the action sequence, so device records can
# replay byte-identically through the host sampling contract
RNG_COMPAT = 'strict'

# CENTER_RANK[c] = preference rank of column c in the heuristic's
# center-out ordering (lower = preferred)
CENTER_RANK = np.empty(COLS, dtype=np.int32)
for _rank, _col in enumerate(CENTER_ORDER):
    CENTER_RANK[_col] = _rank


class State(NamedTuple):
    boards: jnp.ndarray
    side: jnp.ndarray
    winner: jnp.ndarray
    moves: jnp.ndarray


def init_state(n: int) -> State:
    return State(
        boards=jnp.zeros((n, ROWS * COLS), jnp.int8),
        side=jnp.ones((n,), jnp.int8),
        winner=jnp.zeros((n,), jnp.int8),
        moves=jnp.zeros((n,), jnp.int8),
    )


def legal_mask(state: State) -> jnp.ndarray:
    """(N, 7) float 1 = legal: the column's top cell is empty."""
    top = state.boards.reshape(-1, ROWS, COLS)[:, 0, :]
    return (top == 0).astype(jnp.float32)


def terminal(state: State) -> jnp.ndarray:
    return (state.winner != 0) | (state.moves >= MAX_STEPS)


def turn(state: State) -> jnp.ndarray:
    """Acting player index (0/1) per env."""
    return (state.moves % 2).astype(jnp.int32)


def observe(state: State) -> jnp.ndarray:
    """Side-to-move view planes (N, 3, 6, 7): [const 1, mine, theirs] —
    the host env's observation codec (connectx.py observation)."""
    board = state.boards.reshape(-1, ROWS, COLS)
    mine = (board == state.side[:, None, None]).astype(jnp.float32)
    theirs = (board == -state.side[:, None, None]).astype(jnp.float32)
    ones = jnp.ones_like(mine)
    return jnp.stack([ones, mine, theirs], axis=1)


def _drop_index(boards: jnp.ndarray, cols: jnp.ndarray):
    """Flat cell index of a drop into ``cols`` per env, plus validity.

    Returns (idx (N,), ok (N,)): ``ok`` is False for a full column (the
    index is then clamped into range; callers mask with legality)."""
    n = boards.shape[0]
    board = boards.reshape(n, ROWS, COLS)
    filled = (board[jnp.arange(n), :, cols] != 0).sum(axis=1)
    row = ROWS - 1 - filled
    idx = jnp.clip(row, 0, ROWS - 1) * COLS + cols
    return idx, row >= 0


def step(state: State, actions: jnp.ndarray) -> State:
    """Drop one checker per env (callers only feed legal actions; envs
    already terminal are replaced by auto-reset)."""
    n = state.boards.shape[0]
    idx, _ = _drop_index(state.boards, actions)
    boards = state.boards.at[jnp.arange(n), idx].set(state.side)
    line_sums = boards[:, WIN_LINES].sum(axis=2)
    won = (line_sums
           == IN_A_ROW * state.side[:, None].astype(jnp.int32)).any(axis=1)
    winner = jnp.where(won & (state.winner == 0), state.side, state.winner)
    return State(boards=boards, side=-state.side,
                 winner=winner.astype(jnp.int8),
                 moves=state.moves + 1)


def outcome(state: State) -> jnp.ndarray:
    """(N, 2) outcome per player seat (player 0 moves first)."""
    w = state.winner.astype(jnp.float32)
    return jnp.stack([w, -w], axis=1)


def auto_reset(state: State, done: jnp.ndarray) -> State:
    """Replace finished envs with fresh boards."""
    fresh = init_state(state.boards.shape[0])
    pick = lambda a, b: jnp.where(done.reshape((-1,) + (1,) * (a.ndim - 1)), a, b)
    return State(*(pick(f, s) for f, s in zip(fresh, state)))


def _drop_wins(boards: jnp.ndarray, side: jnp.ndarray, col: int):
    """Would dropping ``side``'s checker into static column ``col`` make
    four in a row? (N,) bool, False where the column is full."""
    n = boards.shape[0]
    idx, ok = _drop_index(boards, jnp.full((n,), col, jnp.int32))
    cand = boards.at[jnp.arange(n), idx].set(side)
    sums = cand[:, WIN_LINES].sum(axis=2)
    won = (sums == IN_A_ROW * side[:, None].astype(jnp.int32)).any(axis=1)
    return won & ok


def greedy_action(state: State, key=None) -> jnp.ndarray:
    """Vectorized host ``rule_based_action``: the winning drop if one
    exists (lowest column first, like the host's ascending legal scan),
    else the drop blocking the opponent's win, else the first legal column
    center-out. Deterministic — ``key`` is accepted for the device-eval
    rulebase protocol and ignored."""
    legal = legal_mask(state) > 0                                 # (N, 7)
    my_win = jnp.stack([_drop_wins(state.boards, state.side, c)
                        for c in range(COLS)], axis=1) & legal
    opp_win = jnp.stack([_drop_wins(state.boards, -state.side, c)
                         for c in range(COLS)], axis=1) & legal
    first = lambda m: jnp.argmax(m, axis=1).astype(jnp.int32)
    rank = jnp.where(legal, jnp.asarray(CENTER_RANK)[None, :], COLS + 1)
    center = jnp.argmin(rank, axis=1).astype(jnp.int32)
    pick = jnp.where(my_win.any(axis=1), first(my_win),
                     jnp.where(opp_win.any(axis=1), first(opp_win), center))
    return pick
