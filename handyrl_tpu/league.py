"""League training: population orchestration over the model registry.

The learner owns a :class:`LeaguePool` that turns the versioned
:class:`~handyrl_tpu.serving.registry.ModelRegistry` into an opponent
*population*.  Pool members are registry versions of the configured line
(named ``line@version``) plus built-in anchors (``random``, and
``rulebase``/``rulebase-*`` for environments that implement
``rule_based_action``).  PFSP-style opponent sampling weights registry
members by a configurable curve over the learner's empirical win rate
against each member:

* ``variance`` — weight ∝ p·(1−p): prefers opponents the learner is
  ~50/50 against (maximum learning signal), the PFSP default.
* ``hard``     — weight ∝ (1−p)^k: prefers opponents the learner loses
  to (``k`` = ``league.hard_exponent``).
* ``uniform``  — every member equally likely.

Draws are routed through the audited :func:`~handyrl_tpu.generation.sample_seed`
machinery keyed on ``(seed, sample_key)`` (episode-key namespace ``3``),
so opponent assignment is a pure function of the task: byte-identical
across ledger re-issues and independent of wall clock or process
identity (GL001-clean — no raw ``random`` in the record path).

A persistent :class:`RatingBook` maintains an Elo rating per member
(optionally a TrueSkill-lite ``sigma`` that shrinks with games and
scales the effective K-factor), updated from ``'g'`` episode outcomes
and from dedicated rating matches scheduled as a slice of ``'e'``
tasks.  The book is journaled atomically via
:func:`handyrl_tpu.utils.fs.atomic_write_bytes` so ratings survive
learner restart/preemption bit-identically, and it gates champion
promotion: the registry champion flips only when the candidate's
rating clears the incumbent member's by ``league.promote_margin`` with
at least ``league.min_games`` games since the last flip.
"""

import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .generation import sample_seed
from .utils.fs import atomic_write_bytes

# Episode-key namespace for league opponent draws (0 = server-stamped
# generation episodes, 1 = worker-local fallback, 2 = evaluator opponent
# draws — see generation.py / evaluation.py).
LEAGUE_SEED_NAMESPACE = 3

# RatingBook entry name for the learner (the live training model).
LEARNER = 'learner'

# Anchor members that need no checkpoint.  ``random`` plays uniformly
# over legal actions (ModelVault serves it as model_id 0 for 'g' tasks);
# ``rulebase`` anchors call the environment's rule_based_action and can
# therefore only be exercised through 'e' rating matches (worker-side
# agents), never as a 'g' seat.
RANDOM_ANCHOR = 'random'

PFSP_CURVES = ('variance', 'hard', 'uniform')

# Floor added to every PFSP weight so no member's sampling probability
# collapses to zero (a member at p=1.0 must stay reachable, both to
# detect regressions and to keep its rating current).
_WEIGHT_FLOOR = 0.01


def pfsp_weights(win_rates: Sequence[float], curve: str = 'variance',
                 hard_exponent: float = 2.0) -> np.ndarray:
    """Unnormalized PFSP sampling weights for a vector of win rates.

    ``win_rates[i]`` is the learner's empirical win probability against
    member ``i`` (0.5 for unplayed members).  Returns a strictly
    positive float64 vector of the same length."""
    p = np.clip(np.asarray(win_rates, dtype=np.float64), 0.0, 1.0)
    if curve == 'variance':
        w = p * (1.0 - p)
    elif curve == 'hard':
        w = (1.0 - p) ** float(hard_exponent)
    elif curve == 'uniform':
        w = np.ones_like(p)
    else:
        raise ValueError('unknown PFSP curve %r (expected one of %s)'
                         % (curve, ', '.join(PFSP_CURVES)))
    return w + _WEIGHT_FLOOR


def plan_slots(task_mids: Sequence[Sequence[int]], slots: int
               ) -> Tuple[Dict[int, int], List[bool]]:
    """Pack a block of tasks' model ids into a fixed device slot stack.

    ``task_mids[i]`` lists the model ids task ``i`` needs materialized on
    device (its slot-backed seats). Tasks are admitted greedily IN ORDER
    while their ids still fit into ``slots`` distinct entries; a task whose
    new ids would overflow the compiled stack is skipped (False) — it runs
    on the host fallback instead of forcing a retrace. Returns
    ``(assign, admitted)``: the mid -> slot map and the per-task verdicts.
    The slot count is a compile-time constant of the device actor program,
    so this plan is the ONLY degree of freedom per block."""
    assign: Dict[int, int] = {}
    admitted: List[bool] = []
    for mids in task_mids:
        new = sorted({int(m) for m in mids if int(m) >= 1} - set(assign))
        if len(assign) + len(new) > int(slots):
            admitted.append(False)
            continue
        for m in new:
            assign[m] = len(assign)
        admitted.append(True)
    return assign, admitted


def member_name(line: str, version: Any) -> str:
    return '%s@%s' % (line, version)


def split_member(name: str) -> Tuple[Optional[str], Optional[str]]:
    """``'line@version' -> (line, version)``; anchors return (None, None)."""
    if '@' not in name:
        return None, None
    line, _, version = name.rpartition('@')
    return line, version


class RatingBook:
    """Persistent Elo ratings for the learner and every pool member.

    Entries are ``{'rating', 'sigma', 'games', 'wins'}``; ``wins``
    accumulates fractional scores (draw = 0.5).  All updates are pure
    float arithmetic on the stored state, so a journal round-trip
    reproduces subsequent updates bit-identically."""

    def __init__(self, initial_rating: float = 1200.0,
                 k_factor: float = 32.0, track_sigma: bool = True,
                 initial_sigma: float = 200.0, min_sigma: float = 50.0):
        self.initial_rating = float(initial_rating)
        self.k_factor = float(k_factor)
        self.track_sigma = bool(track_sigma)
        self.initial_sigma = float(initial_sigma)
        self.min_sigma = float(min_sigma)
        self._entries: Dict[str, Dict[str, float]] = {}
        # Games credited to the learner since the last champion flip —
        # the denominator of the league.min_games promotion gate.
        self.games_since_promote = 0
        self.promotions = 0

    # -- entries ---------------------------------------------------------

    def entry(self, name: str) -> Dict[str, float]:
        e = self._entries.get(name)
        if e is None:
            e = {'rating': self.initial_rating,
                 'sigma': self.initial_sigma, 'games': 0, 'wins': 0.0}
            self._entries[name] = e
        return e

    def seed(self, name: str, rating: float) -> None:
        """Create ``name`` with a starting rating (fresh sigma, no games)."""
        self._entries[name] = {'rating': float(rating),
                               'sigma': self.initial_sigma,
                               'games': 0, 'wins': 0.0}

    def seed_provisional(self, name: str, rating: Optional[float] = None
                         ) -> Dict[str, float]:
        """Create (or return) a *provisional* member: an unrated outsider
        — a gateway player, a guest bot — seeded at the learner's current
        rating with full (high) sigma so its first games move it fast.
        Provisional members never feed the promotion gate (their games
        don't count toward ``min_games`` and they can never be a champion
        candidate — champions come from the registry manifest)."""
        e = self._entries.get(name)
        if e is not None:
            return e
        if rating is None:
            rating = self.rating(LEARNER)
        e = {'rating': float(rating), 'sigma': self.initial_sigma,
             'games': 0, 'wins': 0.0, 'provisional': True}
        self._entries[name] = e
        return e

    def is_provisional(self, name: str) -> bool:
        e = self._entries.get(name)
        return bool(e is not None and e.get('provisional'))

    def rating(self, name: str) -> float:
        e = self._entries.get(name)
        return self.initial_rating if e is None else float(e['rating'])

    def games(self, name: str) -> int:
        e = self._entries.get(name)
        return 0 if e is None else int(e['games'])

    def win_rate(self, name: str) -> float:
        """Learner's empirical win rate against ``name`` (0.5 unplayed)."""
        e = self._entries.get(name)
        if e is None or e['games'] <= 0:
            return 0.5
        return float(e['wins']) / float(e['games'])

    def names(self) -> List[str]:
        return sorted(self._entries)

    # -- updates ---------------------------------------------------------

    def _k(self, e: Dict[str, float]) -> float:
        if not self.track_sigma:
            return self.k_factor
        scale = max(float(e['sigma']) / self.initial_sigma, 0.25)
        return self.k_factor * scale

    def _shrink(self, e: Dict[str, float]) -> None:
        if self.track_sigma:
            e['sigma'] = max(self.min_sigma,
                             self.initial_sigma
                             / math.sqrt(1.0 + float(e['games']) / 8.0))

    def record(self, opponent: str, score: float) -> None:
        """Book one game: learner scored ``score`` ∈ [0, 1] vs ``opponent``.

        Standard Elo with per-side effective K (scaled by sigma when
        TrueSkill-lite tracking is on); the opponent entry moves by the
        mirrored delta, and per-opponent (games, wins) feed the PFSP
        win-rate curve."""
        s = min(max(float(score), 0.0), 1.0)
        learner = self.entry(LEARNER)
        member = self.entry(opponent)
        expected = 1.0 / (1.0 + 10.0 ** ((member['rating']
                                          - learner['rating']) / 400.0))
        learner['rating'] += self._k(learner) * (s - expected)
        member['rating'] += self._k(member) * ((1.0 - s) - (1.0 - expected))
        learner['games'] += 1
        learner['wins'] += s
        member['games'] += 1
        member['wins'] += s  # learner's score vs this member (PFSP input)
        self._shrink(learner)
        self._shrink(member)
        if not member.get('provisional'):
            # Games against outsiders calibrate their rating but say
            # nothing about the learner vs the league — they never feed
            # the min_games promotion gate.
            self.games_since_promote += 1

    def record_between(self, a: str, b: str, score_a: float) -> None:
        """Book one game between two named members, neither the learner —
        the gateway path (external player vs a served ``line@version``).

        Ratings move by standard Elo with per-side effective K; the
        promotion gate is untouched.  Per-member (games, wins) are
        *learner-relative* PFSP statistics, so only provisional entries
        accumulate them here (as their own score); a rated member's PFSP
        win-rate is never polluted by third-party matches."""
        s = min(max(float(score_a), 0.0), 1.0)
        ea, eb = self.entry(a), self.entry(b)
        expected = 1.0 / (1.0 + 10.0 ** ((eb['rating']
                                          - ea['rating']) / 400.0))
        ea['rating'] += self._k(ea) * (s - expected)
        eb['rating'] += self._k(eb) * ((1.0 - s) - (1.0 - expected))
        for e, own in ((ea, s), (eb, 1.0 - s)):
            if e.get('provisional'):
                e['games'] += 1
                e['wins'] += own
                self._shrink(e)

    def note_promotion(self) -> None:
        self.promotions += 1
        self.games_since_promote = 0

    # -- persistence -----------------------------------------------------

    def to_state(self) -> Dict[str, Any]:
        return {'entries': {k: dict(v) for k, v in self._entries.items()},
                'games_since_promote': self.games_since_promote,
                'promotions': self.promotions,
                'initial_rating': self.initial_rating}

    def from_state(self, state: Dict[str, Any]) -> None:
        self._entries = {k: dict(v)
                         for k, v in (state.get('entries') or {}).items()}
        self.games_since_promote = int(state.get('games_since_promote', 0))
        self.promotions = int(state.get('promotions', 0))

    def save(self, path: str) -> None:
        """Atomic journal write (temp + fsync + rename via utils.fs)."""
        payload = json.dumps(self.to_state(), sort_keys=True) + '\n'
        atomic_write_bytes(path, payload.encode('utf-8'))

    def load(self, path: str) -> bool:
        """Reload a journal written by :meth:`save`; False if absent."""
        try:
            with open(path, 'rb') as f:
                raw = f.read()
        except OSError:
            return False
        try:
            self.from_state(json.loads(raw.decode('utf-8')))
        except (ValueError, UnicodeDecodeError):
            return False
        return True


class LeaguePool:
    """The opponent population: registry members plus built-in anchors.

    Refreshed from the registry manifest at epoch boundaries; the
    member window keeps the champion, the rollback target, and the
    ``max_members`` newest versions of the line.  Sampling is
    deterministic per ``(seed, sample_key)`` (see module docstring)."""

    def __init__(self, league_args: Dict[str, Any], line: str):
        self.args = dict(league_args or {})
        self.line = line
        self.curve = self.args.get('curve', 'variance')
        self.hard_exponent = float(self.args.get('hard_exponent', 2.0))
        self.max_members = int(self.args.get('max_members', 8))
        self.anchors = list(self.args.get('anchors', [RANDOM_ANCHOR]))
        self.self_play_rate = float(self.args.get('self_play_rate', 0.5))
        # name -> absolute checkpoint path (registry members only)
        self._member_paths: Dict[str, str] = {}
        # name -> int version id usable as a 'g' task model_id
        self._member_ids: Dict[str, int] = {}
        self.champion: Optional[str] = None

    # -- membership ------------------------------------------------------

    def refresh(self, registry) -> None:
        """Rebuild the member window from the registry manifest."""
        entry = (registry.describe() or {}).get(self.line) or {}
        versions = entry.get('versions') or {}
        order = sorted(versions,
                       key=lambda v: int(versions[v].get('seq', 0)))
        keep = set(order[-self.max_members:])
        for special in (entry.get('champion'), entry.get('previous')):
            if special is not None:
                keep.add(special)
        paths, ids = {}, {}
        for vid in keep:
            meta = versions.get(vid)
            if meta is None:
                continue
            name = member_name(self.line, vid)
            paths[name] = meta['path']
            try:
                ids[name] = int(vid)
            except (TypeError, ValueError):
                pass  # non-numeric version: usable via 'e' specs only
        self._member_paths = paths
        self._member_ids = ids
        champ = entry.get('champion')
        self.champion = (member_name(self.line, champ)
                         if champ is not None else None)

    def members(self) -> List[str]:
        """Registry members, sorted (stable draw order)."""
        return sorted(self._member_paths)

    def roster(self) -> List[str]:
        """Members plus anchors — everything the RatingBook tracks."""
        return self.members() + list(self.anchors)

    def member_paths(self) -> Set[str]:
        """Checkpoint paths the GC must pin while membership lasts."""
        return set(self._member_paths.values())

    def member_model_id(self, name: str) -> Optional[int]:
        """The model_id a 'g' task carries for this member's seats:
        the registry version id for members, 0 (uniform-random model)
        for the ``random`` anchor, None for members a worker cannot
        realize as a model (rulebase anchors, non-numeric versions)."""
        if name == RANDOM_ANCHOR:
            return 0
        return self._member_ids.get(name)

    # -- sampling --------------------------------------------------------

    def gen_candidates(self) -> List[str]:
        """Members a 'g' episode can seat: anything with a model_id."""
        out = [m for m in self.members() if m in self._member_ids]
        if RANDOM_ANCHOR in self.anchors:
            out.append(RANDOM_ANCHOR)
        return out

    def sample_opponent(self, base_seed: int, sample_key: int,
                        ratings: RatingBook) -> Optional[str]:
        """PFSP draw for the 'g' task stamped ``sample_key``.

        Returns None for the self-play share (probability
        ``self_play_rate``) and when no candidate exists.  Both the
        self-play coin and the member draw consume the same audited
        seed sequence (namespace 3, draw indices 0 and 1), so the
        assignment is a pure function of ``(seed, sample_key)``."""
        candidates = self.gen_candidates()
        if not candidates:
            return None
        key = (LEAGUE_SEED_NAMESPACE, int(sample_key))
        coin = np.random.default_rng(
            sample_seed(base_seed, key, 0)).random()
        if coin < self.self_play_rate:
            return None
        weights = pfsp_weights([ratings.win_rate(m) for m in candidates],
                               self.curve, self.hard_exponent)
        probs = weights / weights.sum()
        u = np.random.default_rng(sample_seed(base_seed, key, 1)).random()
        idx = min(int(np.searchsorted(np.cumsum(probs), u, side='right')),
                  len(candidates) - 1)
        return candidates[idx]

    def rating_opponent(self, counter: int) -> Optional[str]:
        """Deterministic round-robin over the full roster for rating
        matches (the 'e' slice) — coverage, not exploration, so no RNG:
        every member and anchor gets rated at the same cadence."""
        roster = self.roster()
        if not roster:
            return None
        return roster[int(counter) % len(roster)]

    # -- promotion gate --------------------------------------------------

    def should_promote(self, ratings: RatingBook) -> bool:
        """True when the learner's rating clears the incumbent champion
        member's by ``promote_margin`` with ≥ ``min_games`` games booked
        since the last flip.  With no champion yet the registry's
        bootstrap auto-promotion handles the first version."""
        if self.champion is None:
            return False
        margin = float(self.args.get('promote_margin', 30.0))
        min_games = int(self.args.get('min_games', 20))
        if ratings.games_since_promote < min_games:
            return False
        return (ratings.rating(LEARNER)
                >= ratings.rating(self.champion) + margin)


def journal_path(root: str) -> str:
    """Default RatingBook journal location under the registry root."""
    return os.path.join(root, 'league_ratings.json')


def make_rating_book(league_args: Dict[str, Any]) -> RatingBook:
    lg = league_args or {}
    return RatingBook(
        initial_rating=float(lg.get('initial_rating', 1200.0)),
        k_factor=float(lg.get('k_factor', 32.0)),
        track_sigma=bool(lg.get('track_sigma', True)),
        initial_sigma=float(lg.get('initial_sigma', 200.0)),
        min_sigma=float(lg.get('min_sigma', 50.0)))
