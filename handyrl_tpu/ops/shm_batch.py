"""Shared-memory batch arenas: zero-copy batcher-process IPC.

The process-mode Batcher (train.py, ``batcher_processes: True``) originally
returned every finished ``(B, T, P, ...)`` batch over an ``mp.Pipe`` — a
full pickle + copy on the child side and another deserialize + copy on the
trainer side, per batch (~12 MB at the GeeseNet headline geometry). With
``batcher_shared_memory: True`` each child instead owns a small ring of
``multiprocessing.shared_memory`` arenas, builds batches IN PLACE with
``make_batch(..., out=arena_views)``, and sends only a tiny slot descriptor
over the pipe; the trainer maps the same pages once per slot and hands the
numpy views straight to ``jax.device_put``. The only copy left on the whole
host path is the H2D DMA itself.

Layout: one SharedMemory segment per slot, leaves packed at 64-byte-aligned
offsets in spec order. The spec (leaf paths, shapes, dtypes, offsets) is
derived from the first batch the child builds and shipped once inside the
first descriptor; geometry is fixed for a run, so every later descriptor is
just ``(slot,)``.

Flow control: a child marks a slot busy when it sends the descriptor and
reuses it only after the trainer's ``('free', slot)`` message comes back
(sent after the staged device transfer completes), so at most ``slots``
batches per child are ever in flight — backpressure, not corruption, when
the trainer falls behind.
"""

from __future__ import annotations

import atexit
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry

_ALIGN = 64   # leaf offsets cache-line aligned (also keeps dtypes aligned)


# ---------------------------------------------------------------------------
# spec: serializable description of a batch's memory layout


def _walk_leaves(prefix: Tuple, x, out: List[Tuple[Tuple, np.ndarray]]):
    if isinstance(x, dict):
        for k in x:
            _walk_leaves(prefix + (k,), x[k], out)
    elif isinstance(x, (list, tuple)):
        for i, v in enumerate(x):
            _walk_leaves(prefix + (i,), v, out)
    else:
        out.append((prefix, np.asarray(x)))


def batch_spec(batch: Dict[str, Any]) -> Dict[str, Any]:
    """Describe ``batch``'s leaves as msgpack-able metadata + total bytes."""
    leaves: List[Tuple[Tuple, np.ndarray]] = []
    _walk_leaves((), batch, leaves)
    entries = []
    offset = 0
    for path, arr in leaves:
        offset = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
        entries.append({'path': list(path), 'shape': list(arr.shape),
                        'dtype': arr.dtype.str, 'offset': offset})
        offset += arr.nbytes
    return {'entries': entries, 'nbytes': max(offset, 1)}


def _set_path(root: Dict[str, Any], path: List, value):
    """Insert ``value`` at ``path``, creating nested dicts/lists on the way.
    Integer components denote list indices (filled in ascending order)."""
    node = root
    for key, nxt in zip(path[:-1], path[1:]):
        container = [] if isinstance(nxt, int) else {}
        if isinstance(node, list):
            if key == len(node):
                node.append(container)
            node = node[key]
        else:
            node = node.setdefault(key, container)
    last = path[-1]
    if isinstance(node, list):
        assert last == len(node), (last, len(node))
        node.append(value)
    else:
        node[last] = value


def map_batch(buf, spec: Dict[str, Any]) -> Dict[str, Any]:
    """Rebuild the batch structure as numpy views over ``buf`` (zero-copy)."""
    root: Dict[str, Any] = {}
    for e in spec['entries']:
        arr = np.ndarray(tuple(e['shape']), dtype=np.dtype(e['dtype']),
                         buffer=buf, offset=e['offset'])
        _set_path(root, list(e['path']), arr)
    return root


# ---------------------------------------------------------------------------
# child side


class ArenaRing:
    """A batcher child's ring of shared-memory batch slots."""

    def __init__(self, spec: Dict[str, Any], slots: int = 4):
        self.spec = spec
        self.shms = [shared_memory.SharedMemory(create=True,
                                                size=spec['nbytes'])
                     for _ in range(slots)]
        self.views = [map_batch(shm.buf, spec) for shm in self.shms]
        self.free: List[int] = list(range(slots))
        self._slots = slots
        # arena occupancy (slots in flight toward the trainer): a gauge
        # pinned at the ring size means the trainer is the bottleneck
        self._m_in_use = telemetry.gauge('shm_slots_in_use')
        self._closed = False
        # the owning (child) process must unlink its segments on ANY exit —
        # a crashed learner tree must not strand /dev/shm segments until
        # reboot. atexit covers interpreter exits that bypass the builder
        # loop's finally; close() is idempotent so both firing is fine.
        atexit.register(self.close)

    @property
    def names(self) -> List[str]:
        return [shm.name for shm in self.shms]

    def acquire(self) -> Optional[int]:
        slot = self.free.pop(0) if self.free else None
        self._m_in_use.set(self._slots - len(self.free))
        return slot

    def release(self, slot: int):
        self.free.append(slot)
        self._m_in_use.set(self._slots - len(self.free))

    def close(self):
        if self._closed:
            return
        self._closed = True
        shms, self.shms, self.views, self.free = self.shms, [], [], []
        for shm in shms:
            try:
                shm.close()
            except Exception:
                # live numpy views may pin the mapping (BufferError); the
                # OS reclaims the mapping at process exit — what must not
                # leak is the /dev/shm NAME, which unlink below removes
                pass
            try:
                shm.unlink()
            except (FileNotFoundError, OSError):
                pass   # double-unlink (e.g. resource tracker won) is fine


def copy_into(views: Dict[str, Any], batch: Dict[str, Any]):
    """Leaf-wise copy of ``batch`` into mapped arena ``views`` (used once,
    for the first batch that had to be built before the spec existed)."""
    leaves: List[Tuple[Tuple, np.ndarray]] = []
    _walk_leaves((), batch, leaves)
    dst: List[Tuple[Tuple, np.ndarray]] = []
    _walk_leaves((), views, dst)
    for (ps, src), (pd, d) in zip(leaves, dst):
        assert ps == pd, (ps, pd)
        np.copyto(d, src)


# ---------------------------------------------------------------------------
# trainer side


class ArenaMap:
    """The trainer's lazily-attached view of every child's slot segments."""

    def __init__(self):
        self._segs: Dict[str, shared_memory.SharedMemory] = {}
        self._views: Dict[str, Dict[str, Any]] = {}

    def attach(self, name: str, spec: Dict[str, Any]) -> Dict[str, Any]:
        if name not in self._views:
            seg = shared_memory.SharedMemory(name=name)
            self._segs[name] = seg
            self._views[name] = map_batch(seg.buf, spec)
        return self._views[name]

    def close(self):
        self._views.clear()
        for seg in self._segs.values():
            try:
                seg.close()
            except OSError:
                pass
        self._segs.clear()


class SharedBatch:
    """A mapped batch plus the callback releasing its slot to the child.

    The consumer MUST call :meth:`release` (exactly once) after the data has
    been fully read (for the trainer: after the staged device transfer is
    ready) — the child blocks on slot exhaustion, it never overwrites a
    slot that has not been freed.

    ``trace_ids`` carries the sampled episode trace ids of the windows the
    child assembled into this slot (ridden over the descriptor when episode
    tracing is on), so the trainer's ``train_step`` trace event can link
    back to the episodes it consumed.
    """

    __slots__ = ('batch', '_release', 'trace_ids')

    def __init__(self, batch: Dict[str, Any], release_fn, trace_ids=None):
        self.batch = batch
        self._release = release_fn
        self.trace_ids = trace_ids

    def release(self):
        fn, self._release = self._release, None
        if fn is not None:
            fn()
