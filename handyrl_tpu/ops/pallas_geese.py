"""Fused GeeseNet trunk as one Pallas TPU kernel.

Why: the round-5 per-op HBM table (BENCHMARKS.md) shows the GeeseNet
update step is bound by per-conv materialization — wrap-pad copies and
im2col patch buffers written to HBM for every one of the 13 torus-conv
layers, forward and backward. The whole trunk is tiny (weights ~240 KB,
a 64-sample activation tile ~1 MB), so the entire 13-layer stack fits in
VMEM: one kernel reads an observation tile from HBM once, runs
stem + 12 residual blocks on-chip, and writes the final feature map
once. The backward kernel recomputes the tile forward in VMEM
(flash-attention-style rematerialization) and gets exact gradients by
calling ``jax.vjp`` on the SAME tile function inside the kernel — no
hand-derived chain rule to get wrong — accumulating weight grads across
the (sequential) TPU grid.

This is the capability peer of the reference GeeseNet trunk
(hungry_geese.py:23-50: TorusConv2d stem + 12 residual blocks); the
function is pinned against the Flax module stack by
tests/test_pallas_geese.py, and GeeseNet(torus_impl='pallas') routes
through it with the exact same parameter tree.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


# ---------------------------------------------------------------- tile math

def _torus_conv(h, w, out_dtype):
    """3x3 torus conv on a VMEM-resident tile. h (B,7,11,C), w (3,3,C,F).

    Wrap-pad via concatenate (VMEM copies, never HBM), then 9 tap
    matmuls accumulated in fp32 — the MXU path Mosaic lowers dot_general
    to; fp32 accumulation matches XLA's conv behavior for bf16 inputs.
    Dots are kept strictly 2-D ((B*7*11, C) x (C, F)): Mosaic rejects
    multi-non-contracting-dim dot_generals, and merging/splitting LEADING
    dims is a free row-major relayout (splitting the lane dim is not)."""
    B = h.shape[0]
    F = w.shape[-1]
    hp = jnp.concatenate([h[:, -1:], h, h[:, :1]], axis=1)
    hp = jnp.concatenate([hp[:, :, -1:], hp, hp[:, :, :1]], axis=2)
    acc = None
    for a in range(3):
        for b in range(3):
            patch = hp[:, a:a + 7, b:b + 11].reshape(B * 77, -1)
            t = jax.lax.dot_general(
                patch, w[a, b], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc = t if acc is None else acc + t
    return acc.reshape(B, 7, 11, F).astype(out_dtype)


def _group_norm(h, scale, bias, groups, eps=1e-6):
    """flax nn.GroupNorm semantics: per-sample stats over spatial dims and
    the channels of each group, fp32 statistics.

    Group reductions go through a one-hot (C, G) matmul instead of the
    textbook reshape to (..., G, C/G): splitting the channel (lane) dim
    is an unsupported shape cast in Mosaic, while matmuls and leading-dim
    reductions lower fine. E[x^2]-E[x]^2 replaces the two-pass variance;
    fp32 accumulation keeps it stable at GroupNorm's O(1) activations."""
    B, H, W, C = h.shape
    cpg = C // groups
    row = jax.lax.broadcasted_iota(jnp.int32, (C, groups), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (C, groups), 1)
    M = (row // cpg == col).astype(jnp.float32)          # (C, G)
    hf = h.astype(jnp.float32).reshape(B, H * W, C)
    n = float(H * W * cpg)
    s1 = jax.lax.dot_general(hf.reshape(-1, C), M, (((1,), (0,)), ((), ())))
    s2 = jax.lax.dot_general((hf * hf).reshape(-1, C), M,
                             (((1,), (0,)), ((), ())))
    s1 = s1.reshape(B, H * W, groups).sum(axis=1)        # (B, G)
    s2 = s2.reshape(B, H * W, groups).sum(axis=1)
    mean_g = s1 / n
    rstd_g = jax.lax.rsqrt(jnp.maximum(s2 / n - mean_g ** 2, 0.0) + eps)
    # broadcast per-group stats back to channels via (G, C) matmul
    mean_c = jax.lax.dot_general(mean_g, M.T, (((1,), (0,)), ((), ())))
    rstd_c = jax.lax.dot_general(rstd_g, M.T, (((1,), (0,)), ((), ())))
    hn = (hf - mean_c[:, None, :]) * rstd_c[:, None, :]
    return (hn.reshape(h.shape) * scale + bias).astype(h.dtype)


def tile_forward(x, stem_w, stem_scale, stem_bias,
                 block_w, block_scale, block_bias, *, groups, dtype):
    """The trunk on one batch tile, all operands VMEM-resident.

    x (B,7,11,Cin); stem_w (3,3,Cin,F); block_w (L,3,3,F,F);
    scales/biases (F,) and (L,F). Mirrors GeeseNet exactly:
    relu(norm(conv(x))) stem, then L x relu(h + norm(conv(h)))."""
    x = x.astype(dtype)
    h = _torus_conv(x, stem_w.astype(dtype), dtype)
    h = jax.nn.relu(_group_norm(h, stem_scale, stem_bias, groups))
    for i in range(block_w.shape[0]):
        c = _torus_conv(h, block_w[i].astype(dtype), dtype)
        c = _group_norm(c, block_scale[i], block_bias[i], groups)
        h = jax.nn.relu(h + c)
    return h


# ---------------------------------------------------------------- kernels

def _fwd_kernel(x_ref, sw_ref, ss_ref, sb_ref, bw_ref, bs_ref, bb_ref,
                out_ref, *, groups, dtype):
    out_ref[...] = tile_forward(
        x_ref[...], sw_ref[...], ss_ref[...], sb_ref[...],
        bw_ref[...], bs_ref[...], bb_ref[...], groups=groups, dtype=dtype)


def _bwd_kernel(x_ref, sw_ref, ss_ref, sb_ref, bw_ref, bs_ref, bb_ref,
                dy_ref, dx_ref, dsw_ref, dss_ref, dsb_ref, dbw_ref,
                dbs_ref, dbb_ref, *, groups, dtype):
    """Recompute the tile forward and transpose it with jax.vjp, entirely
    in VMEM. Weight grads accumulate across the sequential TPU grid."""
    fn = functools.partial(tile_forward, groups=groups, dtype=dtype)
    _, vjp = jax.vjp(fn, x_ref[...], sw_ref[...], ss_ref[...], sb_ref[...],
                     bw_ref[...], bs_ref[...], bb_ref[...])
    dx, dsw, dss, dsb, dbw, dbs, dbb = vjp(dy_ref[...].astype(dtype))
    dx_ref[...] = dx.astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _zero():
        for r in (dsw_ref, dss_ref, dsb_ref, dbw_ref, dbs_ref, dbb_ref):
            r[...] = jnp.zeros_like(r)

    for r, g in ((dsw_ref, dsw), (dss_ref, dss), (dsb_ref, dsb),
                 (dbw_ref, dbw), (dbs_ref, dbs), (dbb_ref, dbb)):
        r[...] += g.astype(r.dtype)


# ------------------------------------------------------------- public entry

def _specs(weight_arrays, tile, x_shape):
    """BlockSpecs: batch-tiled x (block-index convention: grid step i
    reads block i along the batch dim), whole-array weights (block 0
    along every dim — identical under either index-map convention)."""
    xs = pl.BlockSpec((tile,) + x_shape[1:], lambda i: (i, 0, 0, 0))
    ws = [pl.BlockSpec(a.shape, (lambda nd: (lambda i: (0,) * nd))(a.ndim))
          for a in weight_arrays]
    return xs, ws


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9))
def trunk_apply(x, stem_w, stem_scale, stem_bias, block_w, block_scale,
                block_bias, groups=8, tile=64, interpret=False):
    """Fused trunk: (N,7,11,Cin) -> (N,7,11,F). N must divide by tile."""
    return _trunk_fwd(x, stem_w, stem_scale, stem_bias, block_w,
                      block_scale, block_bias, groups, tile, interpret)[0]


def _trunk_fwd(x, stem_w, stem_scale, stem_bias, block_w, block_scale,
               block_bias, groups, tile, interpret):
    N = x.shape[0]
    assert N % tile == 0, (N, tile)
    dtype = x.dtype
    F = stem_w.shape[-1]
    weights = (stem_w, stem_scale, stem_bias, block_w, block_scale,
               block_bias)
    xs, ws = _specs(weights, tile, x.shape)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, groups=groups, dtype=dtype),
        grid=(N // tile,),
        in_specs=[xs] + ws,
        out_specs=pl.BlockSpec((tile, 7, 11, F), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((N, 7, 11, F), dtype),
        interpret=interpret,
    )(x, stem_w, stem_scale, stem_bias, block_w, block_scale, block_bias)
    return y, (x, stem_w, stem_scale, stem_bias, block_w, block_scale,
               block_bias)


def _trunk_bwd(groups, tile, interpret, res, dy):
    x, stem_w, stem_scale, stem_bias, block_w, block_scale, block_bias = res
    N = x.shape[0]
    dtype = x.dtype
    # The bwd kernel's VMEM live set is ~L x the fwd's: jax.vjp saves a
    # residual activation per conv/norm/relu for every layer. A (64, 7,
    # 11, 32) bf16 tile pads to (64, 7, 16, 128) on TPU (~1.8 MB), so 13
    # layers of residuals at the fwd tile would blow the ~16 MB VMEM.
    # Run bwd at the LARGEST divisor of N that is <= 8 (1 always divides,
    # so every N degrades gracefully instead of silently keeping the full
    # forward tile and blowing the VMEM budget at compile time); grid
    # steps are sequential, so this only trades dispatch count, not
    # correctness (parity tests cover both).
    tile = max(d for d in range(1, min(tile, 8) + 1) if N % d == 0)
    F = stem_w.shape[-1]
    weights = (stem_w, stem_scale, stem_bias, block_w, block_scale,
               block_bias)
    xs, ws = _specs(weights, tile, x.shape)
    dy_spec = pl.BlockSpec((tile, 7, 11, F), lambda i: (i, 0, 0, 0))
    # weight-grad outputs are revisited on every grid step (sequential on
    # TPU), so the kernel zero-initializes at step 0 and accumulates
    grad_specs = [pl.BlockSpec(a.shape,
                               (lambda nd: (lambda i: (0,) * nd))(a.ndim))
                  for a in weights]
    grad_shapes = [jax.ShapeDtypeStruct(a.shape, jnp.float32)
                   for a in weights]
    out = pl.pallas_call(
        functools.partial(_bwd_kernel, groups=groups, dtype=dtype),
        grid=(N // tile,),
        in_specs=[xs] + ws + [dy_spec],
        out_specs=[xs] + grad_specs,
        out_shape=[jax.ShapeDtypeStruct(x.shape, dtype)] + grad_shapes,
        interpret=interpret,
    )(x, stem_w, stem_scale, stem_bias, block_w, block_scale, block_bias, dy)
    dx = out[0]
    dws = [g.astype(a.dtype) for g, a in zip(
        out[1:], (stem_w, stem_scale, stem_bias, block_w, block_scale,
                  block_bias))]
    return (dx,) + tuple(dws)


trunk_apply.defvjp(_trunk_fwd, _trunk_bwd)


# --------------------------------------------------- flax param extraction

def trunk_params_from_geesenet(params, layers=12) -> Tuple[jnp.ndarray, ...]:
    """Stack the GeeseNet trunk's Flax params (TorusConv_i/{Conv_0,
    GroupNorm_0}) into the kernel's operand arrays. The param TREE is
    owned by the Flax modules — this is a read-only view, so checkpoints
    and optimizer state are impl-agnostic."""
    p = params['params'] if 'params' in params else params
    stem = p['TorusConv_0']
    stem_w = stem['Conv_0']['kernel']
    stem_scale = stem['GroupNorm_0']['scale']
    stem_bias = stem['GroupNorm_0']['bias']
    bw, bs, bb = [], [], []
    for i in range(1, layers + 1):
        blk = p['TorusConv_%d' % i]
        bw.append(blk['Conv_0']['kernel'])
        bs.append(blk['GroupNorm_0']['scale'])
        bb.append(blk['GroupNorm_0']['bias'])
    return (stem_w, stem_scale, stem_bias,
            jnp.stack(bw), jnp.stack(bs), jnp.stack(bb))
