"""Value/advantage target algorithms: MC, TD(lambda), UPGO, V-Trace.

Numerical parity targets: the backward recursions of the reference
(`/root/reference/handyrl/losses.py:16-78`), re-expressed as ``lax.scan`` over
reversed time so the whole pipeline stays inside one XLA program (no Python
loops over T).

Conventions:
  * arrays are batch-first ``(B, T, ...)`` exactly as the batch builder emits
    them; internally time is moved to the leading axis for the scan.
  * ``masks`` marks *valid* steps; invalid steps collapse to ``lambda = 1``
    via ``lambda_t = lmb + (1 - lmb) * (1 - mask_t)`` (losses.py:71) so they
    pass the bootstrap straight through.
  * ``rewards`` may be None (the outcome-value head trains with no
    intermediate rewards and gamma = 1).

V-Trace follows Espeholt et al. 2018 (arXiv:1802.01561) with importance
ratios rho/c clipped upstream by the loss pipeline.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

ALGORITHMS = ('MC', 'TD', 'UPGO', 'VTRACE')


def _tm(x: Array) -> Array:
    """Batch-first -> time-major."""
    return jnp.moveaxis(x, 1, 0)


def _bf(x: Array) -> Array:
    """Time-major -> batch-first."""
    return jnp.moveaxis(x, 0, 1)


def _zeros_like_rewards(rewards: Optional[Array], template: Array) -> Array:
    return jnp.zeros_like(template) if rewards is None else rewards


def monte_carlo(values: Array, returns: Array) -> Tuple[Array, Array]:
    return returns, returns - values


def td_lambda(values: Array, returns: Array, rewards: Optional[Array],
              lambda_: Array, gamma: float) -> Tuple[Array, Array]:
    """TD(lambda) targets: tv_t = r_t + g*((1-l_{t+1})*V_{t+1} + l_{t+1}*tv_{t+1}),
    boot-strapped from returns at the final step."""
    v, ret, lam = _tm(values), _tm(returns), _tm(lambda_)
    rew = _tm(_zeros_like_rewards(rewards, values))

    def step(carry, x):
        v_next, lam_next, r = x
        tv = r + gamma * ((1 - lam_next) * v_next + lam_next * carry)
        return tv, tv

    init = ret[-1]
    _, tvs = lax.scan(step, init, (v[1:], lam[1:], rew[:-1]), reverse=True)
    tvs = jnp.concatenate([tvs, ret[-1:]], axis=0)
    return _bf(tvs), _bf(tvs - v)


def upgo(values: Array, returns: Array, rewards: Optional[Array],
         lambda_: Array, gamma: float) -> Tuple[Array, Array]:
    """UPGO: bootstrap with max(V_{t+1}, mixed target) so targets never dip
    below the one-step value estimate."""
    v, ret, lam = _tm(values), _tm(returns), _tm(lambda_)
    rew = _tm(_zeros_like_rewards(rewards, values))

    def step(carry, x):
        v_next, lam_next, r = x
        tv = r + gamma * jnp.maximum(v_next, (1 - lam_next) * v_next + lam_next * carry)
        return tv, tv

    init = ret[-1]
    _, tvs = lax.scan(step, init, (v[1:], lam[1:], rew[:-1]), reverse=True)
    tvs = jnp.concatenate([tvs, ret[-1:]], axis=0)
    return _bf(tvs), _bf(tvs - v)


def vtrace(values: Array, returns: Array, rewards: Optional[Array],
           lambda_: Array, gamma: float, rhos: Array, cs: Array
           ) -> Tuple[Array, Array]:
    """V-Trace: vs_t = V_t + sum of c-weighted rho-corrected TD errors;
    advantage evaluated against vs_{t+1}."""
    v, ret, lam = _tm(values), _tm(returns), _tm(lambda_)
    rew = _tm(_zeros_like_rewards(rewards, values))
    rho, c = _tm(rhos), _tm(cs)

    v_next = jnp.concatenate([v[1:], ret[-1:]], axis=0)
    deltas = rho * (rew + gamma * v_next - v)

    def step(carry, x):
        delta, lam_c = x
        out = delta + gamma * lam_c * carry
        return out, out

    init = deltas[-1]
    _, vmv = lax.scan(step, init, (deltas[:-1], lam[1:] * c[:-1]), reverse=True)
    vmv = jnp.concatenate([vmv, deltas[-1:]], axis=0)

    vs = vmv + v
    vs_next = jnp.concatenate([vs[1:], ret[-1:]], axis=0)
    advantages = rew + gamma * vs_next - v
    return _bf(vs), _bf(advantages)


def compute_target(algorithm: str, values: Optional[Array], returns: Array,
                   rewards: Optional[Array], lmb: float, gamma: float,
                   rhos: Array, cs: Array, masks: Array,
                   use_pallas: Optional[bool] = None) -> Tuple[Array, Array]:
    """Dispatch on algorithm name; mirrors losses.py:63-78 including the
    no-baseline Monte-Carlo fallback and the lambda-mask collapse.

    The backward recursion runs as lax.scan by default on every backend
    (measured faster than the Pallas kernels inside the full update step —
    ops/pallas_targets.py module docstring); HANDYRL_PALLAS_TARGETS=1 plus
    a passing on-device probe switches TPU backends to the fused kernels."""
    if values is None:
        return returns, returns
    if algorithm == 'MC':
        return monte_carlo(values, returns)

    lambda_ = lmb + (1 - lmb) * (1 - masks)

    if use_pallas is None:
        from .pallas_targets import use_pallas_targets
        use_pallas = use_pallas_targets()

    if use_pallas:
        from . import pallas_targets as pt
        if algorithm == 'TD':
            return pt.td_lambda_pallas(values, returns, rewards, lambda_, gamma)
        if algorithm == 'UPGO':
            return pt.upgo_pallas(values, returns, rewards, lambda_, gamma)
        if algorithm == 'VTRACE':
            return pt.vtrace_pallas(values, returns, rewards, lambda_, gamma,
                                    rhos, cs)

    if algorithm == 'TD':
        return td_lambda(values, returns, rewards, lambda_, gamma)
    if algorithm == 'UPGO':
        return upgo(values, returns, rewards, lambda_, gamma)
    if algorithm == 'VTRACE':
        return vtrace(values, returns, rewards, lambda_, gamma, rhos, cs)
    raise ValueError('unknown target algorithm: %s' % algorithm)
