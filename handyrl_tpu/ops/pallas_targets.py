"""Pallas TPU kernels for the backward target recursions.

The TD(lambda)/UPGO/V-Trace recursions are T sequential elementwise steps
over tiny (B, P, 1) slices — as ``lax.scan`` they compile to a T-iteration
loop of small fused bodies. Here the whole backward pass is ONE Pallas
kernel: data is laid out time-major as (T, N) with N = B*P padded to the
128-lane tile, the T loop is unrolled inside the kernel (T is static), and
every step is a VPU elementwise op on a full lane vector. One kernel launch,
zero intermediate HBM traffic.

Gradients never flow through targets (they consume stop_gradient'd values —
losses.py), so no custom VJP is needed; callers get stop_gradient semantics.

Status (measured on a real TPU v5e chip, round 2): the kernels compile,
run, and agree with the scan reference on silicon (tests/test_pallas_targets.py
with HANDYRL_TPU_TESTS=1), but inside the full update step they are SLOWER
than the lax.scan path — 56.9 vs 51.4 ms/step for TD/TD and 110.7 vs 50.0
for UPGO/VTRACE at B=128 T=16 (BENCHMARKS.md). The recursion is elementwise
on tiny (T, B·P) blocks, so XLA fuses the scan into the surrounding program,
while a pallas_call is an opaque custom call that forces its inputs to be
materialized and breaks fusion. The scan path is therefore the default on
every backend; set ``HANDYRL_PALLAS_TARGETS=1`` to opt in (the startup
probe still verifies the kernel against the scan before enabling it).
``interpret=True`` makes the same kernels testable on CPU.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_OK = True
except ImportError:                      # pragma: no cover
    _PALLAS_OK = False

LANES = 128


_PROBE_RESULT = None


def _trace_state_clean() -> bool:
    """True when no jit/vmap/etc. trace is active (safe to run the probe)."""
    try:
        from jax._src.core import trace_state_clean
        return bool(trace_state_clean())
    except Exception:
        # Private API moved: assume clean. Worst case the probe runs
        # mid-trace, fails loudly inside its own try/except, and the gate
        # stays closed — whereas returning False here would silently turn
        # the explicit opt-in into a no-op.
        return True


def _probe_on_device() -> bool:
    """Compile and run one tiny TD(λ) kernel on the live backend and compare
    it against the lax.scan reference. A kernel that fails to compile, or
    compiles but disagrees, disqualifies the whole Pallas path for this
    process — training silently falls back to the scan implementation
    instead of faceplanting (or mis-training) on the hot path."""
    import numpy as np
    from . import targets as scan_ref
    try:
        return _probe_body(np, scan_ref)
    except Exception as exc:   # compile/runtime failure -> scan fallback
        print('pallas targets probe failed (%s: %s); using the scan path'
              % (type(exc).__name__, str(exc)[:120]))
        return False


def _probe_body(np, scan_ref) -> bool:
    rng = np.random.RandomState(0)
    shape = (2, 8, 1, 1)
    values = rng.randn(*shape).astype(np.float32)
    returns = rng.randn(*shape).astype(np.float32)
    rewards = rng.randn(*shape).astype(np.float32)
    lambda_ = (0.7 + 0.3 * (rng.rand(*shape) > 0.5)).astype(np.float32)
    got_t, got_a = td_lambda_pallas(values, returns, rewards,
                                    lambda_, 0.9)
    want_t, want_a = scan_ref.td_lambda(values, returns, rewards,
                                        lambda_, 0.9)
    ok = (np.allclose(np.asarray(got_t), np.asarray(want_t),
                      rtol=1e-4, atol=1e-4)
          and np.allclose(np.asarray(got_a), np.asarray(want_a),
                          rtol=1e-4, atol=1e-4))
    if not ok:
        print('pallas targets probe: kernel DISAGREES with lax.scan '
              'on this backend; using the scan path')
    return ok


def use_pallas_targets() -> bool:
    """True only when explicitly opted in (HANDYRL_PALLAS_TARGETS=1), on a
    TPU backend, where the kernels have actually executed and matched the
    reference recursion in this process (probed once). Off by default: the
    scan path measured faster inside the full update step (module docstring).

    The probe must run OUTSIDE any jit trace (it compiles and executes a
    real kernel); step builders call this eagerly before tracing
    (ops/train_step.py). If the first call nevertheless lands mid-trace,
    we answer False for that trace rather than probing — safe fallback,
    never a crash."""
    global _PROBE_RESULT
    if not _PALLAS_OK:
        return False
    if os.environ.get('HANDYRL_PALLAS_TARGETS') != '1':
        return False
    if _PROBE_RESULT is None and not _trace_state_clean():
        return False
    try:
        if jax.default_backend() not in ('tpu', 'axon'):
            return False
    except Exception:
        return False
    if _PROBE_RESULT is None:
        _PROBE_RESULT = _probe_on_device()
    return _PROBE_RESULT


# ---- kernels (refs are (T, N) or (1, N) VMEM blocks) ---------------------

def _td_kernel(v_ref, g_ref, rew_ref, lam_ref, out_ref, *, T, gamma):
    carry = g_ref[0, :]
    out_ref[T - 1, :] = carry
    for t in range(T - 2, -1, -1):
        lam = lam_ref[t + 1, :]
        carry = rew_ref[t, :] + gamma * ((1 - lam) * v_ref[t + 1, :] + lam * carry)
        out_ref[t, :] = carry


def _upgo_kernel(v_ref, g_ref, rew_ref, lam_ref, out_ref, *, T, gamma):
    carry = g_ref[0, :]
    out_ref[T - 1, :] = carry
    for t in range(T - 2, -1, -1):
        v_next = v_ref[t + 1, :]
        lam = lam_ref[t + 1, :]
        mixed = (1 - lam) * v_next + lam * carry
        carry = rew_ref[t, :] + gamma * jnp.maximum(v_next, mixed)
        out_ref[t, :] = carry


def _vtrace_kernel(delta_ref, lamc_ref, out_ref, *, T, gamma):
    """vmv_t = delta_t + gamma * (lam_{t+1} c_t) * vmv_{t+1}; lamc_ref holds
    the pre-multiplied factor aligned at index t."""
    carry = delta_ref[T - 1, :]
    out_ref[T - 1, :] = carry
    for t in range(T - 2, -1, -1):
        carry = delta_ref[t, :] + gamma * lamc_ref[t, :] * carry
        out_ref[t, :] = carry


# ---- host-side wrappers --------------------------------------------------

def _to_tn(x: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
    """(B, T, P, 1) -> time-major (T, N_padded); returns (array, N)."""
    B, T = x.shape[0], x.shape[1]
    flat = jnp.moveaxis(x, 1, 0).reshape(T, -1)
    N = flat.shape[1]
    pad = (-N) % LANES
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    return flat, N


def _from_tn(tn: jnp.ndarray, shape) -> jnp.ndarray:
    B, T, P = shape[0], shape[1], shape[2]
    return jnp.moveaxis(tn[:, :B * P].reshape(T, B, P, 1), 0, 1)


def _call(kernel, out_T, args, *, T, gamma, interpret):
    specs = [pl.BlockSpec(memory_space=pltpu.VMEM) for _ in args]
    return pl.pallas_call(
        functools.partial(kernel, T=T, gamma=gamma),
        out_shape=jax.ShapeDtypeStruct((out_T, args[0].shape[1]), jnp.float32),
        in_specs=specs,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(*args)


def td_lambda_pallas(values, returns, rewards, lambda_, gamma,
                     interpret: bool = False):
    shape = values.shape
    T = shape[1]
    v, _ = _to_tn(values)
    lam, _ = _to_tn(lambda_)
    rew, _ = _to_tn(rewards if rewards is not None else jnp.zeros_like(values))
    g = _to_tn(returns[:, -1:])[0]
    tvs = _call(_td_kernel, T, (v, g, rew, lam), T=T, gamma=gamma,
                interpret=interpret)
    tvs = _from_tn(tvs, shape)
    return tvs, tvs - values


def upgo_pallas(values, returns, rewards, lambda_, gamma,
                interpret: bool = False):
    shape = values.shape
    T = shape[1]
    v, _ = _to_tn(values)
    lam, _ = _to_tn(lambda_)
    rew, _ = _to_tn(rewards if rewards is not None else jnp.zeros_like(values))
    g = _to_tn(returns[:, -1:])[0]
    tvs = _call(_upgo_kernel, T, (v, g, rew, lam), T=T, gamma=gamma,
                interpret=interpret)
    tvs = _from_tn(tvs, shape)
    return tvs, tvs - values


def vtrace_pallas(values, returns, rewards, lambda_, gamma, rhos, cs,
                  interpret: bool = False):
    shape = values.shape
    T = shape[1]
    rew = rewards if rewards is not None else jnp.zeros_like(values)
    v_next = jnp.concatenate([values[:, 1:], returns[:, -1:]], axis=1)
    deltas = rhos * (rew + gamma * v_next - values)
    # lamc aligned at t: lambda_{t+1} * c_t (last row unused)
    lamc = jnp.concatenate([lambda_[:, 1:] * cs[:, :-1],
                            jnp.zeros_like(cs[:, -1:])], axis=1)
    d_tn, _ = _to_tn(deltas)
    lamc_tn, _ = _to_tn(lamc)
    vmv = _call(_vtrace_kernel, T, (d_tn, lamc_tn), T=T, gamma=gamma,
                interpret=interpret)
    vmv = _from_tn(vmv, shape)
    vs = vmv + values
    vs_next = jnp.concatenate([vs[:, 1:], returns[:, -1:]], axis=1)
    advantages = rew + gamma * vs_next - values
    return vs, advantages
