"""On-device window assembly: rollout records -> replay ring, zero host copies.

The host splice path (device_generation.step_chunk -> moment dicts -> bz2 ->
ingest decompress -> build_window -> ring push) rebuilds every episode in
Python: ~chunk_steps x n_envs dict constructions per dispatch. On a single
host core that, not the accelerator, bounds the fully-device pipeline.

This module closes the loop in HBM. A per-env episode history lives on
device as fixed (N, L, ...) buffers; one jitted program consumes a rollout
chunk ply by ply (lax.scan), and wherever an episode terminates it

  * draws ``clip(steps // forward_steps, 1, W)`` random training windows
    (the host ingestion rate, train.py _ingest_new_episodes),
  * materializes them with the EXACT pad/mask semantics of
    ops/batch.py build_window (reference train.py:33-124): prob pad 1,
    action_mask pad +1e32, value tail = final outcome, progress pad 1,
    episode/turn/observation masks,
  * and scatters them into the DeviceReplay ring with prefix-sum slot
    compaction (invalid lanes dropped via out-of-range scatter indices).

The host sees only (episodes_done, outcome) scalars per chunk. Two layouts
are supported, mirroring build_window's two player-axis regimes:

  * 'solo' (simultaneous env, turn_based_training=False): one random seat
    per window; every window leaf has P axis 1 (reference train.py:57-58).
  * 'turn' (turn-based, observation=False): obs/prob/action/action_mask
    carry the turn player (P axis 1) while value/reward/return/outcome and
    the masks span all players (reference train.py:65-68).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _take(hist_leaf, idxm):
    """hist_leaf (L, ...) gathered at idxm (T,) -> (T, ...)."""
    return hist_leaf[idxm]


def flatten_window_keys(win: Dict[str, Any]) -> Dict[str, Any]:
    """Window dicts may carry a PYTREE observation (e.g. geister's
    {'scalar', 'board'}); the ring stores flat 2-D rows per leaf, so
    nested dict levels become dotted keys ('observation.board'), recursing
    to arbitrary depth. Keys must not contain '.' (asserted — a dotted
    env observation key would collide with the path encoding) and every
    flattened value must be an array-like, so a deeper-than-expected
    pytree fails HERE with a clear message, not later inside the ring."""
    out = {}

    def walk(prefix, v):
        if isinstance(v, dict):
            for sk, sv in v.items():
                assert '.' not in str(sk), (
                    'observation key %r contains "." which is reserved for '
                    'the ring\'s flattened-path encoding' % (sk,))
                walk('%s.%s' % (prefix, sk) if prefix else str(sk), sv)
        else:
            assert hasattr(v, 'shape'), (
                'window leaf %r is %r, not an array — unsupported pytree '
                'node in the observation?' % (prefix, type(v)))
            out[prefix] = v

    for k, v in win.items():
        walk(str(k), v)
    return out


def unflatten_window_keys(win: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of flatten_window_keys — rebuilds the batch pytree the
    loss consumes (batch['observation'] nested again, any depth)."""
    out: Dict[str, Any] = {}
    for k, v in win.items():
        parts = k.split('.')
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def build_windows_solo(hist: Dict[str, Any], S, ts, seat, outcome,
                       fs: int, bi: int, L: int):
    """Windows for ONE env in solo layout.

    hist leaves are (L, P, ...); S scalar episode length; ts (W,) train
    starts; seat (W,) evaluated seats; outcome (P,). Returns a window dict
    with leading axis W.
    """
    T = bi + fs

    def one(ts_w, seat_w):
        m = ts_w - bi + jnp.arange(T)                    # (T,)
        in_ep = (m >= 0) & (m < S)
        idxm = jnp.clip(m, 0, L - 1)
        acting = _take(hist['acting'], idxm)[:, seat_w]  # (T,)
        valid = in_ep & acting
        tail = (m >= S)

        def vmask(x, fill, cond):
            c = cond.reshape((-1,) + (1,) * (x.ndim - 1))
            return jnp.where(c, x, fill)

        obs = jax.tree_util.tree_map(          # obs may be a pytree
            lambda x: vmask(_take(x, idxm)[:, seat_w][:, None], 0.0, valid),
            hist['obs'])                                            # (T,1,...)
        prob = jnp.where(valid, _take(hist['prob'], idxm)[:, seat_w], 1.0)
        act = jnp.where(valid, _take(hist['action'], idxm)[:, seat_w], 0)
        amask = vmask(_take(hist['amask'], idxm)[:, seat_w][:, None],
                      1e32, valid)
        val = _take(hist['value'], idxm)[:, seat_w, 0]
        val = jnp.where(valid, val,
                        jnp.where(tail, outcome[seat_w], 0.0))
        if 'reward' in hist:
            rew = jnp.where(in_ep, _take(hist['reward'], idxm)[:, seat_w], 0.0)
            ret = jnp.where(in_ep, _take(hist['return'], idxm)[:, seat_w], 0.0)
        else:
            rew = jnp.zeros((T,), jnp.float32)
            ret = jnp.zeros((T,), jnp.float32)
        progress = jnp.where(in_ep, m.astype(jnp.float32) / S, 1.0)
        f32 = jnp.float32
        return {
            'observation': obs,
            'selected_prob': prob.astype(f32)[:, None, None],
            'action': act.astype(jnp.int32)[:, None, None],
            'action_mask': amask.astype(f32),
            'value': val.astype(f32)[:, None, None],
            'reward': rew.astype(f32)[:, None, None],
            'return': ret.astype(f32)[:, None, None],
            'outcome': outcome[seat_w].astype(f32).reshape(1, 1, 1),
            'episode_mask': in_ep.astype(f32)[:, None, None],
            'turn_mask': valid.astype(f32)[:, None, None],
            'observation_mask': valid.astype(f32)[:, None, None],
            'progress': progress.astype(f32)[:, None],
        }

    return jax.vmap(lambda t, s: flatten_window_keys(one(t, s)))(ts, seat)


def build_windows_turn(hist: Dict[str, Any], S, ts, outcome,
                       fs: int, bi: int, L: int, num_players: int):
    """Windows for ONE env in turn-based (observation=False) layout.

    hist leaves are (L, ...) with the turn player's data per ply plus
    hist['player'] (L,); outcome (P,). Returns a window dict with leading
    axis W; mask/value leaves span all P players, data leaves P axis 1.
    """
    T = bi + fs
    P = num_players

    def one(ts_w):
        m = ts_w - bi + jnp.arange(T)
        in_ep = (m >= 0) & (m < S)
        idxm = jnp.clip(m, 0, L - 1)
        player = _take(hist['player'], idxm)             # (T,)
        tail = (m >= S)

        def vmask(x, fill, cond):
            c = cond.reshape((-1,) + (1,) * (x.ndim - 1))
            return jnp.where(c, x, fill)

        obs = jax.tree_util.tree_map(          # obs may be a pytree
            lambda x: vmask(_take(x, idxm)[:, None], 0.0, in_ep),
            hist['obs'])
        prob = jnp.where(in_ep, _take(hist['prob'], idxm), 1.0)
        act = jnp.where(in_ep, _take(hist['action'], idxm), 0)
        amask = vmask(_take(hist['amask'], idxm)[:, None], 1e32, in_ep)
        # (T, P) per-player masks: the turn player acted and observed
        is_turn = (player[:, None] == jnp.arange(P)[None, :]) \
            & in_ep[:, None]
        val_turn = _take(hist['value'], idxm)[:, 0]       # (T,)
        val = jnp.where(is_turn, val_turn[:, None],
                        jnp.where(tail[:, None], outcome[None, :], 0.0))
        if 'reward' in hist:
            rew = jnp.where(in_ep[:, None],
                            _take(hist['reward'], idxm), 0.0)   # (T, P)
            ret = jnp.where(in_ep[:, None],
                            _take(hist['return'], idxm), 0.0)
        else:
            rew = jnp.zeros((T, P), jnp.float32)
            ret = jnp.zeros((T, P), jnp.float32)
        progress = jnp.where(in_ep, m.astype(jnp.float32) / S, 1.0)
        f32 = jnp.float32
        return {
            'observation': obs,
            'selected_prob': prob.astype(f32)[:, None, None],
            'action': act.astype(jnp.int32)[:, None, None],
            'action_mask': amask.astype(f32),
            'value': val.astype(f32)[:, :, None],
            'reward': rew.astype(f32)[:, :, None],
            'return': ret.astype(f32)[:, :, None],
            'outcome': outcome.astype(f32).reshape(1, P, 1),
            'episode_mask': in_ep.astype(f32)[:, None, None],
            'turn_mask': is_turn.astype(f32)[:, :, None],
            'observation_mask': is_turn.astype(f32)[:, :, None],
            'progress': progress.astype(f32)[:, None],
        }

    return jax.vmap(lambda t: flatten_window_keys(one(t)))(ts)


def _discounted_returns(rewards, valid, gamma: float):
    """Backward discounted returns over the (L, P) reward history.

    ret[m] = r[m] + gamma * ret[m+1] within the valid prefix; zeros outside.
    """
    def body(carry, xs):
        r, v = xs
        nxt = r + gamma * carry
        nxt = jnp.where(v.reshape((-1,) + (1,) * (r.ndim - 1)), nxt, 0.0)
        return nxt, nxt

    rev = lambda x: jnp.flip(x, axis=0)
    _, rets = jax.lax.scan(body, jnp.zeros_like(rewards[0]),
                           (rev(rewards), rev(valid)))
    return rev(rets)


class DeviceWindower:
    """Owns the per-env episode history and the chunk-ingest program.

    ``ingest(records, state, ring, cursor, size, rng)`` consumes one rollout
    chunk and returns updated (state, ring, cursor, size, rng, n_done).
    The ring/state/cursor/size live as device arrays owned by the caller
    (single-owner: the trainer thread), so buffers are donated in place.
    """

    def __init__(self, mode: str, fs: int, bi: int, max_steps: int,
                 windows_cap: int, capacity: int, num_players: int,
                 gamma: float, has_reward: bool):
        assert mode in ('solo', 'turn')
        self.mode = mode
        self.fs, self.bi = fs, bi
        self.L = max_steps
        self.W = max(1, windows_cap)
        self.capacity = capacity
        self.P = num_players
        self.gamma = gamma
        self.has_reward = has_reward
        self.window_spec: Optional[Dict[str, Tuple]] = None  # set by init_ring
        self._ingest = None   # jitted lazily once ring shapes exist

    # -- state/ring allocation --------------------------------------------
    def init_state(self, records) -> Dict[str, Any]:
        """Zero history buffers shaped after one rollout chunk's records."""
        hist = {}
        for key in self._hist_keys():
            # records leaf (K, N, ...) -> hist (N, L, ...); 'obs' may be a
            # pytree (dict observations), so map over leaves
            hist[key] = jax.tree_util.tree_map(
                lambda leaf: jnp.zeros(
                    (leaf.shape[1], self.L) + leaf.shape[2:], leaf.dtype),
                records[key])
        return {'hist': hist,
                'counts': jnp.zeros((records['done'].shape[1],), jnp.int32)}

    def _hist_keys(self):
        keys = ['obs', 'action', 'prob', 'amask', 'value']
        keys.append('acting' if self.mode == 'solo' else 'player')
        if self.has_reward:
            keys.append('reward')
        return keys

    def init_ring(self, records) -> Dict[str, Any]:
        """Zero ring buffers, shaped via eval_shape — NOTHING runs on
        device here. (Running the window builder eagerly op-by-op cost ~26 s
        through the TPU tunnel: every un-jitted op is its own compile +
        dispatch.)

        Ring storage is FLATTENED per window: leaf (capacity, prod(shape)).
        TPU tiled layouts pad the two minormost dims to (8, 128); storing
        windows in natural (T, P, ...) shape put tiny trailing dims (e.g.
        Hungry Geese's 7x11 board) in the tile, inflating a 4 GB ring to a
        31 GB allocation. 2-D storage pads ~1%; consumers reshape after
        gather via ``window_spec``."""
        def spec_of(key):
            return jax.tree_util.tree_map(
                lambda leaf: jax.ShapeDtypeStruct(
                    (self.L,) + tuple(leaf.shape[2:]), leaf.dtype),
                records[key])

        hist1 = {k: spec_of(k) for k in self._hist_keys()}
        if self.has_reward:
            hist1['return'] = hist1['reward']
        outcome1 = jax.ShapeDtypeStruct((self.P,), jnp.float32)
        ts = jax.ShapeDtypeStruct((1,), jnp.int32)
        s_one = jax.ShapeDtypeStruct((), jnp.int32)
        if self.mode == 'solo':
            win = jax.eval_shape(
                lambda h, s, t, seat, oc: build_windows_solo(
                    h, s, t, seat, oc, self.fs, self.bi, self.L),
                hist1, s_one, ts, ts, outcome1)
        else:
            win = jax.eval_shape(
                lambda h, s, t, oc: build_windows_turn(
                    h, s, t, oc, self.fs, self.bi, self.L, self.P),
                hist1, s_one, ts, outcome1)
        self.window_spec = {k: (tuple(w.shape[1:]), w.dtype)
                            for k, w in win.items()}
        return {k: jnp.zeros(
                    (self.capacity, int(np.prod(shape)) if shape else 1),
                    dtype)
                for k, (shape, dtype) in self.window_spec.items()}

    def unflatten_rows(self, rows: Dict[str, Any]) -> Dict[str, Any]:
        """(n, flat) ring rows -> batch pytree: (n,) + window shape per
        leaf, dotted keys rebuilt into the nested observation."""
        return unflatten_window_keys(
            {k: v.reshape((v.shape[0],) + self.window_spec[k][0])
             for k, v in rows.items()})

    # -- the ingest program ------------------------------------------------
    def ingest(self, records, state, ring, cursor, size, rng):
        if self._ingest is None:
            # donate history/ring/cursor/size/rng: the trainer thread is the
            # single owner and always rebinds them from the outputs
            self._ingest = jax.jit(self.ingest_fn(),
                                   donate_argnums=(1, 2, 3, 4, 5))
        return self._ingest(records, state, ring, cursor, size, rng)

    def ingest_fn(self):
        """The pure (un-jitted) chunk-ingest function — used by the jitted
        standalone path above and inlined into the fused
        generate+ingest+train program (ops/fused_pipeline.py)."""
        return self._build_ingest()

    def _build_ingest(self):
        fs, bi, L, W, cap = self.fs, self.bi, self.L, self.W, self.capacity
        P, gamma, mode = self.P, self.gamma, self.mode
        has_reward = self.has_reward
        hist_record_keys = [k for k in self._hist_keys() if k != 'return']

        def ply(carry, rec):
            hist, counts, ring, cursor, size, rng = carry
            hist = dict(hist)   # never mutate the traced carry structure
            N = counts.shape[0]
            rows = jnp.arange(N)
            idx = jnp.clip(counts, 0, L - 1)

            for key in hist_record_keys:
                hist[key] = jax.tree_util.tree_map(
                    lambda h, r: h.at[rows, idx].set(r),
                    hist[key], rec[key])
            counts = counts + 1
            done = rec['done']                       # (N,) bool
            S = counts                               # (N,) episode lengths
            rng, k_ts, k_seat = jax.random.split(rng, 3)
            outcome = rec['outcome']                 # (N, P)

            def finalize(_):
                """Returns recompute + window build + ring scatter — only
                reached on plies where some episode actually ended (most
                plies skip all of this via the cond below)."""
                win_hist = dict(hist)
                if has_reward:
                    valid = (jnp.arange(L)[None, :] < S[:, None])  # (N, L)
                    win_hist['return'] = jax.vmap(
                        _discounted_returns, in_axes=(0, 0, None))(
                            hist['reward'], valid, gamma)

                # windows per finished episode: the host ingestion rate
                wcount = jnp.clip(S // fs, 1, W)     # (N,)
                span = jnp.maximum(S - fs, 0) + 1    # train_start in [0, span)
                u = jax.random.uniform(k_ts, (N, W))
                ts = jnp.minimum((u * span[:, None]).astype(jnp.int32),
                                 span[:, None] - 1)

                if mode == 'solo':
                    seat = jax.random.randint(k_seat, (N, W), 0, P)
                    windows = jax.vmap(
                        build_windows_solo,
                        in_axes=(0, 0, 0, 0, 0, None, None, None))(
                            win_hist, S, ts, seat, outcome, fs, bi, L)
                else:
                    windows = jax.vmap(
                        build_windows_turn,
                        in_axes=(0, 0, 0, 0, None, None, None, None))(
                            win_hist, S, ts, outcome, fs, bi, L, P)

                # ring slots with prefix-sum compaction over done envs
                dcount = jnp.where(done, wcount, 0)  # (N,)
                base = cursor + jnp.cumsum(dcount) - dcount
                w_ix = jnp.arange(W)[None, :]
                slot = (base[:, None] + w_ix) % cap
                valid_w = done[:, None] & (w_ix < wcount[:, None])
                slot = jnp.where(valid_w, slot, cap)  # cap = dropped
                flat_slot = slot.reshape(-1)

                def scatter(rb, wb):
                    # ring rows are flat (see init_ring): (N, W, ...) ->
                    # (N*W, prod(window shape))
                    return rb.at[flat_slot].set(
                        wb.reshape((wb.shape[0] * wb.shape[1], -1)),
                        mode='drop')

                return (jax.tree_util.tree_map(scatter, ring, windows),
                        jnp.sum(dcount))

            ring, n_new = jax.lax.cond(
                jnp.any(done), finalize,
                lambda _: (ring, jnp.int32(0)), None)
            cursor = (cursor + n_new) % cap
            size = jnp.minimum(size + n_new, cap)
            counts = jnp.where(done, 0, counts)
            return ((hist, counts, ring, cursor, size, rng),
                    (jnp.sum(done), n_new))

        def ingest(records, state, ring, cursor, size, rng):
            rec_scan = {k: records[k] for k in hist_record_keys}
            rec_scan['done'] = records['done']
            rec_scan['outcome'] = records['outcome']
            ((hist, counts, ring, cursor, size, rng),
             (dones, wins)) = jax.lax.scan(
                ply, (state['hist'], state['counts'], ring, cursor, size,
                      rng), rec_scan)
            return ({'hist': hist, 'counts': counts}, ring, cursor, size,
                    rng, jnp.sum(dones), jnp.sum(wins))

        return ingest
