"""HBM-resident replay buffer: training windows stored and sampled on device.

The host-side pipeline (episode deque -> select_episode -> make_batch)
decompresses and re-pads windows on every SGD step. For device-generation
runs this buffer removes that host work from the steady state: fixed-shape
training windows are pushed to device once, live in HBM as a ring, and batch
assembly is a gather by random indices inside jit — the sampled batch never
touches the host.

Recency bias matches the reference sampler (train.py:291-297): index i of n
buffered windows is drawn with probability proportional to (i+1) (newest
most likely), implemented as a closed-form inverse-CDF on device.

Windows are dicts of arrays shaped (T, P, ...) exactly as ops/batch.py
builds them; ``sample`` returns the same (B, T, P, ...) batch dict the
update step consumes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry

_WINDOWS = telemetry.counter('replay_windows_ingested_total')
_SAMPLES = telemetry.counter('replay_samples_drawn_total')
_SIZE = telemetry.gauge('replay_ring_size')
_OCC = telemetry.gauge('replay_ring_occupancy')


def recency_slots(key, size, cursor, capacity: int, batch_size: int):
    """Draw ``batch_size`` ring slots with the reference's recency bias.

    P(i) ~ (i+1) for buffer index i in [0, size), newest most likely
    (reference train.py:291-297), via the closed-form inverse CDF of the
    triangular weighting: i = floor(sqrt(u) * size). Traceable — used both
    by DeviceReplay.sample and inside the fused multi-step trainer's scan
    (ops/train_step.py), so the replay distribution has exactly one
    definition.
    """
    u = jax.random.uniform(key, (batch_size,))
    # clamp to 0 so size==0 yields slot 0 instead of wrapping to capacity-1
    # and silently sampling uninitialized windows; callers must still gate
    # training on size > 0 (the drawn window is all-zeros either way)
    idx = jnp.clip((jnp.sqrt(u) * size).astype(jnp.int32), 0, jnp.maximum(size - 1, 0))
    # ring order: oldest window sits at cursor when full
    start = jnp.where(size >= capacity, cursor, 0)
    return (start + idx) % capacity


class DeviceReplay:
    """Fixed-capacity ring of training windows in device memory.

    With a ``mesh``, the ring lives replicated across the mesh devices so
    the fused multi-step trainer (ops/train_step.py build_replay_update)
    can gather batches from a local replica with no per-dispatch resharding;
    each device then computes its 'data' shard of the batch.
    """

    def __init__(self, capacity: int, mesh=None):
        self.capacity = capacity
        # storage is a LIST of 2-D (capacity, prod(window shape)) buffers —
        # TPU tiled layouts pad the two minormost dims to (8, 128), so
        # natural (T, P, ...) storage with tiny trailing dims inflates HBM
        # by an order of magnitude; window_spec + treedef restore the
        # original pytree after sampling
        self.buffers: List[Any] = []
        self.window_spec: List[tuple] = []   # per-leaf (shape, dtype)
        self.treedef = None
        self.cursor = 0
        self.size = 0
        self.mesh = mesh
        self._repl = None
        if mesh is not None:
            from ..parallel.mesh import replicated_sharding
            self._repl = replicated_sharding(mesh)

        def _write(buffers, leaves, cursor):
            n = leaves[0].shape[0]
            idx = (cursor + jnp.arange(n)) % self.capacity
            return [buf.at[idx].set(leaf.reshape(leaf.shape[0], -1))
                    for buf, leaf in zip(buffers, leaves)]

        if mesh is None:
            _write = jax.jit(_write)
        else:
            _write = jax.jit(_write, out_shardings=self._repl)

        @partial(jax.jit, static_argnames=('batch_size',))
        def _sample(buffers, key, size, cursor, batch_size):
            slots = recency_slots(key, size, cursor, capacity, batch_size)
            rows = [b[slots].reshape((batch_size,) + shape)
                    for b, (shape, _) in zip(buffers, self.window_spec)]
            return jax.tree_util.tree_unflatten(self.treedef, rows)

        self._write_fn = _write
        self._sample_fn = _sample

    def push(self, windows: Dict[str, Any]):
        """Append a stack of windows (leading axis = window count)."""
        leaves, treedef = jax.tree_util.tree_flatten(windows)
        n = leaves[0].shape[0]
        if not self.buffers:
            self.treedef = treedef
            self.window_spec = [(tuple(l.shape[1:]), l.dtype)
                                for l in leaves]
            self.buffers = [
                jnp.zeros((self.capacity,
                           max(1, int(np.prod(l.shape[1:])))), l.dtype)
                for l in leaves]
        self.buffers = self._write_fn(self.buffers, leaves,
                                      jnp.asarray(self.cursor, jnp.int32))
        self.cursor = (self.cursor + n) % self.capacity
        self.size = min(self.size + n, self.capacity)
        _WINDOWS.inc(n)
        _SIZE.set(self.size)
        _OCC.set(self.size / self.capacity)

    def sample(self, key, batch_size: int) -> Dict[str, Any]:
        assert self.size > 0, 'sampling from an empty replay buffer'
        _SAMPLES.inc(batch_size)
        return self._sample_fn(self.buffers, key,
                               jnp.asarray(self.size, jnp.int32),
                               jnp.asarray(self.cursor, jnp.int32),
                               batch_size)
